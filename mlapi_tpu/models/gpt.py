"""Decoder-only (GPT-style) causal LM — the generative model family.

The reference serves only classifiers (``main.py:16-27``); this goes
past parity: same TPU-first recipe as the BERT encoder (one flat param
pytree, explicit einsum attention, bf16 hidden compute / f32 softmax
+ layernorm stats, Megatron TP layout over the ``model`` mesh axis)
plus what decoding actually needs on a TPU:

- **Causal attention** through the shared ops (`full_attention` /
  Pallas ``flash_attention`` / sequence-parallel ``ring_attention``
  all take ``causal=True``).
- **KV-cache decode under ``lax.scan``**: generation is one compiled
  XLA while-program — fixed-shape cache ``[B, max_len, H, D]`` per
  layer, one token per step, no per-token Python dispatch.

Pre-norm blocks (GPT-2 style: ln -> attn -> residual, ln -> mlp ->
residual, final ln), learned positions, weight-tied LM head.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mlapi_tpu.models import register_model

_LN_EPS = 1e-5


def _layer_norm(x, scale, bias):
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + _LN_EPS) * scale + bias


@register_model("gpt_lm")
@dataclass(frozen=True)
class GptLM:
    """Decoder-only causal language model with weight-tied head."""

    input_kind = "text"

    vocab_size: int = 512
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    max_positions: int = 256
    compute_dtype: str = "bfloat16"
    # "full" | "flash" (Pallas kernel) | "ring" (sequence-parallel
    # over mesh's seq axis; requires ``mesh``) — all causal. Ring
    # applies to ``apply`` (training/scoring, where the whole sequence
    # is live); ``generate`` decodes one token at a time against the
    # KV cache, where there is no sequence dimension to shard.
    attention_impl: str = "full"
    mesh: object = None  # jax.sharding.Mesh for attention_impl="ring"
    seq_axis: str = "seq"
    # Ring options: per-block attention ("einsum" | "flash") and the
    # zigzag stripe layout (flash-only; balances causal work to two
    # half-block units per ring step on every device — ~2x wall time).
    ring_block_impl: str = "einsum"
    ring_zigzag: bool = False
    # KV-cache storage format: "none" keeps the compute dtype;
    # "int8" stores symmetric per-token-per-head int8 payload + f32
    # scales (ops/quant.py) — ~2x less decode HBM per cached token,
    # ~2x the serving cache budget per chip. A dataclass field (not a
    # method argument) so every lru_cache'd program factory
    # (prefill_fn, decode_chunk_fn, generate_tier_fn, ...) keys on the
    # cache format for free.
    kv_quant: str = "none"
    # Cache-read attention: "einsum" (the reference oracle — one
    # [B,U,H,D] x [B,L,H,D] einsum over the dequantized cache) or
    # "flash" (the Pallas split-K kernels,
    # ops/pallas/decode_attention.py, which read int8 cache tiles
    # in-kernel — the 2x HBM saving reaches the READ, not just
    # storage). A MODEL field like kv_quant, so every cached program
    # factory keys on the impl for free. "flash" covers BOTH span
    # widths: single-token decode steps take the flash-decode kernel
    # and multi-token blocks (extend_core — chunked prefill,
    # admission, speculative verify) its U-token flash-extend twin.
    decode_attn_impl: str = "einsum"

    def __post_init__(self):
        from mlapi_tpu.ops.quant import KV_FORMATS

        if self.attention_impl not in ("full", "flash", "ring"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.attention_impl == "ring" and self.mesh is None:
            raise ValueError('attention_impl="ring" requires a mesh')
        if self.ring_zigzag and self.ring_block_impl != "flash":
            raise ValueError('ring_zigzag needs ring_block_impl="flash"')
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide evenly into heads")
        if self.kv_quant not in KV_FORMATS:
            raise ValueError(
                f"unknown kv_quant {self.kv_quant!r}; one of {KV_FORMATS}"
            )
        if self.decode_attn_impl not in ("einsum", "flash"):
            raise ValueError(
                f"unknown decode_attn_impl {self.decode_attn_impl!r}; "
                'one of ("einsum", "flash")'
            )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> dict:
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        keys = iter(jax.random.split(rng, 2 + 6 * self.num_layers))

        def dense(k, shape, scale=0.02):
            return {
                "kernel": scale * jax.random.normal(k, shape),
                "bias": jnp.zeros((shape[-1],)),
            }

        params = {
            "wte": 0.02 * jax.random.normal(next(keys), (v, h)),
            "wpe": 0.01 * jax.random.normal(next(keys), (self.max_positions, h)),
            "ln_f_scale": jnp.ones((h,)),
            "ln_f_bias": jnp.zeros((h,)),
        }
        for n in range(self.num_layers):
            params[f"layer_{n}"] = {
                "qkv": dense(next(keys), (h, 3 * h)),
                "attn_out": dense(next(keys), (h, h)),
                "ln1_scale": jnp.ones((h,)),
                "ln1_bias": jnp.zeros((h,)),
                "ffn_up": dense(next(keys), (h, i)),
                "ffn_down": dense(next(keys), (i, h)),
                "ln2_scale": jnp.ones((h,)),
                "ln2_bias": jnp.zeros((h,)),
            }
        return jax.tree.map(lambda a: a.astype(jnp.float32), params)

    # ------------------------------------------------------------------
    def _block(self, layer, x, attend):
        """One pre-norm transformer block; ``attend(q, k, v)`` supplies
        the attention so the full-sequence and cached-decode paths
        share every other op."""
        cdt = jnp.dtype(self.compute_dtype)
        b, l, h = x.shape
        nh, hd = self.num_heads, self.head_dim

        # lora_apply: the per-tenant serving delta (adapter slot pool,
        # serving/adapter_store.py) — a static no-op returning its
        # ``y`` argument unchanged unless the dispatch augmented this
        # layer dict with a "lora" sub-dict.
        from mlapi_tpu.models.lora import lora_apply

        xn = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"]).astype(cdt)
        qkv = xn @ layer["qkv"]["kernel"].astype(cdt) + layer["qkv"][
            "bias"
        ].astype(cdt)
        qkv = lora_apply(layer, "qkv", xn, qkv)
        q, k, v = jnp.split(qkv.reshape(b, l, 3 * nh, hd), 3, axis=2)
        ctx = attend(q, k, v).reshape(b, l, -1)
        attn = ctx @ layer["attn_out"]["kernel"].astype(cdt) + layer[
            "attn_out"
        ]["bias"].astype(cdt)
        attn = lora_apply(layer, "attn_out", ctx, attn)
        x = x + attn.astype(jnp.float32)

        xn = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"]).astype(cdt)
        up = xn @ layer["ffn_up"]["kernel"].astype(cdt) + layer["ffn_up"][
            "bias"
        ].astype(cdt)
        up = lora_apply(layer, "ffn_up", xn, up)
        up = jax.nn.gelu(up.astype(jnp.float32), approximate=True).astype(cdt)
        down = up @ layer["ffn_down"]["kernel"].astype(cdt) + layer[
            "ffn_down"
        ]["bias"].astype(cdt)
        down = lora_apply(layer, "ffn_down", up, down)
        return x + down.astype(jnp.float32)

    def apply(self, params: dict, token_ids) -> jax.Array:
        """``[B, L]`` ids → ``[B, L, V]`` next-token logits (causal)."""
        from mlapi_tpu.ops import full_attention

        b, l = token_ids.shape
        x = params["wte"][token_ids] + params["wpe"][jnp.arange(l)][None]

        if self.attention_impl == "flash":
            from mlapi_tpu.ops.pallas import flash_attention

            def attend(q, k, v):
                return flash_attention(
                    q, k, v, causal=True,
                    interpret=jax.default_backend() != "tpu",
                )
        elif self.attention_impl == "ring":
            from mlapi_tpu.ops import ring_self_attention

            def attend(q, k, v):
                return ring_self_attention(
                    self.mesh, q, k, v, causal=True,
                    seq_axis=self.seq_axis, head_axis="model",
                    block_impl=self.ring_block_impl,
                    zigzag=self.ring_zigzag,
                )
        else:
            def attend(q, k, v):
                return full_attention(q, k, v, causal=True)

        for n in range(self.num_layers):
            x = self._block(params[f"layer_{n}"], x, attend)
        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        # Weight-tied head; logits in f32 for a stable softmax/loss.
        return x.astype(jnp.float32) @ params["wte"].T.astype(jnp.float32)

    # ------------------------------------------------------------------
    def init_cache(self, batch: int, max_len: int) -> dict:
        """Fixed-shape KV cache: ``[B, max_len, H, D]`` per layer in
        the compute dtype, or the int8 payload+scale layout under
        ``kv_quant="int8"`` (see ``ops/quant.init_kv_cache``)."""
        from mlapi_tpu.ops.quant import init_kv_cache

        cdt = jnp.dtype(self.compute_dtype)
        return {
            f"layer_{n}": init_kv_cache(
                batch, max_len, self.num_heads, self.head_dim, cdt,
                self.kv_quant,
            )
            for n in range(self.num_layers)
        }

    def prefill_core(self, params, prompt_ids, n_pad, total_len: int,
                     cache=None, pos0=None):
        """Full causal forward over a left-padded ``[B, P]`` prompt,
        writing K/V into a fresh ``[B, total_len, H, D]`` cache — this
        model family's implementation of the decoder protocol (see
        :func:`_prefill_core` for the shared contract).

        ``cache``/``pos0`` (page-native prefill): write the prompt's
        K/V into an EXISTING cache pytree at traced slot offset
        ``pos0`` instead of building a fresh one (``total_len`` is
        then ignored). With a paged cache this is what makes prefill
        write pool pages ONCE — the block's attention is unchanged
        (full-precision in-register over ``kv_seen``), only the
        append's destination moves, so token streams are pinned
        identical to the fresh-cache path.
        """
        b, p = prompt_ids.shape
        cache = self.init_cache(b, total_len) if cache is None else dict(cache)
        if pos0 is None:
            pos0 = jnp.int32(0)
        cdt = jnp.dtype(self.compute_dtype)

        from mlapi_tpu.ops import full_attention
        from mlapi_tpu.ops.quant import kv_cache_append

        pos_idx = jnp.maximum(jnp.arange(p)[None, :] - n_pad[:, None], 0)
        x = params["wte"][prompt_ids] + params["wpe"][pos_idx]
        mask = (jnp.arange(p)[None, :] >= n_pad[:, None]).astype(jnp.float32)
        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]
            kv_seen = {}

            def attend(q, k, v, *, _kv=kv_seen):
                _kv["k"], _kv["v"] = k, v
                return full_attention(q, k, v, mask=mask, causal=True)

            x = self._block(layer, x, attend)
            # The prompt block attends full-precision in-register
            # (kv_seen); only the STORED cache is quantized — the
            # append fuses the quantize into this write (ops/quant).
            cache[f"layer_{n}"] = kv_cache_append(
                cache[f"layer_{n}"], kv_seen["k"], kv_seen["v"],
                pos0, cdt,
            )
        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        last_logits = x[:, -1].astype(jnp.float32) @ params["wte"].T.astype(
            jnp.float32
        )
        return cache, last_logits

    def decode_step(self, params, cache, token_ids, pos, n_pad=None,
                    prefix_len=None, prefix_lo=None):
        """One decode step: ``[B, 1]`` ids at position ``pos`` (traced
        scalar) → (``[B, V]`` logits, updated cache). The KV for the
        new token is written into the fixed-shape cache; attention
        reads the full cache with positions ``> pos`` masked out —
        static shapes, so the scan body compiles once.

        ``n_pad`` (``[B]`` int32) is the per-row count of left-pad
        positions in the cache: those keys are masked out and the
        position embedding is shifted so row ``b``'s real tokens sit
        at effective positions ``0..pos-n_pad[b]`` — a prompt's output
        is identical whichever pad bucket it landed in.
        ``prefix_len``/``prefix_lo`` describe a shared prefix-cache
        region ahead of the per-row pads (see
        :func:`decode_valid_and_shift`).
        """
        from mlapi_tpu.ops.quant import kv_cache_seq_len

        cdt = jnp.dtype(self.compute_dtype)
        b = token_ids.shape[0]
        hd = self.head_dim
        max_len = kv_cache_seq_len(cache)
        if n_pad is None:
            n_pad = jnp.zeros((b,), jnp.int32)

        valid, shift = decode_valid_and_shift(
            max_len, pos, n_pad, prefix_len, prefix_lo
        )
        posq = jnp.maximum(pos - shift, 0)
        x = params["wte"][token_ids] + params["wpe"][posq][:, None, :]
        new_cache = {}

        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]

            def attend(q, k_new, v_new, *, _n=n):
                out, new_cache[f"layer_{_n}"] = cached_attend(
                    cache[f"layer_{_n}"], q, k_new, v_new, pos, valid,
                    cdt, hd, impl=self.decode_attn_impl,
                    mesh=self.mesh,
                )
                return out

            x = self._block(layer, x, attend)

        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = x[:, 0].astype(jnp.float32) @ params["wte"].T.astype(
            jnp.float32
        )
        return logits, new_cache

    def extend_core(self, params, cache, token_ids, pos0, n_pad,
                    prefix_len, prefix_lo, all_logits: bool = False):
        """Fused BLOCK forward of ``[B, U]`` tokens at cache slots
        ``[pos0, pos0+U)`` against an existing cache — the multi-token
        generalization of :meth:`decode_step` (one weight pass over
        the whole block instead of U serial steps; this is what makes
        prefix-cache suffix prefill MXU-bound, not bandwidth-bound).
        Queries attend to every earlier valid cache slot plus the
        causal part of their own block, under the same
        prefix-region/pad-hole layout as
        :func:`decode_valid_and_shift`. Returns
        ``(cache, last_logits [B, V])`` — or, with ``all_logits=True``
        (speculative-decoding verification), logits at EVERY block
        position ``[B, U, V]``.

        Under ``decode_attn_impl="flash"`` the block attends through
        the U-token flash-extend kernel (``cached_attend`` routes on
        the query width), so chunked prefill, admission mini-prefills
        and speculative verify read the cache at its stored byte
        format — the einsum read stays the oracle.
        """
        from mlapi_tpu.ops.quant import kv_cache_seq_len

        cdt = jnp.dtype(self.compute_dtype)
        b, u = token_ids.shape
        hd = self.head_dim
        max_len = kv_cache_seq_len(cache)

        posq, mask = extend_positions_and_mask(
            max_len, u, pos0, n_pad, prefix_len, prefix_lo
        )
        x = params["wte"][token_ids] + params["wpe"][posq]
        new_cache = {}

        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]

            def attend(q, k_new, v_new, *, _n=n):
                out, new_cache[f"layer_{_n}"] = cached_attend(
                    cache[f"layer_{_n}"], q, k_new, v_new, pos0, mask,
                    cdt, hd, impl=self.decode_attn_impl,
                    mesh=self.mesh,
                )
                return out

            x = self._block(layer, x, attend)

        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        if not all_logits:
            x = x[:, -1]
        logits = x.astype(jnp.float32) @ params["wte"].T.astype(
            jnp.float32
        )
        return new_cache, logits

    def generate(
        self,
        params,
        prompt_ids,
        *,
        max_new_tokens: int,
        temperature=0.0,
        rng: jax.Array | None = None,
        pad_lens=None,
        top_k=0,
        top_p=1.0,
    ):
        """Greedy (``temperature=0``) or sampled generation.

        ``prompt_ids``: ``[B, P]`` int32. Returns ``[B, max_new_tokens]``.
        Prefill runs the full forward once; decode is a ``lax.scan``
        over single-token steps against the KV cache — one jitted
        program end to end, compiled per (shape, max_new_tokens).

        ``temperature`` may be a float or a per-row ``[B]`` array; it
        is a *traced* argument, so a client cycling temperatures never
        forces recompilation. ``top_k``/``top_p`` (scalar or per-row,
        traced likewise) restrict sampling to the k highest logits /
        the smallest nucleus reaching cumulative probability p —
        ``0``/``1.0`` disable them. ``pad_lens`` (``[B]`` int) marks how many
        left-pad tokens each row carries: pads are masked out of
        attention and position embeddings are shifted, so bucketed
        serving produces bucket-invariant outputs. Sampling uses one
        PRNG stream per row (``fold_in(rng, row)``), making each row's
        tokens independent of its batch position.
        """
        return run_generate(
            self, params, prompt_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, rng=rng, pad_lens=pad_lens,
            top_k=top_k, top_p=top_p,
        )

    # ------------------------------------------------------------------
    def param_shardings(self, layout=None) -> dict:
        """Megatron TP: qkv/ffn-up column-sharded, attn-out/ffn-down
        row-sharded, embeddings vocab-sharded. Axis names come from
        the shared ``SpecLayout`` (mesh renames touch one place)."""
        from mlapi_tpu.parallel import SpecLayout

        lo = layout or SpecLayout()
        col = {"kernel": lo.attn_qkv(), "bias": lo.bias_col()}
        row = {"kernel": lo.attn_out(), "bias": lo.replicated()}
        specs = {
            "wte": lo.embedding_rows(),
            "wpe": lo.replicated(),
            "ln_f_scale": lo.replicated(),
            "ln_f_bias": lo.replicated(),
        }
        for n in range(self.num_layers):
            specs[f"layer_{n}"] = {
                "qkv": dict(col),
                "attn_out": dict(row),
                "ln1_scale": lo.replicated(), "ln1_bias": lo.replicated(),
                "ffn_up": dict(col),
                "ffn_down": dict(row),
                "ln2_scale": lo.replicated(), "ln2_bias": lo.replicated(),
            }
        return specs


_FILTERED = -1e30  # finite stand-in for -inf (f32-safe; prob == 0)


def _filter_top_k_top_p(scaled, top_k, top_p):
    """Per-row nucleus filtering on temperature-scaled logits
    ``[B, V]``: keep the ``top_k[b]`` highest logits (``<= 0`` or
    ``>= V`` disables), then the smallest prefix of the sorted
    distribution whose cumulative probability reaches ``top_p[b]``
    (``<= 0`` or ``>= 1`` disables; the argmax token always
    survives). Both are traced vectors, so no program is keyed on
    them; cost is two per-row sorts — noise next to the decode
    matmuls."""
    v = scaled.shape[-1]

    def _one(lg, k, p):
        s = jnp.sort(lg)[::-1]  # descending — the ONE sort per row
        k_eff = jnp.clip(k, 1, v)
        kth = jax.lax.dynamic_index_in_dim(s, k_eff - 1, keepdims=False)
        apply_k = (k > 0) & (k < v)
        lg = jnp.where(apply_k, jnp.where(lg >= kth, lg, _FILTERED), lg)
        # The k-filtered sorted vector is s with positions >= k_eff
        # masked — no second sort. (Ties at the kth logit: lg keeps
        # all tied tokens while the positional mask counts exactly k
        # toward the nucleus — the same keep-the-ties behavior a
        # re-sort would give, since thr only tightens.)
        s2 = jnp.where(
            apply_k & (jnp.arange(v) >= k_eff), _FILTERED, s
        )
        probs = jax.nn.softmax(s2)
        cum = jnp.cumsum(probs)
        keep = (cum - probs) < p  # prefix mask; index 0 always kept
        thr = jnp.min(jnp.where(keep, s2, jnp.inf))
        apply_p = (p > 0.0) & (p < 1.0)
        return jnp.where(
            apply_p, jnp.where(lg >= thr, lg, _FILTERED), lg
        )

    return jax.vmap(_one)(scaled, top_k, top_p)


def _pick_token(temps, logits, key_data, step, top_k=None, top_p=None):
    """Next token per row: greedy where ``temps[b] <= 0``, else sampled
    from ``logits / temps[b]`` — optionally top-k/top-p (nucleus)
    filtered — with the row's own PRNG stream
    (``fold_in(row_key, step)``): a row's tokens do not depend on
    which batch slot it landed in. ``step`` may be a scalar or a
    per-row ``[B]`` vector — rows admitted into a running batch
    (continuous batching) sample at their OWN token index, so the
    stream matches a solo run exactly."""
    b = logits.shape[0]
    step = jnp.broadcast_to(jnp.asarray(step, jnp.int32), (b,))
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temps > 0.0, temps, 1.0)
    scaled = logits / safe_t[:, None]
    if top_k is None:
        top_k = jnp.zeros((b,), jnp.int32)
    if top_p is None:
        top_p = jnp.ones((b,), jnp.float32)
    v = logits.shape[-1]
    need = jnp.any((top_k > 0) & (top_k < v)) | jnp.any(
        (top_p > 0.0) & (top_p < 1.0)
    )
    # cond, not where: batches with no filtering requested (greedy /
    # plain temperature) skip the per-row sorts at runtime.
    scaled = jax.lax.cond(
        need,
        lambda s: _filter_top_k_top_p(s, top_k, top_p),
        lambda s: s,
        scaled,
    )
    keys = jax.vmap(
        lambda kd, s: jax.random.fold_in(jax.random.wrap_key_data(kd), s)
    )(key_data, step)
    sampled = jax.vmap(
        lambda k, lg: jax.random.categorical(k, lg)
    )(keys, scaled).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def run_generate(
    model,
    params,
    prompt_ids,
    *,
    max_new_tokens: int,
    temperature=0.0,
    rng: jax.Array | None = None,
    pad_lens=None,
    top_k=0,
    top_p=1.0,
):
    """Model-generic generation entry (every decoder family's
    ``generate`` delegates here) — see ``GptLM.generate`` for the full
    argument semantics."""
    b, p = prompt_ids.shape
    if p + max_new_tokens > model.max_positions:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_positions ({model.max_positions})"
        )
    rng = jax.random.key(0) if rng is None else rng
    # The key crosses the jit boundary as raw uint32 data: a typed
    # key array as a jit argument trips a fastpath buffer-count
    # bug in this JAX version once other executables exist on a
    # multi-device host (second identical call INVALID_ARGUMENT).
    row_keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
        jnp.arange(b)
    )
    temps = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    n_pad = (
        jnp.zeros((b,), jnp.int32)
        if pad_lens is None
        else jnp.asarray(pad_lens, jnp.int32)
    )
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    return _generate_fn(model, max_new_tokens)(
        params, prompt_ids, jax.random.key_data(row_keys), temps, n_pad,
        top_k, top_p,
    )


def decode_valid_and_shift(max_len, pos, n_pad, prefix_len=None,
                           prefix_lo=None):
    """Shared decode-time key mask + per-row position shift, for both
    the plain left-padded layout and the prefix-cache layout.

    Cache-slot layout (per row ``b``):
    ``[prefix_lo .. prefix_len)`` real PREFIX tokens (shared across
    the batch, scattered from the prefix KV cache; empty when
    ``prefix_len == 0``), ``[prefix_len .. prefix_len + n_pad[b])``
    this row's suffix pad slots (masked), then real suffix/generated
    tokens. Valid keys: ``idx <= pos`` (written so far), ``idx >=
    prefix_lo`` (prefix's own left-pad), and NOT inside the per-row
    pad hole. With ``prefix_len == prefix_lo == 0`` this reduces
    exactly to the original ``(idx <= pos) & (idx >= n_pad[b])``.

    The position shift maps slot ``s`` to effective position
    ``s - prefix_lo - n_pad[b]`` (prefix real count + suffix index),
    which likewise reduces to ``s - n_pad[b]``.
    Returns ``(valid [B,1,1,L], shift [B])``.

    ``pos`` may be a traced scalar (all rows at the same slot — the
    serving decode loop) or a per-row ``[B]`` vector (rows at
    DESYNCHRONIZED slots — batched speculation, where per-row
    acceptance lengths advance each row's cache independently).
    ``prefix_lo`` likewise: scalar for a batch sharing ONE prefix, or
    per-row ``[B]`` when rows carry DIFFERENT prefixes right-aligned
    to the common region end ``prefix_len`` (cross-batch prefix
    sharing — each row's real prefix occupies ``[lo_b, prefix_len)``;
    ``lo_b == prefix_len`` is an empty region).
    """
    if prefix_len is None:
        prefix_len = jnp.int32(0)
    if prefix_lo is None:
        prefix_lo = jnp.int32(0)
    idx = jnp.arange(max_len)[None, :]
    posk = pos[:, None] if jnp.ndim(pos) else pos
    lok = prefix_lo[:, None] if jnp.ndim(prefix_lo) else prefix_lo
    valid = (
        (idx <= posk)
        & (idx >= lok)
        & ((idx < prefix_len) | (idx >= prefix_len + n_pad[:, None]))
    )[:, None, None, :]
    shift = prefix_lo + n_pad
    return valid, shift


def extend_positions_and_mask(max_len, u, pos0, n_pad, prefix_len=None,
                              prefix_lo=None):
    """Block-extend variant of :func:`decode_valid_and_shift`: for U
    queries at cache slots ``[pos0, pos0+U)``, per-row effective
    positions ``[B, U]`` (clipped at 0 for pad slots) and the
    ``[B, 1, U, L]`` key mask — earlier valid slots plus the causal
    part of the block itself, minus the prefix pad and the per-row
    suffix pad hole. ``pos0``: traced scalar, or per-row ``[B]`` for
    desynchronized rows (batched speculation). ``prefix_lo``: scalar,
    or per-row ``[B]`` for cross-batch prefix sharing (see
    :func:`decode_valid_and_shift`)."""
    if prefix_len is None:
        prefix_len = jnp.int32(0)
    if prefix_lo is None:
        prefix_lo = jnp.int32(0)
    idx = jnp.arange(max_len)
    pos0k = pos0[:, None] if jnp.ndim(pos0) else pos0
    lok = prefix_lo[:, None] if jnp.ndim(prefix_lo) else prefix_lo
    qpos = pos0k + jnp.arange(u)[None, :]          # [B|1, U] slot ids
    shift = prefix_lo + n_pad                          # [B]
    posq = jnp.maximum(qpos - shift[:, None], 0)
    valid_k = (idx[None, :] >= lok) & (
        (idx[None, :] < prefix_len)
        | (idx[None, :] >= prefix_len + n_pad[:, None])
    )                                                  # [B, L]
    causal = idx[None, None, :] <= qpos[:, :, None]  # [B|1, U, L]
    mask = (valid_k[:, None, :] & causal)[:, None, :, :]
    return posq, mask


def cached_attend(
    cache_layer, q, k_new, v_new, pos, valid, cdt, head_dim, expand=None,
    impl: str = "einsum", mesh=None,
):
    """One decode-time attention over a fixed-shape KV cache, shared
    by every decoder family: write the new K/V at ``pos``, attend the
    ``[B, 1]`` query against the whole cache under the ``valid`` mask.
    ``expand`` broadcasts kv-heads to query heads (GQA families pass
    their repeat; MHA passes nothing). Returns ``(ctx, new_layer)``.

    ``pos`` scalar: one fused slice-update writes every row at the
    same slot (the serving layout). ``pos`` per-row ``[B]``: the
    write vmaps over rows so each lands at its own slot — the layout
    batched speculation needs, where per-row acceptance lengths
    desynchronize row positions. Scalar callers compile the exact
    HLO they always did.

    Both cache formats route through here. The write always goes
    through ``ops.quant.kv_cache_append`` (quantize fused into the
    append for int8 layers). The READ depends on ``impl``:

    - ``"einsum"`` (default, the reference oracle): ``kv_cache_kv``
      dequantizes at the read seam and a ``[B,1,H,D] x [B,L,H,D]``
      einsum attends — the full-precision operand materializes
      between the dequant and the einsum, so the int8 format saves
      storage but not read traffic.
    - ``"flash"``: the Pallas split-K kernels
      (``ops/pallas/decode_attention``) read the STORED tiles — int8
      payload + scales dequantized per tile in registers — so int8
      is what crosses HBM on the read. Single-token queries take the
      flash-decode kernel; multi-token blocks (``extend_core``:
      chunked prefill, admission mini-prefills, prefix suffixes,
      speculative verify) take its U-token flash-extend twin, whose
      ``[B, U, L]`` mask (``extend_positions_and_mask``) carries the
      causal intra-span structure — every token the server processes
      reads the cache at its stored byte format.

    PAGED cache layers (``ops/quant.kv_is_paged_layer``: pool +
    page-table) route through the same two impls: the einsum path
    gathers pages into the contiguous oracle layout inside
    ``kv_cache_kv`` (the reference), while the flash path hands the
    pools and the table to ``paged_decode_attention`` — the page
    table becomes the kernel's BlockSpec index map and no contiguous
    cache ever materializes.

    ``mesh`` (optional): when it carries a ``model`` axis of size > 1
    that divides the cache's KV-head count, the flash kernel runs
    under an explicit ``shard_map`` over that axis
    (``decode_attention_tp`` / ``paged_decode_attention_tp``) so
    GSPMD cannot all-gather head-sharded cache operands around the
    opaque ``pallas_call``. Indivisible head counts fall back to the
    unwrapped kernel (GSPMD decides, as before).
    """
    from mlapi_tpu.ops.attention import NEG
    from mlapi_tpu.ops.quant import (
        kv_cache_append, kv_cache_kv, kv_is_paged_layer,
        kv_is_quantized_layer,
    )

    expand = expand or (lambda t: t)
    new_layer = kv_cache_append(cache_layer, k_new, v_new, pos, cdt)
    if impl == "flash":
        from mlapi_tpu.ops.pallas import (
            decode_attention, decode_attention_tp,
            extend_attention, extend_attention_tp,
            paged_decode_attention, paged_decode_attention_tp,
            paged_extend_attention, paged_extend_attention_tp,
        )

        u = q.shape[1]
        paged = kv_is_paged_layer(new_layer)
        if kv_is_quantized_layer(new_layer):
            k = {"q": new_layer["k_q"], "scale": new_layer["k_scale"]}
            v = {"q": new_layer["v_q"], "scale": new_layer["v_scale"]}
            kvh = new_layer["k_q"].shape[2]
        else:
            k, v = new_layer["k"], new_layer["v"]
            kvh = new_layer["k"].shape[2]
        # Single-token steps carry a [B, 1, 1, L] validity; extends a
        # [B, 1, U, L] one. Both collapse the same way: drop the
        # broadcast head axis, keep one mask row per query row.
        if u == 1:
            mask2 = valid[:, 0, 0, :].astype(jnp.float32)  # [B, L]
        else:
            mask2 = valid[:, 0].astype(jnp.float32)        # [B, U, L]
        scale = 1.0 / head_dim**0.5
        # Interpret ONLY on CPU (the CI backend). On TPU the
        # compiled kernel runs; any other accelerator attempts a
        # real lowering and fails loudly — silently interpreting
        # every decode step there would be orders slower than the
        # einsum path this kernel exists to beat.
        interp = jax.default_backend() == "cpu"
        tp = (
            mesh.shape["model"]
            if mesh is not None and "model" in getattr(
                mesh, "axis_names", ()
            )
            else 1
        )
        use_tp = tp > 1 and kvh % tp == 0 and q.shape[2] % tp == 0
        if paged:
            table = new_layer["table"]
            fn_tp = (
                paged_decode_attention_tp if u == 1
                else paged_extend_attention_tp
            )
            fn = (
                paged_decode_attention if u == 1
                else paged_extend_attention
            )
            if use_tp:
                ctx = fn_tp(
                    mesh, q, k, v, table, mask2, scale=scale,
                    interpret=interp,
                )
            else:
                ctx = fn(
                    q, k, v, table, mask2, scale=scale,
                    interpret=interp,
                )
        elif use_tp:
            fn_tp = decode_attention_tp if u == 1 else extend_attention_tp
            ctx = fn_tp(
                mesh, q, k, v, mask2, scale=scale, interpret=interp,
            )
        else:
            fn = decode_attention if u == 1 else extend_attention
            ctx = fn(
                q, k, v, mask2, scale=scale, interpret=interp,
            )
        return ctx, new_layer
    ck, cv = kv_cache_kv(new_layer, cdt)
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, expand(ck),
            preferred_element_type=jnp.float32,
        )
        / head_dim**0.5
    )
    scores = jnp.where(valid, scores, NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    ctx = jnp.einsum(
        "bhqk,bkhd->bqhd", probs, expand(cv),
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    return ctx, new_layer


def _prefill_core(model, params, prompt_ids, n_pad, total_len: int):
    """Decoder-protocol prefill dispatch: every model family
    implements ``prefill_core`` (full forward over a left-padded
    ``[B, P]`` prompt → ``(cache, last_logits)``); everything
    downstream (``_decode_scan``, ``prefill_fn``, ``decode_chunk_fn``,
    ``_generate_fn``) is model-generic.

    Contract (see ``GptLM.prefill_core`` for the canonical
    implementation): per-row ``n_pad`` pad positions are masked out of
    attention and positions are shifted so real tokens occupy
    effective positions ``0..P-1-n_pad[b]``; every row's last real
    token sits at index ``P-1`` (right-aligned), so the next-token
    logits are one static slice. One batched forward + cache build is
    a single fused program — prefilling via P decode-shaped steps
    would cost P dispatches.
    """
    return model.prefill_core(params, prompt_ids, n_pad, total_len)


def _decode_scan(
    model, params, cache, tok, pos, n_pad, temps, key_data,
    n_steps: int, step0, top_k=None, top_p=None,
    prefix_len=None, prefix_lo=None,
):
    """``n_steps`` cached decode steps under one ``lax.scan``.

    ``tok`` ``[B]`` is the last emitted token (fed back in), ``pos``
    the traced cache position it occupies + 1 is written next;
    ``step0`` the traced sampling-stream offset — scalar or per-row
    ``[B]`` (so chunked decoding reproduces the single-scan token
    stream exactly, including rows admitted mid-batch at a different
    token index than their neighbours). Returns
    ``(tokens [B, n_steps], cache, last_tok)``.
    """
    b = tok.shape[0]
    step0 = jnp.broadcast_to(jnp.asarray(step0, jnp.int32), (b,))

    def step(carry, i):
        cache, tok, pos = carry
        logits, cache = model.decode_step(
            params, cache, tok[:, None], pos, n_pad,
            prefix_len, prefix_lo,
        )
        nxt = _pick_token(temps, logits, key_data, i + step0, top_k, top_p)
        return (cache, nxt, pos + 1), nxt

    (cache, tok, _), toks = jax.lax.scan(
        step, (cache, tok, pos), jnp.arange(n_steps)
    )
    return toks.T, cache, tok


@functools.lru_cache(maxsize=256)
def _generate_fn(model, max_new_tokens: int):
    """One jitted end-to-end generation program per (model config,
    token count); temperature, pad widths, and PRNG keys are traced
    arguments (the key as raw uint32 data — see ``generate``)."""

    def _run(params, prompt_ids, key_data, temps, n_pad, top_k, top_p):
        p = prompt_ids.shape[1]
        cache, first_logits = _prefill_core(
            model, params, prompt_ids, n_pad, p + max_new_tokens
        )
        first = _pick_token(temps, first_logits, key_data, 0, top_k, top_p)
        if max_new_tokens == 1:
            return first[:, None]
        rest, _, _ = _decode_scan(
            model, params, cache, first, jnp.int32(p), n_pad, temps,
            key_data, max_new_tokens - 1, jnp.int32(1), top_k, top_p,
        )
        return jnp.concatenate([first[:, None], rest], axis=1)

    return jax.jit(_run)


@functools.lru_cache(maxsize=64)
def generate_tier_fn(model, tier: int):
    """A whole generation — any batch size — as ONE XLA program:
    prefill + a ``lax.while_loop`` of cached decode steps writing into
    a ``[B, tier]`` output buffer, with per-row budgets ``n_actual <=
    tier`` TRACED (the loop runs to the row maximum; a finished row's
    later writes land beyond its budget and are sliced off by the
    caller). One compile per (model, batch, prompt bucket, tier)
    serves every budget combination in the tier, and through a
    high-RTT attach (the tunneled chip pays ~one RTT per dispatch,
    chained or not) the whole BATCH costs ONE dispatch + ONE readback
    instead of one per chunk — the serving engine's fused fast path,
    solo and batched.

    ``(params, prompt_ids [B, P], key_data [B, ...], temps [B],
    n_pad [B], top_k [B], top_p [B], n_actual [B] or scalar)`` →
    ``tokens [B, tier]`` (row ``b``'s first ``n_actual[b]`` valid).
    Every row's stream is byte-identical to the chunked engine path
    AND to its own solo run: same left-padded prefill, same per-row
    PRNG streams at per-token ``_pick_token`` indices (first token at
    0, then 1, 2, ...) — a row's tokens do not depend on its batch.
    """

    def _run(params, prompt_ids, key_data, temps, n_pad, top_k, top_p,
             n_actual):
        p = prompt_ids.shape[1]
        cache, logits = _prefill_core(
            model, params, prompt_ids, n_pad, p + tier
        )
        first = _pick_token(temps, logits, key_data, 0, top_k, top_p)
        b = first.shape[0]
        out = jnp.zeros((b, tier), jnp.int32).at[:, 0].set(first)
        n_max = jnp.max(jnp.asarray(n_actual))

        def cond(s):
            return s[3] < n_max

        def body(s):
            cache, tok, pos, i, out = s
            logits, cache = model.decode_step(
                params, cache, tok[:, None], pos, n_pad
            )
            nxt = _pick_token(temps, logits, key_data, i, top_k, top_p)
            out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, i))
            return (cache, nxt, pos + 1, i + 1, out)

        s = (cache, first, jnp.int32(p), jnp.int32(1), out)
        return jax.lax.while_loop(cond, body, s)[4]

    return jax.jit(_run)


@functools.lru_cache(maxsize=64)
def prefill_fn(model, total_len: int):
    """Jitted prefill + first-token program for incremental decoding:
    ``(params, prompt_ids [B,P], key_data, temps, n_pad)`` →
    ``(first_tok [B], cache)``. Compiled per (model, B, P, total_len);
    any ``max_new_tokens`` then reuses it via ``decode_chunk_fn`` —
    the serving engine's compile count stays bounded by shape buckets,
    not by request parameters."""

    def _run(params, prompt_ids, key_data, temps, n_pad, top_k, top_p):
        cache, logits = _prefill_core(
            model, params, prompt_ids, n_pad, total_len
        )
        return _pick_token(temps, logits, key_data, 0, top_k, top_p), cache

    return jax.jit(_run)


@functools.cache
def admit_scatter_fn():
    """Jitted continuous-batching admission scatter: place a joiner's
    prompt K/V (a ``[1, bucket]``-shaped cache pytree from
    ``prefill_fn(model, bucket)``) into row ``r`` of a RUNNING batch's
    ``[B, total]`` cache, ending at the batch's current decode
    position ``pos`` (``r`` and ``pos - bucket`` are traced scalars —
    one compile covers every admission point). Splitting admission
    into (bucket-keyed prefill) + (this scatter) keeps the EXPENSIVE
    compile keyed on the prompt bucket alone; the scatter is pure
    data movement and compiles per (bucket, cache, batch) shape in
    negligible time, which is what makes admission viable at every
    cache tier, not just the warmed default.

    Cache-slot layout for the admitted row: real prompt tokens land in
    slots ``[pos - used, pos)`` and everything earlier is masked via
    ``n_pad_row = pos - used``, so the next decode step (which writes
    at ``pos``) sees exactly the joiner's prompt at effective
    positions ``0..used-1`` — byte-identical semantics to a row that
    was in the batch from its own prefill.
    """

    def _run(cache, mini, r, off):
        def scatter(big, small):
            start = (r,) + (off,) + (0,) * (big.ndim - 2)
            return jax.lax.dynamic_update_slice(
                big, small.astype(big.dtype), start
            )

        return jax.tree.map(scatter, cache, mini)

    return jax.jit(_run, donate_argnums=(0,))


@functools.cache
def realign_fn():
    """Jitted per-row cache ROLL for batched-speculation handoff:
    shift row ``b``'s slots right by ``delta[b]`` (``new[b, i] =
    old[b, i - delta_b]``, clamped reads below 0 land on slot 0 and
    are garbage). Callers bump ``n_pad[b] += delta_b`` so the rolled
    rows' effective positions (``slot - n_pad``) are UNCHANGED —
    wpe indices and stored rotary phases both key on effective
    position, so the roll is exact for every decoder family. This is
    what lets desynchronized per-row speculative positions rejoin
    the scalar-``pos`` chunk loop (and its admission machinery) at a
    round boundary."""

    def _run(cache, delta):
        def roll(a):
            L = a.shape[1]
            idx = jnp.arange(L)[None, :] - delta[:, None]  # [B, L]
            idx = jnp.clip(idx, 0, L - 1)
            return jnp.take_along_axis(
                a, idx.reshape(idx.shape + (1,) * (a.ndim - 2)), axis=1
            )

        return jax.tree.map(roll, cache)

    return jax.jit(_run, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def decode_chunk_fn(model, chunk: int):
    """Jitted ``chunk``-step decode program:
    ``(params, cache, tok, pos, n_pad, temps, key_data, step0)`` →
    ``(tokens [B, chunk], cache, last_tok)``. The cache is donated —
    each chunk updates it in place (no per-chunk HBM copy); callers
    must use the returned cache handle."""

    def _run(params, cache, tok, pos, n_pad, temps, key_data, step0,
             top_k, top_p, prefix_len, prefix_lo):
        return _decode_scan(
            model, params, cache, tok, pos, n_pad, temps, key_data,
            chunk, step0, top_k, top_p, prefix_len, prefix_lo,
        )

    return jax.jit(_run, donate_argnums=(1,))


@functools.lru_cache(maxsize=64)
def extend_chunk_fn(model, width: int, total: int):
    """Jitted chunked-prefill program: one ``[B, width]`` block of a
    long prompt forwarded against the cache at traced offset ``pos0``
    (``extend_core``). Because the offset is traced, ONE compile
    serves every chunk of every prompt padded to a ``width`` multiple
    — a 4096-token prompt costs ceil(4096/width) dispatches of this
    same program instead of a bespoke exact-length compile per prompt
    length (the compile-count story that makes long-context serving
    predictable). Returns ``(cache, last_logits)``; the caller samples
    from the FINAL chunk's logits only."""

    def _run(params, cache, chunk_ids, pos0, n_pad):
        return model.extend_core(
            params, cache, chunk_ids, pos0, n_pad,
            jnp.int32(0), jnp.int32(0),
        )

    return jax.jit(_run, donate_argnums=(1,))


@functools.lru_cache(maxsize=64)
def paged_extend_fn(model, width: int):
    """Jitted ``[B, width]`` block forward against a PAGED cache at
    traced offset ``pos0`` with traced prefix-region parameters — the
    paged serving lifecycle's one prefill workhorse. It covers what
    took two contiguous programs: chunked long-prompt prefill
    (``prefix_len = 0``, the ``extend_chunk_fn`` role) and
    shared-prefix suffix prefill (``pos0 = prefix_len = P`` with the
    region's ``lo``, the ``prefix_prefill_fn`` role) — because a paged
    cache arrives with its page TABLE already describing the rows
    (shared prefix pages included), there is no per-variant cache
    construction left to fuse in. Callers sample the final block's
    logits with ``sample_fn`` (stream index 0 — byte-identical to the
    contiguous programs' draws). The cache is donated: pool updates
    are in place."""

    def _run(params, cache, chunk_ids, pos0, n_pad, prefix_len, lo):
        return model.extend_core(
            params, cache, chunk_ids, pos0, n_pad, prefix_len, lo
        )

    return jax.jit(_run, donate_argnums=(1,))


@functools.lru_cache(maxsize=64)
def paged_prefill_fn(model, width: int):
    """Page-native prefill + first token: the SAME full causal forward
    as ``prefill_fn`` (prompt block attends full-precision
    in-register), but the K/V append lands straight in pool pages —
    ``cache`` is a paged pytree (pool leaves + ``[R, NP]`` table
    mirrors) and ``off`` the traced VIRTUAL slot of the row's bucket
    start, so formation and admission write the prefill bytes exactly
    once (``generate.prefill_adopt_bytes`` reads 0 on this path where
    the contiguous-then-``paged_scatter_fn`` adopt paid one full extra
    copy). ``n_pad`` stays the row's LOCAL pad count (``bucket -
    used``): effective positions are ``local_slot - n_pad``, invariant
    under ``off``, which is what pins the token stream identical to
    the adopt path. ``(params, cache, prompt_ids [R, width], off,
    key_data, temps, n_pad, top_k, top_p) → (first_tok [R], cache)``;
    the cache is donated (pool updates in place)."""

    def _run(params, cache, prompt_ids, off, key_data, temps, n_pad,
             top_k, top_p):
        cache, logits = model.prefill_core(
            params, prompt_ids, n_pad, 0, cache=cache, pos0=off
        )
        return _pick_token(temps, logits, key_data, 0, top_k, top_p), cache

    return jax.jit(_run, donate_argnums=(1,))


@functools.cache
def paged_scatter_fn():
    """Jitted paged ADOPT: copy a contiguous ``[R, W]``-shaped cache
    pytree (a prefill's output, a joiner's mini cache, a prefix
    entry's KV) into pool pages at virtual offset ``off`` of the
    ``[R, NP]`` page-table rows ``table`` — one scatter per leaf, the
    coordinates shared with ``ops/quant``'s paged append. This is the
    page-granular replacement for ``admit_scatter_fn`` (no whole-row
    cache object to write into) and the bridge by which contiguous
    prefill programs feed the paged pool; formation pays one extra
    copy of the bytes prefill just wrote (page-native prefill is a
    noted follow-up), while ADMISSION keeps the contiguous path's
    shape: bucket-keyed prefill + a trivial scatter."""

    def _run(cache, mini, table, off):
        from mlapi_tpu.ops.quant import kv_layer_page_size

        out = {}
        for ln, layer in cache.items():
            page = kv_layer_page_size(layer)
            small = mini[ln]
            w = next(iter(small.values())).shape[1]
            r = table.shape[0]
            vpos = off + jnp.arange(w)  # [W] virtual slots
            pids = jnp.take_along_axis(
                table, jnp.broadcast_to((vpos // page)[None], (r, w)),
                axis=1,
            )
            offs = jnp.broadcast_to((vpos % page)[None], (r, w))
            new_layer = {"table": layer["table"]}
            for name in small:
                new_layer[name] = layer[name].at[pids, offs].set(
                    small[name].astype(layer[name].dtype)
                )
            out[ln] = new_layer
        return out

    return jax.jit(_run, donate_argnums=(0,))


@functools.cache
def paged_cow_fn():
    """Jitted copy-on-write page copy: duplicate pool pages ``src``
    into freshly-allocated pages ``dst`` (both ``int32 [R]``) across
    every layer's pools — the device half of COW. One gather+scatter
    of R pages, independent of sequence length or batch size: this is
    what lets a shared prefix's last partial page diverge per row
    without copying anyone's cache. The caller rewrites the HOST page
    table; the pools are donated."""

    def _run(cache, src, dst):
        out = {}
        for ln, layer in cache.items():
            new_layer = {"table": layer["table"]}
            for name in layer:
                if name == "table":
                    continue
                pool = layer[name]
                new_layer[name] = pool.at[dst].set(pool[src])
            out[ln] = new_layer
        return out

    return jax.jit(_run, donate_argnums=(0,))


@functools.cache
def paged_realign_fn():
    """Jitted paged fallback for the batched-speculation handoff when
    a row's realign delta is NOT a page multiple (the page-aligned
    case is a pure HOST table shift — see ``BatchRun._paged_realign``).
    Row gather + write-back THROUGH the tables: every row's virtual
    window is gathered at the shifted coordinates
    (``new[b, v] = old[b, v - delta_b]``, clamped like
    :func:`realign_fn`) and scattered back into the row's OWN mapped
    pages (unmapped tiles route through the never-read null page).
    Cost is one pass over the rows' whole VIRTUAL window — bounded by
    the cache tier, same order as the contiguous ``realign_fn`` roll
    it replaces (keying the program on the live extent would compile
    per handoff width) — a loud, counted repack
    (``generate.spec_realign_repacks``), kept only for the sub-page
    case page identity cannot express. Rows must not share pages
    (``p_len == 0`` batches — the only ones batched spec takes).
    The cache is donated."""

    def _run(cache, delta):
        from mlapi_tpu.ops.quant import kv_layer_page_size

        out = {}
        for ln, layer in cache.items():
            page = kv_layer_page_size(layer)
            table = layer["table"]
            b, npv = table.shape
            L = npv * page
            vdst = jnp.arange(L)[None, :]                     # [1, L]
            vsrc = jnp.clip(vdst - delta[:, None], 0, L - 1)  # [B, L]
            pd = jnp.take_along_axis(
                table, jnp.broadcast_to(vdst // page, (b, L)), axis=1
            )
            od = jnp.broadcast_to(vdst % page, (b, L))
            ps = jnp.take_along_axis(table, vsrc // page, axis=1)
            os_ = vsrc % page
            new_layer = {"table": table}
            for name, pool in layer.items():
                if name == "table":
                    continue
                new_layer[name] = pool.at[pd, od].set(pool[ps, os_])
            out[ln] = new_layer
        return out

    return jax.jit(_run, donate_argnums=(0,))


@functools.lru_cache(maxsize=16)
def sample_fn(model):
    """Jitted standalone sampler for the chunked-prefill path: the
    final chunk's logits → each row's first token at stream index 0
    (identical draw to the fused prefill programs)."""

    def _run(logits, key_data, temps, top_k, top_p):
        return _pick_token(temps, logits, key_data, 0, top_k, top_p)

    return jax.jit(_run)


@functools.lru_cache(maxsize=64)
def prefix_prefill_fn(model, suffix_len: int, total: int):
    """Jitted prefix-cache prefill + first-token program: scatter a
    shared prompt prefix's precomputed KV (``prefix_kv``, a
    ``[1, P]``-shaped cache pytree from ``prefill_fn(model, P)``)
    into slots ``[0, P)`` of EVERY row of a fresh ``[B, total]``
    cache, then run a teacher-forced scan over the left-padded
    ``[B, suffix_len]`` suffix block at slots ``[P, P+suffix_len)``.
    The prefix forward is never recomputed — that is the entire
    point: time-to-first-token for a request with an S-token shared
    prefix drops from O(P + U) to O(U) forward work.

    Per-row suffix pads (``hole [B]``) are masked via the pad hole in
    :func:`extend_positions_and_mask`; ``lo`` is the prefix's OWN
    left-pad inside its bucket. Cross-batch prefix sharing rides the
    same program shapes: ``prefix_kv`` may be a per-row ``[B, P]``
    stack (each row's own prefix, right-aligned to the common region
    end ``P``) with ``lo`` a per-row ``[B]`` vector — the broadcast
    becomes the identity and the mask helpers handle the vector. The suffix runs as ONE fused block
    forward (``extend_core``) — a single weight pass, like the plain
    prefill, so the KV path beats re-prefilling the concatenation for
    every nonempty prefix. Sampling draws at each row's stream index
    0, so the emitted stream is byte-identical to the same prompt
    served without prefix caching. Returns ``(first_tok [B], cache)``.
    """

    def _run(params, prefix_kv, suffix_ids, hole, lo, key_data, temps,
             top_k, top_p):
        b = suffix_ids.shape[0]
        p_len = jax.tree.leaves(prefix_kv)[0].shape[1]
        cache = model.init_cache(b, total)
        cache = jax.tree.map(
            lambda big, small: jax.lax.dynamic_update_slice(
                big,
                jnp.broadcast_to(
                    small, (b,) + small.shape[1:]
                ).astype(big.dtype),
                (0, 0, 0, 0),
            ),
            cache, prefix_kv,
        )
        cache, logits = model.extend_core(
            params, cache, suffix_ids, jnp.int32(p_len), hole,
            jnp.int32(p_len), lo,
        )
        first = _pick_token(temps, logits, key_data, 0, top_k, top_p)
        return first, cache

    return jax.jit(_run)
