"""Decoder-only (GPT-style) causal LM — the generative model family.

The reference serves only classifiers (``main.py:16-27``); this goes
past parity: same TPU-first recipe as the BERT encoder (one flat param
pytree, explicit einsum attention, bf16 hidden compute / f32 softmax
+ layernorm stats, Megatron TP layout over the ``model`` mesh axis)
plus what decoding actually needs on a TPU:

- **Causal attention** through the shared ops (`full_attention` /
  Pallas ``flash_attention`` / sequence-parallel ``ring_attention``
  all take ``causal=True``).
- **KV-cache decode under ``lax.scan``**: generation is one compiled
  XLA while-program — fixed-shape cache ``[B, max_len, H, D]`` per
  layer, one token per step, no per-token Python dispatch.

Pre-norm blocks (GPT-2 style: ln -> attn -> residual, ln -> mlp ->
residual, final ln), learned positions, weight-tied LM head.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mlapi_tpu.models import register_model

_LN_EPS = 1e-5


def _layer_norm(x, scale, bias):
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + _LN_EPS) * scale + bias


@register_model("gpt_lm")
@dataclass(frozen=True)
class GptLM:
    """Decoder-only causal language model with weight-tied head."""

    input_kind = "text"

    vocab_size: int = 512
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    max_positions: int = 256
    compute_dtype: str = "bfloat16"
    # "full" | "flash" (Pallas kernel) — both causal. Ring attention
    # composes at the ops level for training on a seq-axis mesh.
    attention_impl: str = "full"

    def __post_init__(self):
        if self.attention_impl not in ("full", "flash"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.hidden_size % self.num_heads:
            raise ValueError("hidden_size must divide evenly into heads")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> dict:
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        keys = iter(jax.random.split(rng, 2 + 6 * self.num_layers))

        def dense(k, shape, scale=0.02):
            return {
                "kernel": scale * jax.random.normal(k, shape),
                "bias": jnp.zeros((shape[-1],)),
            }

        params = {
            "wte": 0.02 * jax.random.normal(next(keys), (v, h)),
            "wpe": 0.01 * jax.random.normal(next(keys), (self.max_positions, h)),
            "ln_f_scale": jnp.ones((h,)),
            "ln_f_bias": jnp.zeros((h,)),
        }
        for n in range(self.num_layers):
            params[f"layer_{n}"] = {
                "qkv": dense(next(keys), (h, 3 * h)),
                "attn_out": dense(next(keys), (h, h)),
                "ln1_scale": jnp.ones((h,)),
                "ln1_bias": jnp.zeros((h,)),
                "ffn_up": dense(next(keys), (h, i)),
                "ffn_down": dense(next(keys), (i, h)),
                "ln2_scale": jnp.ones((h,)),
                "ln2_bias": jnp.zeros((h,)),
            }
        return jax.tree.map(lambda a: a.astype(jnp.float32), params)

    # ------------------------------------------------------------------
    def _block(self, layer, x, attend):
        """One pre-norm transformer block; ``attend(q, k, v)`` supplies
        the attention so the full-sequence and cached-decode paths
        share every other op."""
        cdt = jnp.dtype(self.compute_dtype)
        b, l, h = x.shape
        nh, hd = self.num_heads, self.head_dim

        xn = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"]).astype(cdt)
        qkv = xn @ layer["qkv"]["kernel"].astype(cdt) + layer["qkv"][
            "bias"
        ].astype(cdt)
        q, k, v = jnp.split(qkv.reshape(b, l, 3 * nh, hd), 3, axis=2)
        ctx = attend(q, k, v).reshape(b, l, -1)
        attn = ctx @ layer["attn_out"]["kernel"].astype(cdt) + layer[
            "attn_out"
        ]["bias"].astype(cdt)
        x = x + attn.astype(jnp.float32)

        xn = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"]).astype(cdt)
        up = xn @ layer["ffn_up"]["kernel"].astype(cdt) + layer["ffn_up"][
            "bias"
        ].astype(cdt)
        up = jax.nn.gelu(up.astype(jnp.float32), approximate=True).astype(cdt)
        down = up @ layer["ffn_down"]["kernel"].astype(cdt) + layer[
            "ffn_down"
        ]["bias"].astype(cdt)
        return x + down.astype(jnp.float32)

    def apply(self, params: dict, token_ids) -> jax.Array:
        """``[B, L]`` ids → ``[B, L, V]`` next-token logits (causal)."""
        from mlapi_tpu.ops import full_attention

        b, l = token_ids.shape
        x = params["wte"][token_ids] + params["wpe"][jnp.arange(l)][None]

        if self.attention_impl == "flash":
            from mlapi_tpu.ops.pallas import flash_attention

            def attend(q, k, v):
                return flash_attention(
                    q, k, v, causal=True,
                    interpret=jax.default_backend() != "tpu",
                )
        else:
            def attend(q, k, v):
                return full_attention(q, k, v, causal=True)

        for n in range(self.num_layers):
            x = self._block(params[f"layer_{n}"], x, attend)
        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        # Weight-tied head; logits in f32 for a stable softmax/loss.
        return x.astype(jnp.float32) @ params["wte"].T.astype(jnp.float32)

    # ------------------------------------------------------------------
    def init_cache(self, batch: int, max_len: int) -> dict:
        """Fixed-shape KV cache: ``[B, max_len, H, D]`` per layer."""
        nh, hd = self.num_heads, self.head_dim
        cdt = jnp.dtype(self.compute_dtype)
        return {
            f"layer_{n}": {
                "k": jnp.zeros((batch, max_len, nh, hd), cdt),
                "v": jnp.zeros((batch, max_len, nh, hd), cdt),
            }
            for n in range(self.num_layers)
        }

    def decode_step(self, params, cache, token_ids, pos):
        """One decode step: ``[B, 1]`` ids at position ``pos`` (traced
        scalar) → (``[B, V]`` logits, updated cache). The KV for the
        new token is written into the fixed-shape cache; attention
        reads the full cache with positions ``> pos`` masked out —
        static shapes, so the scan body compiles once."""
        from mlapi_tpu.ops.attention import NEG

        cdt = jnp.dtype(self.compute_dtype)
        b = token_ids.shape[0]
        nh, hd = self.num_heads, self.head_dim
        max_len = cache["layer_0"]["k"].shape[1]

        x = params["wte"][token_ids] + params["wpe"][pos][None, None]
        new_cache = {}
        valid = (jnp.arange(max_len) <= pos)[None, None, None, :]  # [1,1,1,L]

        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]

            def attend(q, k_new, v_new, *, _n=n):
                ck = jax.lax.dynamic_update_slice(
                    cache[f"layer_{_n}"]["k"], k_new.astype(cdt), (0, pos, 0, 0)
                )
                cv = jax.lax.dynamic_update_slice(
                    cache[f"layer_{_n}"]["v"], v_new.astype(cdt), (0, pos, 0, 0)
                )
                new_cache[f"layer_{_n}"] = {"k": ck, "v": cv}
                scores = (
                    jnp.einsum(
                        "bqhd,bkhd->bhqk", q, ck,
                        preferred_element_type=jnp.float32,
                    )
                    / hd**0.5
                )
                scores = jnp.where(valid, scores, NEG)
                probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
                return jnp.einsum(
                    "bhqk,bkhd->bqhd", probs, cv,
                    preferred_element_type=jnp.float32,
                ).astype(q.dtype)

            x = self._block(layer, x, attend)

        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = x[:, 0].astype(jnp.float32) @ params["wte"].T.astype(
            jnp.float32
        )
        return logits, new_cache

    def generate(
        self,
        params,
        prompt_ids,
        *,
        max_new_tokens: int,
        temperature: float = 0.0,
        rng: jax.Array | None = None,
    ):
        """Greedy (``temperature=0``) or sampled generation.

        ``prompt_ids``: ``[B, P]`` int32. Returns ``[B, max_new_tokens]``.
        Prefill runs the full forward once; decode is a ``lax.scan``
        over single-token steps against the KV cache — one jitted
        program end to end (the jit also keys the executable cache
        correctly per (shape, max_new_tokens, temperature) signature).
        """
        p = prompt_ids.shape[1]
        if p + max_new_tokens > self.max_positions:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_positions ({self.max_positions})"
            )
        rng = jax.random.key(0) if rng is None else rng
        # The key crosses the jit boundary as raw uint32 data: a typed
        # key array as a jit argument trips a fastpath buffer-count
        # bug in this JAX version once other executables exist on a
        # multi-device host (second identical call INVALID_ARGUMENT).
        return _generate_fn(self, max_new_tokens, float(temperature))(
            params, prompt_ids, jax.random.key_data(rng)
        )

    # ------------------------------------------------------------------
    def param_shardings(self, layout=None) -> dict:
        """Megatron TP over ``model``: qkv/ffn-up column-sharded,
        attn-out/ffn-down row-sharded, embeddings vocab-sharded."""
        from mlapi_tpu.parallel import MODEL_AXIS

        col = {"kernel": P(None, MODEL_AXIS), "bias": P(MODEL_AXIS)}
        row = {"kernel": P(MODEL_AXIS, None), "bias": P()}
        specs = {
            "wte": P(MODEL_AXIS, None),
            "wpe": P(),
            "ln_f_scale": P(),
            "ln_f_bias": P(),
        }
        for n in range(self.num_layers):
            specs[f"layer_{n}"] = {
                "qkv": dict(col),
                "attn_out": dict(row),
                "ln1_scale": P(), "ln1_bias": P(),
                "ffn_up": dict(col),
                "ffn_down": dict(row),
                "ln2_scale": P(), "ln2_bias": P(),
            }
        return specs


@functools.lru_cache(maxsize=256)
def _generate_fn(model: GptLM, max_new_tokens: int, temperature: float):
    """One jitted generation program per (model config, token count,
    temperature); config enters via closure and the PRNG key as raw
    data (see ``generate`` for the jit-boundary rationale)."""

    def _run(params, prompt_ids, key_data):
        rng = jax.random.wrap_key_data(key_data)
        return _generate(model, params, prompt_ids, max_new_tokens,
                         temperature, rng)

    return jax.jit(_run)


def _generate(
    model: GptLM, params, prompt_ids, max_new_tokens: int,
    temperature: float, rng,
):
    self = model
    b, p = prompt_ids.shape
    total = p + max_new_tokens
    # Prefill: full causal forward over the prompt while writing
    # the cache via decode-shaped updates would cost P steps; one
    # batched forward + cache build is a single fused program.
    cache = self.init_cache(b, total)
    cdt = jnp.dtype(self.compute_dtype)
    nh, hd = self.num_heads, self.head_dim

    from mlapi_tpu.ops import full_attention

    x = params["wte"][prompt_ids] + params["wpe"][jnp.arange(p)][None]
    for n in range(self.num_layers):
        layer = params[f"layer_{n}"]
        kv_seen = {}

        def attend(q, k, v, *, _n=n, _kv=kv_seen):
            _kv["k"], _kv["v"] = k, v
            return full_attention(q, k, v, causal=True)

        x = self._block(layer, x, attend)
        cache[f"layer_{n}"] = {
            "k": jax.lax.dynamic_update_slice(
                cache[f"layer_{n}"]["k"], kv_seen["k"].astype(cdt),
                (0, 0, 0, 0),
            ),
            "v": jax.lax.dynamic_update_slice(
                cache[f"layer_{n}"]["v"], kv_seen["v"].astype(cdt),
                (0, 0, 0, 0),
            ),
        }
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    first_logits = x[:, -1].astype(jnp.float32) @ params["wte"].T.astype(
        jnp.float32
    )

    def pick(logits, step_rng):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            step_rng, logits / temperature, axis=-1
        ).astype(jnp.int32)

    def step(carry, step_rng):
        cache, tok, pos = carry
        logits, cache = self.decode_step(params, cache, tok[:, None], pos)
        nxt = pick(logits, step_rng)
        return (cache, nxt, pos + 1), nxt

    first = pick(first_logits, jax.random.fold_in(rng, 0))
    if max_new_tokens == 1:
        return first[:, None]
    (_, _, _), rest = jax.lax.scan(
        step,
        (cache, first, jnp.int32(p)),
        jax.random.split(jax.random.fold_in(rng, 1), max_new_tokens - 1),
    )
    return jnp.concatenate([first[:, None], rest.T], axis=1)
