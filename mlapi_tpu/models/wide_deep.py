"""Wide&Deep classifier — config 4 of the ladder
(``BASELINE.json:10``); the first model where mesh layout matters
(SURVEY §7 step 6).

Architecture (Cheng et al. 2016, re-designed for TPU/GSPMD):

- **Wide**: linear on the dense features + per-(feature, id) scalar
  weights for the categoricals — implemented as dim-``num_classes``
  embedding lookups so the whole wide path is gathers + one matmul.
- **Deep**: dim-``embed_dim`` embeddings per categorical feature,
  concatenated with the dense features into an MLP (bfloat16 hidden
  compute on the MXU, f32 logits).

All 26 tables share one stacked tensor ``[F, V, D]`` (vocabs padded
to the max size), so the lookup is ONE advanced-indexing gather that
XLA maps onto a batched dynamic-slice — no per-feature Python loop in
the traced graph.

Sharding: the tables' vocab axis is the big dimension
(26 × 100k × 16 floats for the preset), so ``param_shardings`` places
it on the ``model`` mesh axis — each chip owns a slab of the hash
space and XLA turns the gather into gather + all-to-all over ICI.
Everything else (dense weights, MLP) is small and replicated.

Input rows are flat float32 ``[num_dense + F]`` vectors (categorical
ids as floats, cast inside ``apply``) so the tabular serving stack —
schema, batcher, engine — works unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mlapi_tpu.models import register_model


@register_model("wide_deep")
@dataclass(frozen=True)
class WideDeepClassifier:
    num_dense: int
    vocab_sizes: tuple[int, ...]
    embed_dim: int = 16
    hidden_dims: tuple[int, ...] = (256, 128)
    num_classes: int = 2
    compute_dtype: str = "bfloat16"

    def __post_init__(self):
        object.__setattr__(self, "vocab_sizes", tuple(self.vocab_sizes))
        object.__setattr__(self, "hidden_dims", tuple(self.hidden_dims))

    @property
    def num_categorical(self) -> int:
        return len(self.vocab_sizes)

    @property
    def num_features(self) -> int:
        return self.num_dense + self.num_categorical

    @property
    def padded_vocab(self) -> int:
        return max(self.vocab_sizes)

    def init(self, rng: jax.Array) -> dict:
        k_deep, k_wide, *k_mlp = jax.random.split(
            rng, 2 + len(self.hidden_dims) + 1
        )
        f, v, d = self.num_categorical, self.padded_vocab, self.embed_dim
        params = {
            "wide_dense": jnp.zeros((self.num_dense, self.num_classes)),
            "wide_bias": jnp.zeros((self.num_classes,)),
            "wide_tables": 1e-3
            * jax.random.normal(k_wide, (f, v, self.num_classes)),
            "deep_tables": (1.0 / jnp.sqrt(d))
            * jax.random.normal(k_deep, (f, v, d)),
        }
        widths = [self.num_dense + f * d, *self.hidden_dims, self.num_classes]
        for i, (w_in, w_out) in enumerate(zip(widths[:-1], widths[1:])):
            scale = jnp.sqrt(2.0 / w_in)
            params[f"deep_{i}"] = {
                "kernel": scale * jax.random.normal(k_mlp[i], (w_in, w_out)),
                "bias": jnp.zeros((w_out,)),
            }
        return jax.tree.map(lambda a: a.astype(jnp.float32), params)

    def embedding_ids(self, x: jax.Array) -> jax.Array:
        """Categorical ids for a batch, ``[B, F]`` int32. Ids arrive
        as floats in the flat row; clamp into each table's vocab."""
        return jnp.remainder(
            x[:, self.num_dense:].astype(jnp.int32),
            jnp.asarray(self.vocab_sizes, jnp.int32),
        )

    # -- sparse-embedding-update protocol (train/sparse_embed.py) ----
    # The forward is split at the GATHER so a training step can take
    # gradients w.r.t. the gathered [B, F, D] rows instead of the
    # dense [F, V, D] tables — the dense table cotangent (and the
    # dense optimizer sweep it forces) is the criteo step's dominant
    # HBM traffic.

    def split_embeddings(self, params: dict) -> tuple[dict, dict]:
        """(dense leaves, embedding-table leaves)."""
        tables = {k: v for k, v in params.items() if k.endswith("_tables")}
        dense = {k: v for k, v in params.items() if k not in tables}
        return dense, tables

    @staticmethod
    def merge_embeddings(dense: dict, tables: dict) -> dict:
        return {**dense, **tables}

    def gather_rows(self, tables: dict, ids: jax.Array) -> dict:
        """Per-occurrence embedding rows for every table,
        ``{name: [B, F, D_k]}``."""
        feat_idx = jnp.arange(self.num_categorical)[None, :]
        return {k: t[feat_idx, ids] for k, t in tables.items()}

    def apply_from_rows(
        self, dense_params: dict, rows: dict, x: jax.Array
    ) -> jax.Array:
        """Forward from pre-gathered embedding rows — identical math
        to :meth:`apply`, which delegates here."""
        dense = x[:, : self.num_dense]
        wide_cat = rows["wide_tables"]  # [B, F, K]
        deep_emb = rows["deep_tables"]  # [B, F, D]

        wide_logits = (
            dense @ dense_params["wide_dense"]
            + dense_params["wide_bias"]
            + jnp.sum(wide_cat, axis=1)
        )

        cdt = jnp.dtype(self.compute_dtype)
        h = jnp.concatenate(
            [dense, deep_emb.reshape(dense.shape[0], -1)], axis=1
        ).astype(cdt)
        n_hidden = len(self.hidden_dims)
        for i in range(n_hidden):
            layer = dense_params[f"deep_{i}"]
            h = jax.nn.relu(
                h @ layer["kernel"].astype(cdt)
                + layer["bias"].astype(cdt)
            )
        out = dense_params[f"deep_{n_hidden}"]
        deep_logits = h.astype(jnp.float32) @ out["kernel"] + out["bias"]

        return wide_logits + deep_logits

    def apply(self, params: dict, x: jax.Array) -> jax.Array:
        dense_params, tables = self.split_embeddings(params)
        rows = self.gather_rows(tables, self.embedding_ids(x))
        return self.apply_from_rows(dense_params, rows, x)

    def param_shardings(self, layout=None) -> dict:
        """PartitionSpec pytree matching ``init``'s structure: tables
        sharded on the vocab dim, the rest replicated. Axis names come
        from the shared ``SpecLayout``."""
        from mlapi_tpu.parallel import SpecLayout

        lo = layout or SpecLayout()
        specs = {
            "wide_dense": lo.replicated(),
            "wide_bias": lo.replicated(),
            "wide_tables": lo.embedding_tables(),
            "deep_tables": lo.embedding_tables(),
        }
        for i in range(len(self.hidden_dims) + 1):
            specs[f"deep_{i}"] = {
                "kernel": lo.replicated(), "bias": lo.replicated()
            }
        return specs

    def optimizer_partitions(self, params: dict) -> dict:
        """Label pytree for ``train.optimizers.partitioned``: the two
        embedding stacks take the rowwise-AdaGrad path (the Wide&Deep
        paper's own AdaGrad recipe; dense Adam moments over [F, V, D]
        are the step's HBM bottleneck), everything else the base
        optimizer."""
        return {
            k: jax.tree.map(
                lambda _, lab=(
                    "embedding" if k.endswith("_tables") else "default"
                ): lab,
                v,
            )
            for k, v in params.items()
        }
