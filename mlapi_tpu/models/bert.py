"""BERT encoder + [CLS] classifier — config 5 of the ladder
(``BASELINE.json:11``: BERT-base on SST-2, batched serving).

A from-scratch TPU-first implementation (no torch, no HF runtime):

- Params are one flat pytree; attention is explicit ``einsum`` over a
  ``[B, L, heads, head_dim]`` layout — XLA fuses QKV projections and
  keeps the big matmuls MXU-shaped.
- Hidden compute in bfloat16 (params/f32 logits/layernorm stats in
  f32), the standard TPU mixed-precision recipe.
- Tensor-parallel layout via ``param_shardings``: QKV/FFN-up kernels
  column-sharded over the ``model`` axis, attention-out/FFN-down
  row-sharded (the Megatron pairing: one all-reduce per block,
  inserted by GSPMD), word embeddings sharded over the vocab dim.
- Weights can be imported from a HuggingFace torch
  ``BertForSequenceClassification`` checkpoint via
  ``params_from_hf_torch`` (logit-parity-tested against torch; SURVEY
  §7 step 7's "silent-accuracy killer" guard).

Dropout is omitted: serving is deterministic, and the ladder's
fine-tuning runs are short enough that it isn't the difference that
matters. (Add stochastic depth later if config 5 fine-tuning
regresses.)

Long-context: ``attention_impl="ring"`` swaps in sequence-parallel
ring attention (``mlapi_tpu.ops.ring_attention``) with the sequence
sharded over the mesh's ``seq`` axis — attention is the only
cross-token op, so the rest of the encoder partitions along L under
GSPMD with no code change.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mlapi_tpu.models import register_model

BERT_PRESETS = {
    # name: (vocab, hidden, layers, heads, intermediate, max_positions)
    "bert-base-uncased": (30522, 768, 12, 12, 3072, 512),
    "bert-large-uncased": (30522, 1024, 24, 16, 4096, 512),
    "bert-tiny": (30522, 128, 2, 2, 512, 512),
}

_LN_EPS = 1e-12  # BERT's layernorm epsilon


def _layer_norm(x, scale, bias):
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + _LN_EPS) * scale + bias


@register_model("bert_classifier")
@dataclass(frozen=True)
class BertClassifier:
    """BERT encoder with a pooled-[CLS] classification head."""

    input_kind = "text"  # serving: token ids, not tabular features

    num_classes: int = 2
    bert_preset: str | None = None
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_positions: int = 512
    type_vocab_size: int = 2
    compute_dtype: str = "bfloat16"
    # "full"  = whole-sequence softmax attention on each device;
    # "flash" = fused Pallas kernel (mlapi_tpu.ops.pallas): scores/
    #           softmax/PV stay in VMEM, no [L, L] HBM traffic;
    # "ring"  = sequence-parallel ring attention (mlapi_tpu.ops) with
    #           L sharded over ``mesh``'s ``seq_axis`` (long context).
    attention_impl: str = "full"
    mesh: object | None = None
    seq_axis: str = "seq"

    def __post_init__(self):
        if self.attention_impl not in ("full", "flash", "ring"):
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}"
            )
        if self.attention_impl == "ring" and self.mesh is None:
            raise ValueError(
                "attention_impl='ring' needs a mesh with a "
                f"{self.seq_axis!r} axis"
            )
        if self.bert_preset is not None:
            v, h, l, a, i, p = BERT_PRESETS[self.bert_preset]
            for name, val in [
                ("vocab_size", v), ("hidden_size", h), ("num_layers", l),
                ("num_heads", a), ("intermediate_size", i),
                ("max_positions", p),
            ]:
                object.__setattr__(self, name, val)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> dict:
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        keys = iter(jax.random.split(rng, 6 + 10 * self.num_layers))

        def dense(k, shape, scale=0.02):
            return {
                "kernel": scale * jax.random.normal(k, shape),
                "bias": jnp.zeros((shape[-1],)),
            }

        params = {
            "embeddings": {
                "word": 0.02 * jax.random.normal(next(keys), (v, h)),
                "position": 0.02 * jax.random.normal(
                    next(keys), (self.max_positions, h)
                ),
                "token_type": 0.02 * jax.random.normal(
                    next(keys), (self.type_vocab_size, h)
                ),
                "ln_scale": jnp.ones((h,)),
                "ln_bias": jnp.zeros((h,)),
            },
            "pooler": dense(next(keys), (h, h)),
            "classifier": dense(next(keys), (h, self.num_classes)),
        }
        for n in range(self.num_layers):
            params[f"layer_{n}"] = {
                "q": dense(next(keys), (h, h)),
                "k": dense(next(keys), (h, h)),
                "v": dense(next(keys), (h, h)),
                "attn_out": dense(next(keys), (h, h)),
                "ln1_scale": jnp.ones((h,)),
                "ln1_bias": jnp.zeros((h,)),
                "ffn_up": dense(next(keys), (h, i)),
                "ffn_down": dense(next(keys), (i, h)),
                "ln2_scale": jnp.ones((h,)),
                "ln2_bias": jnp.zeros((h,)),
            }
        return jax.tree.map(lambda a: a.astype(jnp.float32), params)

    # ------------------------------------------------------------------
    def encode(self, params: dict, token_ids, attention_mask=None):
        """Token ids ``[B, L]`` → hidden states ``[B, L, H]``."""
        cdt = jnp.dtype(self.compute_dtype)
        b, l = token_ids.shape
        if attention_mask is None:
            attention_mask = (token_ids != 0).astype(jnp.int32)

        emb = params["embeddings"]
        x = (
            emb["word"][token_ids]
            + emb["position"][jnp.arange(l)][None, :, :]
            + emb["token_type"][jnp.zeros_like(token_ids)]
        )
        x = _layer_norm(x, emb["ln_scale"], emb["ln_bias"])

        from mlapi_tpu.ops import full_attention, ring_self_attention

        key_mask = attention_mask.astype(jnp.float32)

        nh, hd = self.num_heads, self.head_dim
        for n in range(self.num_layers):
            layer = params[f"layer_{n}"]
            xc = x.astype(cdt)

            def proj(p):
                return (
                    xc @ p["kernel"].astype(cdt) + p["bias"].astype(cdt)
                ).reshape(b, l, nh, hd)

            q, k, v = proj(layer["q"]), proj(layer["k"]), proj(layer["v"])
            if self.attention_impl == "ring":
                ctx = ring_self_attention(
                    self.mesh, q, k, v, key_mask,
                    seq_axis=self.seq_axis, head_axis="model",
                )
            elif self.attention_impl == "flash":
                from mlapi_tpu.ops.pallas import flash_attention

                # Interpreter off the TPU: correctness-testable
                # anywhere, compiled Mosaic kernel on the real chip.
                ctx = flash_attention(
                    q, k, v, key_mask,
                    interpret=jax.default_backend() != "tpu",
                )
            else:
                ctx = full_attention(q, k, v, key_mask)
            ctx = ctx.reshape(b, l, -1)
            attn = ctx @ layer["attn_out"]["kernel"].astype(cdt) + layer[
                "attn_out"
            ]["bias"].astype(cdt)
            x = _layer_norm(
                x + attn.astype(jnp.float32),
                layer["ln1_scale"], layer["ln1_bias"],
            )

            xc = x.astype(cdt)
            up = xc @ layer["ffn_up"]["kernel"].astype(cdt) + layer["ffn_up"][
                "bias"
            ].astype(cdt)
            up = jax.nn.gelu(up.astype(jnp.float32), approximate=False).astype(cdt)
            down = up @ layer["ffn_down"]["kernel"].astype(cdt) + layer[
                "ffn_down"
            ]["bias"].astype(cdt)
            x = _layer_norm(
                x + down.astype(jnp.float32),
                layer["ln2_scale"], layer["ln2_bias"],
            )
        return x

    def apply(self, params: dict, token_ids, attention_mask=None):
        """Token ids ``[B, L]`` → classification logits ``[B, K]``
        (HF ``BertForSequenceClassification`` semantics: tanh pooler
        over the [CLS] hidden state, then the classifier head)."""
        hidden = self.encode(params, token_ids, attention_mask)
        cls = hidden[:, 0, :]
        pooled = jnp.tanh(
            cls @ params["pooler"]["kernel"] + params["pooler"]["bias"]
        )
        return pooled @ params["classifier"]["kernel"] + params["classifier"]["bias"]

    # ------------------------------------------------------------------
    def param_shardings(self, layout=None) -> dict:
        """Megatron-style TP layout; axis names come from the shared
        ``SpecLayout`` (mesh renames touch one place)."""
        from mlapi_tpu.parallel import SpecLayout

        lo = layout or SpecLayout()
        col = {"kernel": lo.attn_qkv(), "bias": lo.bias_col()}
        row = {"kernel": lo.attn_out(), "bias": lo.replicated()}
        rep = lo.replicated()
        specs = {
            "embeddings": {
                "word": lo.embedding_rows(),  # vocab-sharded
                "position": rep,
                "token_type": rep,
                "ln_scale": rep,
                "ln_bias": rep,
            },
            "pooler": {"kernel": rep, "bias": rep},
            "classifier": {"kernel": rep, "bias": rep},
        }
        for n in range(self.num_layers):
            specs[f"layer_{n}"] = {
                "q": dict(col), "k": dict(col), "v": dict(col),
                "attn_out": dict(row),
                "ln1_scale": rep, "ln1_bias": rep,
                "ffn_up": dict(col),
                "ffn_down": dict(row),
                "ln2_scale": rep, "ln2_bias": rep,
            }
        return specs


# ----------------------------------------------------------------------
def params_from_hf_torch(torch_model, model: BertClassifier) -> dict:
    """Convert a HuggingFace torch ``BertForSequenceClassification``
    state dict into this model's param pytree.

    torch ``nn.Linear`` stores ``weight`` as ``[out, in]`` — every
    kernel is transposed on the way in (the classic silent-accuracy
    killer; guarded by the logit-parity test in
    ``tests/test_bert.py``).
    """
    import numpy as np

    sd = {k: np.asarray(v.detach().cpu().numpy()) for k, v in
          torch_model.state_dict().items()}

    def lin(prefix):
        return {
            "kernel": jnp.asarray(sd[f"{prefix}.weight"].T),
            "bias": jnp.asarray(sd[f"{prefix}.bias"]),
        }

    e = "bert.embeddings"
    params = {
        "embeddings": {
            "word": jnp.asarray(sd[f"{e}.word_embeddings.weight"]),
            "position": jnp.asarray(sd[f"{e}.position_embeddings.weight"]),
            "token_type": jnp.asarray(sd[f"{e}.token_type_embeddings.weight"]),
            "ln_scale": jnp.asarray(sd[f"{e}.LayerNorm.weight"]),
            "ln_bias": jnp.asarray(sd[f"{e}.LayerNorm.bias"]),
        },
        "pooler": lin("bert.pooler.dense"),
        "classifier": lin("classifier"),
    }
    for n in range(model.num_layers):
        p = f"bert.encoder.layer.{n}"
        params[f"layer_{n}"] = {
            "q": lin(f"{p}.attention.self.query"),
            "k": lin(f"{p}.attention.self.key"),
            "v": lin(f"{p}.attention.self.value"),
            "attn_out": lin(f"{p}.attention.output.dense"),
            "ln1_scale": jnp.asarray(sd[f"{p}.attention.output.LayerNorm.weight"]),
            "ln1_bias": jnp.asarray(sd[f"{p}.attention.output.LayerNorm.bias"]),
            "ffn_up": lin(f"{p}.intermediate.dense"),
            "ffn_down": lin(f"{p}.output.dense"),
            "ln2_scale": jnp.asarray(sd[f"{p}.output.LayerNorm.weight"]),
            "ln2_bias": jnp.asarray(sd[f"{p}.output.LayerNorm.bias"]),
        }
    return jax.tree.map(lambda a: a.astype(jnp.float32), params)
