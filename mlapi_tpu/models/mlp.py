"""MLP classifier (Flax) — config 3 of the ladder
(``BASELINE.json:9``: 2-layer MLP on Fashion-MNIST, data-parallel).

Hidden matmuls run in bfloat16 on TPU (MXU-native) with float32
params and a float32 final layer/softmax — the standard mixed
precision recipe; the loss stays numerically stable while the FLOPs
ride the systolic array.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import flax.linen as nn
import jax
import jax.numpy as jnp

from mlapi_tpu.models import register_model


class _MLP(nn.Module):
    hidden_dims: tuple[int, ...]
    num_classes: int
    compute_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        for i, width in enumerate(self.hidden_dims):
            x = nn.Dense(width, dtype=self.compute_dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        # Final projection + logits in f32 for a stable softmax/CE.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="out")(
            x.astype(jnp.float32)
        )


@register_model("mlp")
@dataclass(frozen=True)
class MLPClassifier:
    """Functional wrapper: ``init(rng) -> params``, ``apply(params, x)``."""

    num_features: int
    num_classes: int
    hidden_dims: tuple[int, ...] = (256, 128)
    compute_dtype: str = "bfloat16"

    def __post_init__(self):
        # Configs arriving from JSON/YAML carry lists; params must stay
        # hashable (frozen dataclass) for jit-cache keying.
        object.__setattr__(self, "hidden_dims", tuple(self.hidden_dims))

    @property
    def _module(self) -> _MLP:
        return _MLP(
            hidden_dims=tuple(self.hidden_dims),
            num_classes=self.num_classes,
            compute_dtype=jnp.dtype(self.compute_dtype),
        )

    def init(self, rng: jax.Array) -> dict:
        dummy = jnp.zeros((1, self.num_features), jnp.float32)
        return self._module.init(rng, dummy)["params"]

    def apply(self, params: dict, x: jax.Array) -> jax.Array:
        return self._module.apply({"params": params}, x)
