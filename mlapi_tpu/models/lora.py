"""LoRA (low-rank adaptation) fine-tuning for any model family.

``LoraModel(inner, rank=r)`` trains two small matrices per target
kernel — ``a [in, r]`` and ``b [r, out]`` — while the base weights
stay frozen (``stop_gradient`` in the merge + a masked optimizer, so
base weights get no gradient math and NO optimizer moments: for adamw
that is the difference between 3x and ~1.01x parameter memory during
fine-tuning, which is what lets a big pretrained model fine-tune on
hardware that could only just serve it).

TPU-first shape discipline: the merge ``W_eff = W + (alpha/r)·a@b``
happens INSIDE the traced step, so the train step stays one fused XLA
program with static shapes; ``b`` initializes to zero, so step 0 is
byte-identical to the base model (the standard LoRA guarantee).

Single-tenant serving never sees LoRA: ``merge_params`` folds the
adaptation back into a plain parameter tree that checkpoints and
serves through the unchanged engines. MANY-tenant serving keeps the
base un-merged instead and applies per-request adapters from a
device slot pool (``serving/adapter_store.py``); the serving-side
helpers at the bottom of this module — :func:`lora_apply` inside the
traced blocks, :func:`export_adapter` / :func:`merge_adapter` at the
edges — carry that path.

The reference (`/root/reference`) has no fine-tuning story at all —
this exists for the framework's own pretrained-model scale.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

# Kernel-holding nodes adapted by default: every dense projection the
# decoder/encoder families register under these names. GPT/BERT store
# them as ``{"kernel", "bias"}`` dicts; Llama as bare 2-D arrays —
# both shapes are matched.
DEFAULT_TARGETS = (
    "qkv", "attn_out", "ffn_up", "ffn_down",              # gpt / bert
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",   # llama
)


def _kernel_of(node):
    """The 2-D kernel held by a target node, or None."""
    if isinstance(node, dict) and getattr(
        node.get("kernel"), "ndim", 0
    ) == 2:
        return node["kernel"]
    if getattr(node, "ndim", 0) == 2:
        return node
    return None


def _walk_targets(tree, targets, path=()):
    """Yield (path, kernel) for every adapted kernel."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            kernel = _kernel_of(v) if k in targets else None
            if kernel is not None:
                yield path + (k,), kernel
            else:
                yield from _walk_targets(v, targets, path + (k,))


def _get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


@dataclass(frozen=True)
class LoraModel:
    """Low-rank adapter over any registered model family."""

    inner: object
    rank: int = 8
    alpha: float = 16.0
    targets: tuple = DEFAULT_TARGETS

    def __getattr__(self, name):
        return getattr(self.inner, name)

    @property
    def scale(self) -> float:
        return self.alpha / self.rank

    # -- parameters -----------------------------------------------------
    def init(self, rng, base_params=None):
        """``{"base": <inner params>, "lora": {<joined path>: {a, b}}}``.
        ``base_params`` lets a pretrained checkpoint seed the frozen
        part; ``b`` starts at zero so the adapted model initially
        equals the base exactly."""
        base = self.inner.init(rng) if base_params is None else base_params
        lora = {}
        # Deterministic per-adapter streams: fold by enumeration order
        # (dict order is construction order, which init() fixes) —
        # never by Python string hash, which is salted per process.
        for i, (path, kernel) in enumerate(
            _walk_targets(base, self.targets)
        ):
            d_in, d_out = kernel.shape
            key = jax.random.fold_in(rng, i)
            lora["/".join(path)] = {
                "a": (1.0 / d_in**0.5)
                * jax.random.normal(key, (d_in, self.rank)),
                "b": jnp.zeros((self.rank, d_out)),
            }
        if not lora:
            raise ValueError(
                f"no LoRA targets found in {type(self.inner).__name__} "
                f"params (targets={self.targets})"
            )
        return {"base": base, "lora": lora}

    def merge_params(self, params, *, stop_base_gradient: bool = False):
        """Fold the adapters into a PLAIN inner-model tree:
        ``W + (alpha/rank)·a@b`` per target. Traced (used inside the
        train step) or eager (export for serving — the result
        checkpoints and serves like any base-model tree)."""
        base, lora = params["base"], params["lora"]
        if stop_base_gradient:
            base = jax.lax.stop_gradient(base)
        merged = jax.tree.map(lambda x: x, base)  # fresh containers

        for joined, ab in lora.items():
            path = tuple(joined.split("/"))
            parent = _get(merged, path[:-1])
            node = parent[path[-1]]
            w = _kernel_of(node)
            delta = (self.scale * ab["a"] @ ab["b"]).astype(w.dtype)
            if isinstance(node, dict):
                node = dict(node)
                node["kernel"] = w + delta
                parent[path[-1]] = node
            else:
                parent[path[-1]] = w + delta
        return merged

    # -- model protocol -------------------------------------------------
    def apply(self, params, *args, **kwargs):
        return self.inner.apply(
            self.merge_params(params, stop_base_gradient=True),
            *args, **kwargs,
        )

    def generate(self, params, prompt_ids, **kwargs):
        return self.inner.generate(
            self.merge_params(params), prompt_ids, **kwargs
        )

    def trainable_mask(self, params) -> dict:
        """Pytree of bools matching ``params``: only the adapters
        train. The train loop hands this to ``optax.masked`` so the
        frozen base gets no update AND no optimizer state."""
        return {
            "base": jax.tree.map(lambda _: False, params["base"]),
            "lora": jax.tree.map(lambda _: True, params["lora"]),
        }

    def param_shardings(self, layout=None) -> dict:
        """Adapters are tiny — replicate them; the base keeps the
        inner model's layout."""
        from mlapi_tpu.parallel import SpecLayout

        lo = layout or SpecLayout()
        if not hasattr(self.inner, "param_shardings"):
            raise NotImplementedError(
                f"{type(self.inner).__name__} has no param_shardings"
            )
        # eval_shape: tree structure only, no parameter allocation —
        # the base may be large.
        probe = jax.eval_shape(
            lambda: self.inner.init(jax.random.key(0))
        )
        lora = {
            "/".join(p): {"a": lo.replicated(), "b": lo.replicated()}
            for p, _ in _walk_targets(probe, self.targets)
        }
        return {
            "base": self.inner.param_shardings(layout),
            "lora": lora,
        }


# -- serving-side application (many-adapter slot pool) -----------------
def lora_apply(layer, target, x, y):
    """``y + adapter delta`` for a block matmul ``y = x @ W[target]``
    when the layer dict carries serving adapter state, else ``y``
    ITSELF — the presence check is a static Python branch at trace
    time, so a build with no adapter traffic traces byte-identical
    programs (no masked zero-delta ops riding every batch).

    The state (installed by ``AdapterSlots.batch_params``) is
    ``layer["lora"] = {target: {"a": [S, d_in, r], "b": [S, r,
    d_out]}, ...}`` plus ONE marker: scalar ``"slot"`` (grouped batch
    — a single tenant, one plain ``x @ A @ B`` per target) or int32
    ``"rows"`` ``[B]`` (mixed tenants — the gathered BGMV path,
    ``ops/bgmv.py``; base rows index the all-zero NULL slot 0)."""
    lora = layer.get("lora") if isinstance(layer, dict) else None
    if lora is None:
        return y
    ab = lora.get(target)
    if ab is None:
        return y
    a, b = ab["a"], ab["b"]
    rows = lora.get("rows")
    if rows is not None:
        from mlapi_tpu.ops.bgmv import bgmv

        return y + bgmv(x, a, b, rows)
    slot = lora["slot"]
    return y + (x @ a[slot].astype(x.dtype)) @ b[slot].astype(x.dtype)


def export_adapter(lora_params: dict, scale: float) -> dict:
    """A trained adapter tree (``params["lora"]``: ``{"layer_0/qkv":
    {a, b}}`` joined paths) → the CANONICAL serving payload
    ``{layer: {target: {a, b}}}`` with ``b`` pre-scaled by
    alpha/rank, so the serving delta is exactly ``x @ a @ b`` and no
    scale rides the wire, the store, or the slot pool."""
    import numpy as np

    out: dict = {}
    for joined, ab in lora_params.items():
        path = joined.split("/")
        out.setdefault(path[0], {})[path[-1]] = {
            "a": np.asarray(ab["a"]),
            "b": np.asarray(scale * ab["b"]),
        }
    return out


def merge_adapter(params: dict, payload: dict) -> dict:
    """Eagerly fold a serving payload into a fresh plain params tree:
    ``W + a @ b`` per target (``b`` already carries the scale). The
    merged-weights REFERENCE for the slot-path token-identity pins
    (tests + bench) — and the escape hatch for serving one tenant on
    an engine built without adapter slots."""
    merged = jax.tree.map(lambda x: x, params)  # fresh containers
    for ln, layer in payload.items():
        for target, ab in layer.items():
            node = merged[ln][target]
            w = _kernel_of(node)
            delta = (jnp.asarray(ab["a"]) @ jnp.asarray(ab["b"])).astype(
                w.dtype
            )
            if isinstance(node, dict):
                node = dict(node)
                node["kernel"] = w + delta
                merged[ln][target] = node
            else:
                merged[ln][target] = w + delta
    return merged
