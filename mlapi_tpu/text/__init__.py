"""Text pipeline: tokenizers for the BERT serving/training path."""

from mlapi_tpu.text.tokenizer import (  # noqa: F401
    HashTokenizer,
    WordPieceTokenizer,
    load_tokenizer,
)
