"""Text pipeline: tokenizers for the BERT and GPT serving/training
paths."""

from mlapi_tpu.text.tokenizer import (  # noqa: F401
    ByteTokenizer,
    HashTokenizer,
    WordPieceTokenizer,
    load_tokenizer,
)
