"""Tokenizers — the text ingestion half of the BERT serving path.

Two implementations behind one interface (``encode`` → fixed-length
ids + mask):

- :class:`WordPieceTokenizer` — BERT's actual scheme, implemented
  from scratch: basic (lowercase, punctuation-splitting) tokenization
  followed by greedy longest-match-first wordpiece with ``##``
  continuations. Reads the standard ``vocab.txt`` (one token per
  line) when present — e.g. dropped at ``data/sst2/vocab.txt`` or
  any HF ``bert-base-uncased`` vocab file.
- :class:`HashTokenizer` — air-gapped fallback: word → stable hash →
  id. No vocab file needed, deterministic across runs/processes
  (crc32, not Python's salted ``hash``). Sufficient for training a
  model end-to-end on synthetic text; NOT compatible with pretrained
  BERT weights (which assume the real WordPiece vocab).
"""

from __future__ import annotations

import unicodedata
import zlib
from pathlib import Path

import numpy as np

PAD, CLS, SEP, UNK = "[PAD]", "[CLS]", "[SEP]", "[UNK]"


def _basic_tokens(text: str) -> list[str]:
    """Lowercase, strip accents, split on whitespace and punctuation
    (each punctuation char its own token) — BERT's BasicTokenizer."""
    text = unicodedata.normalize("NFD", text.lower())
    out: list[str] = []
    word: list[str] = []
    for ch in text:
        cat = unicodedata.category(ch)
        if cat == "Mn":  # combining accent
            continue
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif cat.startswith("P") or cat in ("Sm", "Sc", "Sk", "So"):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


class _Base:
    pad_id: int
    cls_id: int
    sep_id: int

    def token_ids(self, text: str) -> list[int]:
        raise NotImplementedError

    def fingerprint(self) -> dict:
        """Identity of this tokenization scheme, recorded in
        checkpoints so serving can refuse to pair a model with a
        different tokenizer than it was trained with (silent id skew
        = confident garbage predictions)."""
        raise NotImplementedError

    def encode(
        self, text: str, max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """``[CLS] tokens [SEP]`` padded/truncated to ``max_len`` →
        (ids int32 [max_len], mask int32 [max_len])."""
        body = self.token_ids(text)[: max_len - 2]
        ids = [self.cls_id, *body, self.sep_id]
        n = len(ids)
        ids = ids + [self.pad_id] * (max_len - n)
        mask = [1] * n + [0] * (max_len - n)
        return np.asarray(ids, np.int32), np.asarray(mask, np.int32)

    def decode(self, ids) -> str:
        """Best-effort ids → text (generation output). Subclasses with
        a real vocab detokenize; schemes without one (hashing) render
        placeholders — generation then needs a vocab-bearing tokenizer."""
        return " ".join(f"<{int(i)}>" for i in ids)


class WordPieceTokenizer(_Base):
    def __init__(self, vocab: list[str], max_chars_per_word: int = 100):
        self.vocab = list(vocab)
        self._index = {t: i for i, t in enumerate(self.vocab)}
        for required in (PAD, CLS, SEP, UNK):
            if required not in self._index:
                raise ValueError(f"vocab missing {required}")
        self.pad_id = self._index[PAD]
        if self.pad_id != 0:
            # Models mask attention with ``ids != 0`` (the standard
            # BERT vocab puts [PAD] at index 0); a vocab violating
            # that would silently attend padding.
            raise ValueError(
                f"[PAD] must be vocab index 0, found at {self.pad_id}"
            )
        self.cls_id = self._index[CLS]
        self.sep_id = self._index[SEP]
        self.unk_id = self._index[UNK]
        self.max_chars_per_word = max_chars_per_word

    @classmethod
    def from_vocab_file(cls, path: str | Path) -> "WordPieceTokenizer":
        lines = Path(path).read_text(encoding="utf-8").splitlines()
        return cls([ln.rstrip("\n") for ln in lines if ln.strip()])

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def fingerprint(self) -> dict:
        import hashlib

        digest = hashlib.sha256(
            "\n".join(self.vocab).encode("utf-8")
        ).hexdigest()[:16]
        return {
            "kind": "wordpiece",
            "vocab_size": self.vocab_size,
            "vocab_sha256": digest,
        }

    def token_ids(self, text: str) -> list[int]:
        ids: list[int] = []
        for word in _basic_tokens(text):
            if len(word) > self.max_chars_per_word:
                ids.append(self.unk_id)
                continue
            # Greedy longest-match-first wordpiece.
            start = 0
            pieces: list[int] = []
            while start < len(word):
                end = len(word)
                found = None
                while start < end:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    if sub in self._index:
                        found = self._index[sub]
                        break
                    end -= 1
                if found is None:
                    pieces = [self.unk_id]
                    break
                pieces.append(found)
                start = end
            ids.extend(pieces)
        return ids


    def decode(self, ids) -> str:
        """WordPiece detokenization: ``##`` continuation pieces join
        their predecessor; specials are dropped."""
        words: list[str] = []
        specials = {self.pad_id, self.cls_id, self.sep_id}
        for i in ids:
            i = int(i)
            if i in specials or not 0 <= i < len(self.vocab):
                continue
            tok = self.vocab[i]
            if tok.startswith("##") and words:
                words[-1] += tok[2:]
            else:
                words.append(tok)
        return " ".join(words)


class HashTokenizer(_Base):
    """word → crc32 hash → id in [4, vocab_size)."""

    pad_id, cls_id, sep_id, unk_id = 0, 1, 2, 3
    _RESERVED = 4

    def __init__(self, vocab_size: int = 30522):
        if vocab_size <= self._RESERVED:
            raise ValueError("vocab_size too small")
        self.vocab_size = vocab_size

    def token_ids(self, text: str) -> list[int]:
        span = self.vocab_size - self._RESERVED
        return [
            self._RESERVED + (zlib.crc32(w.encode("utf-8")) % span)
            for w in _basic_tokens(text)
        ]

    def fingerprint(self) -> dict:
        return {"kind": "hash", "vocab_size": self.vocab_size}


class ByteTokenizer(_Base):
    """Byte-level ids (+4 reserved specials) — lossless round trip
    with no vocab file; the natural pairing for the ``gpt_lm`` demo
    (vocab_size 260)."""

    pad_id, cls_id, sep_id, unk_id = 0, 1, 2, 3
    _RESERVED = 4
    vocab_size = 256 + _RESERVED

    def token_ids(self, text: str) -> list[int]:
        return [self._RESERVED + b for b in text.encode("utf-8")]

    def decode(self, ids) -> str:
        # Best-effort both ways: drop specials below the byte range
        # AND ids past it (an untied LM head can emit ids up to the
        # model's vocab_size, which may exceed 260).
        return bytes(
            int(i) - self._RESERVED
            for i in ids
            if self._RESERVED <= int(i) < self._RESERVED + 256
        ).decode("utf-8", "replace")

    def fingerprint(self) -> dict:
        return {"kind": "bytes", "vocab_size": self.vocab_size}


def _find_vocab_file(data_dir: str | None = None) -> Path | None:
    import os

    for root in (data_dir, os.environ.get("MLAPI_TPU_DATA_DIR"), "data"):
        if root is None:
            continue
        p = Path(root) / "bert" / "vocab.txt"
        if p.exists():
            return p
    return None


def load_tokenizer(vocab_size: int = 30522, data_dir: str | None = None):
    """The real WordPiece vocab if a ``vocab.txt`` is on disk, else
    the hash fallback. Searched: ``$MLAPI_TPU_DATA_DIR/bert/vocab.txt``,
    ``data/bert/vocab.txt``."""
    p = _find_vocab_file(data_dir)
    if p is not None:
        return WordPieceTokenizer.from_vocab_file(p)
    return HashTokenizer(vocab_size)


def tokenizer_from_fingerprint(fp: dict, data_dir: str | None = None):
    """Rebuild EXACTLY the tokenizer a checkpoint was trained with, or
    refuse. The serving environment must not silently substitute a
    different tokenization scheme (ids would skew, predictions would
    be confident garbage)."""
    kind = fp.get("kind")
    if kind == "hash":
        return HashTokenizer(fp["vocab_size"])
    if kind == "bytes":
        return ByteTokenizer()
    if kind == "wordpiece":
        p = _find_vocab_file(data_dir)
        if p is None:
            raise FileNotFoundError(
                "checkpoint was trained with a WordPiece vocab "
                f"(sha256 {fp.get('vocab_sha256')}); place the same "
                "vocab.txt at $MLAPI_TPU_DATA_DIR/bert/ or data/bert/"
            )
        tok = WordPieceTokenizer.from_vocab_file(p)
        got = tok.fingerprint()
        if got.get("vocab_sha256") != fp.get("vocab_sha256"):
            raise ValueError(
                f"vocab.txt at {p} (sha256 {got.get('vocab_sha256')}) does "
                f"not match the checkpoint's training vocab "
                f"(sha256 {fp.get('vocab_sha256')})"
            )
        return tok
    raise ValueError(f"unknown tokenizer fingerprint {fp!r}")
