"""mlapi_tpu — a TPU-native training-and-serving framework.

Re-implements the capabilities of the reference microservice
(``achbogga/mlAPI``: train a linear classifier on CSV data, persist it,
serve schema-validated JSON predictions and CSV uploads over HTTP —
see ``/root/reference/main.py`` and ``Logistic Regression.ipynb``)
as an idiomatic JAX/XLA framework:

(Modules land incrementally along the SURVEY §7 build plan; at any
given commit some of the below may not exist yet.)

- ``models``     — functional model zoo (linear, MLP, Wide&Deep, BERT).
- ``train``      — optax training loops; data-parallel via ``jax.jit`` +
                   ``NamedSharding`` over a device mesh (gradients
                   all-reduced over ICI by XLA-inserted collectives).
- ``parallel``   — mesh construction and canonical PartitionSpec layouts.
- ``checkpoint`` — versioned, atomic, pickle-free checkpoints
                   (replaces the reference's ``pickle.load`` handoff,
                   ``main.py:19``).
- ``serving``    — an asyncio HTTP/ASGI serving stack with an
                   inference micro-batcher in front of a jit-compiled
                   forward pass (replaces FastAPI/uvicorn, which the
                   reference used off-the-shelf).
- ``datasets``   — loaders for the config ladder (Iris → MNIST →
                   Fashion-MNIST → Criteo → SST-2) with deterministic
                   synthetic fallbacks for air-gapped environments.
- ``ops``        — Pallas TPU kernels for hot ops.
"""

__version__ = "0.1.0"
