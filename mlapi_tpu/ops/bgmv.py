"""Batched gathered matrix-vector products for mixed-tenant LoRA.

The serving slot pool (``serving/adapter_store.py``) holds every
resident tenant's ``(A, B)`` pair stacked along a leading slot axis.
A batch where every row shares one tenant applies its adapter as a
plain ``x @ A @ B`` (the grouped fast path — no gather at all); a
MIXED batch instead gathers each row's operands by slot index inside
the traced step, so one compiled program serves any tenant mix at
the same shapes. This is the BGMV formulation from the multi-tenant
LoRA serving line (S-LoRA / Punica): rank is tiny, so the gathered
matmuls are bandwidth-bound on the A/B reads — which the slot gather
keeps at exactly one pair per row.

A fused Pallas tile for the two einsums (gather + both contractions
in one VMEM-resident kernel) is the noted follow-up; at serving
ranks (r ≤ 64) the XLA einsum pair is already within the decode
step's noise floor, and correctness — token-identity with the merged
reference — is what this PR pins.
"""

from __future__ import annotations

import jax.numpy as jnp


def bgmv(x, a, b, rows):
    """Per-row low-rank delta ``x[i] @ a[rows[i]] @ b[rows[i]]``.

    ``x`` is ``[B, ..., d_in]`` (decode passes ``[B, L, d_in]``),
    ``a`` is the slot pool ``[S, d_in, r]``, ``b`` is ``[S, r,
    d_out]``, and ``rows`` is int32 ``[B]`` — slot 0 is the NULL
    slot, all-zero by construction, so base-model rows in a mixed
    batch pay the same two matmuls and gather an exactly-zero delta
    (uniform shapes beat a branchy mask on TPU)."""
    a_g = a[rows].astype(x.dtype)      # [B, d_in, r]
    b_g = b[rows].astype(x.dtype)      # [B, r, d_out]
    t = jnp.einsum("b...d,bdr->b...r", x, a_g)
    return jnp.einsum("b...r,bro->b...o", t, b_g)
