"""Speculative decoding: a small DRAFT model proposes k tokens, the
TARGET model verifies them all in ONE block forward.

Decode on TPU is one full target-weight read per token; verification
reads the target weights once per ROUND of up to k+1 tokens, so with
an in-domain draft the target's HBM bill drops by the mean accepted
length. Greedy-exact: the emitted stream is byte-identical to plain
target-only greedy decoding (accepted drafts ARE the target's argmax;
the round's last token is the target's own argmax after them) — the
guarantee the tests pin, including with draft == target where every
round must accept the full k+1.

TPU-first mechanics worth noting:

- **Rollback is free.** Rejected draft positions leave stale K/V in
  the target cache, but attention masks ``idx <= pos`` and the next
  round overwrites them — no copies, no cache surgery, static shapes
  throughout.
- The verify block is ``extend_core(all_logits=True)`` — one fused
  program per (k+1) width, position-offset traced, so a generation
  compiles exactly three programs (target prefill, verify block,
  draft step) regardless of length.
- The draft runs single-token steps through the same
  ``decode_chunk_fn`` program the serving engine uses.

Batch-1 only: per-row acceptance lengths desynchronize cache
positions across rows, which the scalar-``pos`` decode layout cannot
express — batched serving gets its parallelism from continuous
batching instead; speculation is the SINGLE-STREAM latency lever.

Two schemes share the round/cache algebra:

- :func:`speculative_generate` — greedy (temperature 0), emitted
  stream byte-identical to plain target greedy decoding.
- :func:`speculative_sample` — temperature > 0 via the
  acceptance-rejection rule of Leviathan et al. / Chen et al.
  (accept draft token x with prob ``min(1, p(x)/q(x))``; on the
  first rejection sample from the residual ``norm(max(p - q, 0))``):
  the emitted stream is distributed EXACTLY as plain target sampling
  with the same temperature/top-k/top-p warps, though not
  byte-identical to the non-speculative stream for a given seed (the
  two consume randomness differently — an inherent property of the
  scheme, not an implementation gap).

Both run the draft phase as ONE jitted program per round
(:func:`propose_fn`, a ``lax.scan`` over single decode steps that
consumes the round's pending tokens and chains all k proposals) —
through a high-RTT attach (the tunneled chip here) that is the
difference between ``k + 1`` device round trips per round and 2.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

# Distinct fold_in namespaces so the draft's sampling stream, the
# acceptance uniforms, and the residual/bonus draws are mutually
# independent while all deriving from the ONE request key. Within a
# tag, index = the emitted-token position it decides — each
# output-affecting draw has a unique (tag, index) and is never reused
# for a different role. The tags sit far above any reachable token
# index (engine max_new_tokens tiers are << 2**30) so a tagged
# namespace root can never collide with an untagged per-token
# fold_in(key, token_index) drawn by the plain chunked decode path —
# threefry fold_in and random-bits share one counter space, so a
# collision would correlate draft/acceptance key material with an
# emitted token's draw.
_DRAFT_TAG = 1 << 30
_ACC_TAG = (1 << 30) + 1
_RES_TAG = (1 << 30) + 2


@dataclass
class SpecStats:
    rounds: int = 0
    drafted: int = 0
    accepted: int = 0
    emitted: int = 0
    fallback_steps: int = 0  # first-draft mismatch → plain decode step
    per_round: list = field(default_factory=list)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def tokens_per_round(self) -> float:
        return self.emitted / self.rounds if self.rounds else 0.0


@functools.cache
def _zero_key():
    """Greedy decoding never consumes randomness; one shared dummy
    key avoids rebuilding it in the per-token hot loop."""
    return jnp.asarray(
        np.asarray(jax.random.key_data(jax.random.key(0)))[None]
    )


def _prefill(model, params, prompt_ids, total):
    from mlapi_tpu.models.gpt import prefill_fn

    b, _ = prompt_ids.shape
    first, cache = prefill_fn(model, total)(
        params, prompt_ids, _zero_key(),
        jnp.zeros((b,), jnp.float32), jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.int32), jnp.ones((b,), jnp.float32),
    )
    return int(np.asarray(first)[0]), cache


def _step(model, params, cache, tok, pos):
    """One greedy decode step; returns (next_tok, cache)."""
    from mlapi_tpu.models.gpt import decode_chunk_fn

    toks, cache, _ = decode_chunk_fn(model, 1)(
        params, cache, jnp.asarray(np.asarray([tok], np.int32)),
        jnp.int32(pos), jnp.zeros((1,), jnp.int32),
        jnp.zeros((1,), jnp.float32), _zero_key(), jnp.int32(0),
        jnp.zeros((1,), jnp.int32), jnp.ones((1,), jnp.float32),
        jnp.int32(0), jnp.int32(0),
    )
    return int(np.asarray(toks)[0, 0]), cache


@functools.lru_cache(maxsize=32)
def verify_fn(model, width: int):
    """Jitted verify block: greedy argmax at every position of a
    ``[B, width]`` token block extended onto the target cache at a
    traced offset, honoring per-row left-pad masks (``n_pad``) so the
    serving engine's bucketed rows verify identically to unpadded
    library rows."""

    def _run(params, cache, block, pos0, n_pad):
        cache, logits = model.extend_core(
            params, cache, block, pos0, n_pad,
            jnp.int32(0), jnp.int32(0), all_logits=True,
        )
        return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return jax.jit(_run, donate_argnums=(1,))


def _warped_probs(logits, temps, top_k, top_p):
    """The exact distribution ``models.gpt._pick_token`` samples from
    for ``temps > 0`` rows: softmax of top-k/top-p-filtered
    temperature-scaled logits ``[B, V]``. Sharing the model zoo's own
    filter keeps the acceptance ratio ``p/q`` exactly 1 when draft ==
    target (the 100%-acceptance pin). Greedy rows (``temps <= 0``)
    have no sampling distribution — callers route them to the argmax
    verify instead."""
    from mlapi_tpu.models.gpt import _filter_top_k_top_p

    v = logits.shape[-1]
    safe_t = jnp.where(temps > 0.0, temps, 1.0)
    scaled = logits / safe_t[:, None]
    need = jnp.any((top_k > 0) & (top_k < v)) | jnp.any(
        (top_p > 0.0) & (top_p < 1.0)
    )
    scaled = jax.lax.cond(
        need,
        lambda s: _filter_top_k_top_p(s, top_k, top_p),
        lambda s: s,
        scaled,
    )
    return jax.nn.softmax(scaled, axis=-1)


@functools.lru_cache(maxsize=64)
def propose_fn(model, n_in: int, k: int, sampled: bool = False):
    """Jitted DRAFT PHASE: one ``lax.scan`` program that consumes the
    round's ``n_in`` pending accepted tokens (cache writes at
    ``pos0..``) and chains ``k`` proposals — the last consume's output
    distribution yields proposal 1. One device dispatch replaces the
    ``n_in + k - 1`` chained single-step calls (each a full host
    round trip through the tunnel) the first implementation made.

    ``sampled`` is STATIC (part of the compile key): greedy rounds
    argmax with none of the warp/softmax/PRNG machinery in the
    program (temp is traced, so a runtime select could not be
    dead-code-eliminated); sampled rounds draw each proposal from the
    draft's warped distribution at stream
    ``fold(fold(key, DRAFT), step0+i)`` (``i`` = proposal index).
    Returns ``(cache, proposals [k], q_probs [k, V])`` — ``q_probs``
    stays on device for the sampled verify; zeros (unused) in the
    greedy variant.
    """

    def _run(params, cache, toks_in, pos0, n_pad, key_data, temp,
             topk, topp, step0):
        def body(carry, i):
            cache, tok = carry
            logits, cache = model.decode_step(
                params, cache, tok[:, None], pos0 + i, n_pad
            )
            if sampled:
                probs = _warped_probs(logits, temp, topk, topp)
                prop_i = jnp.maximum(i - (n_in - 1), 0) + step0
                keys = jax.vmap(
                    lambda kd: jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.wrap_key_data(kd), _DRAFT_TAG
                        ),
                        prop_i,
                    )
                )(key_data)
                nxt = jax.vmap(
                    lambda kk, pr: jax.random.categorical(
                        kk, jnp.log(pr)
                    )
                )(keys, probs).astype(jnp.int32)
            else:
                # Greedy: no distribution to carry — a zero-width
                # placeholder keeps the scan ys structure without
                # stacking a [steps, V] buffer nobody reads.
                probs = jnp.zeros((1, 0), jnp.float32)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if n_in > 1:
                feed = jnp.where(
                    i + 1 < n_in,
                    toks_in[jnp.minimum(i + 1, n_in - 1)],
                    nxt[0],
                )
                nxt = jnp.reshape(feed, (1,))
            return (cache, nxt), (nxt[0], probs[0])

        (cache, _), (toks, probs) = jax.lax.scan(
            body, (cache, toks_in[:1]), jnp.arange(n_in + k - 1)
        )
        return cache, toks[n_in - 1:], probs[n_in - 1:]

    return jax.jit(_run, donate_argnums=(1,))


def _accept_and_draw(key, pr, q_probs, props, usable, step0):
    """The distribution-critical acceptance-rejection core shared by
    the jitted verify (:func:`sample_verify_fn`) and the fused loop
    (:func:`fused_spec_fn`): test each proposal with ``u*q < p``
    (ACC-tagged per-token uniforms), find the first rejection ``m``
    (capped by ``usable``), and draw the round's final token — from
    the normalized residual ``max(p_m - q_m, 0)`` at a NATURAL
    rejection, else from the full target distribution ``p_m``
    (all-accepted bonus / budget-capped round) — on the RES-tagged
    stream at the token's own index. Returns ``(m, final_token)``.

    ``pr``: warped target probs ``[k+1, V]``; ``q_probs``: draft
    probs ``[k, V]``; ``props``: ``[k]`` proposal ids.
    """
    k, v = q_probs.shape[0], pr.shape[-1]
    idx = jnp.arange(k)
    ukeys = jax.vmap(
        lambda i: jax.random.fold_in(
            jax.random.fold_in(key, _ACC_TAG), step0 + i
        )
    )(idx)
    us = jax.vmap(jax.random.uniform)(ukeys)
    p_at = pr[idx, props]
    q_at = q_probs[idx, props]
    # u < p/q as u*q < p: no divide, exact at q == 0 (unreachable
    # for a draft-sampled token, but cheap insurance).
    acc = (us * q_at < p_at) & (idx < usable)
    m = jnp.argmin(
        jnp.concatenate([acc, jnp.zeros((1,), bool)]).astype(jnp.int32)
    )
    natural = m < usable  # a tested proposal actually failed
    q_ext = jnp.concatenate([q_probs, jnp.zeros((1, v), q_probs.dtype)])
    r = jnp.where(natural, jnp.maximum(pr[m] - q_ext[m], 0.0), pr[m])
    rsum = jnp.sum(r)
    # Degenerate residual (p <= q everywhere, float ties): fall back
    # to the target distribution — still a valid sample and
    # unreachable in exact arithmetic.
    r = jnp.where(rsum > 0.0, r / rsum, pr[m] / jnp.sum(pr[m]))
    skey = jax.random.fold_in(
        jax.random.fold_in(key, _RES_TAG), step0 + m
    )
    final = jax.random.categorical(skey, jnp.log(r)).astype(jnp.int32)
    return m, final


def _verify_pack_row(key, pr, q_probs, props, usable, step0):
    """One row's accept/draw plus the packed output layout shared by
    the solo and batched sampled verifies: ``[width + 1]`` = emitted
    tokens (``[:m]`` accepted proposals, ``[m]`` the final draw, rest
    garbage) then ``m``."""
    width = props.shape[0] + 1
    m, fin = _accept_and_draw(key, pr, q_probs, props, usable, step0)
    out = jnp.where(
        jnp.arange(width) < m,
        jnp.concatenate([props, jnp.zeros((1,), jnp.int32)]),
        fin,
    )
    return jnp.concatenate([out, m[None].astype(jnp.int32)])


@functools.lru_cache(maxsize=32)
def sample_verify_fn(model, width: int):
    """Jitted SAMPLED verify: the whole acceptance-rejection round on
    device — extend the target cache with ``[t0, x1..xk]``
    (``width = k + 1``), warp the per-position logits with the same
    temperature/top-k/top-p pipeline the draft used, test each
    proposal with ``u_i < p_i(x_i) / q_i(x_i)`` (uniforms from the
    ACC-tagged stream at the token's own index), and draw the round's
    final token: from the normalized residual ``max(p_m - q_m, 0)``
    at a NATURAL rejection ``m < usable``, or from the full target
    distribution ``p_m`` when every usable proposal was accepted
    (``m = usable`` — covers both the all-accepted bonus and the
    budget-capped round, where position ``usable``'s proposal is
    never tested so no residual applies). ``usable`` is traced: the
    budget-capped last round reuses the same program.

    Returns ``(cache, packed [width + 1])`` where ``packed[:width]``
    holds the emitted tokens (``[:m]`` accepted proposals, ``[m]``
    the final draw, rest garbage) and ``packed[width]`` is ``m`` —
    one host readback per round.
    """
    k = width - 1

    def _run(params, cache, tok0, props, pos0, n_pad, q_probs,
             key_data, temp, topk, topp, step0, usable):
        block = jnp.concatenate([tok0[None], props])[None]  # [1, k+1]
        cache, logits = model.extend_core(
            params, cache, block, pos0, n_pad,
            jnp.int32(0), jnp.int32(0), all_logits=True,
        )
        lg = logits[0]  # [width, V]
        wide = lambda x: jnp.broadcast_to(x, (width,))
        p = _warped_probs(lg, wide(temp[0]), wide(topk[0]), wide(topp[0]))
        key = jax.random.wrap_key_data(key_data[0])
        return cache, _verify_pack_row(
            key, p, q_probs, props, usable, step0
        )

    return jax.jit(_run, donate_argnums=(1,))


def speculative_generate(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
) -> tuple[list[int], SpecStats]:
    """Greedy speculative generation for ONE prompt row.

    ``prompt_ids``: ``[1, P]`` int32 (no padding — callers bucket
    upstream if they care about compile reuse). Returns
    ``(token_ids, stats)``; ``token_ids`` equals plain target greedy
    decoding exactly.
    """
    b, p = prompt_ids.shape
    if b != 1:
        raise ValueError("speculative decoding is single-row (batch=1)")
    if target.vocab_size != draft.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    n = int(max_new_tokens)
    if p + n > target.max_positions or p + n > draft.max_positions:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({n}) exceeds a model window"
        )
    k = max(1, min(int(k), n))
    # Room for a full round's block (t0 + k drafts) past the last
    # needed position keeps every verify the same width.
    total_t = min(target.max_positions, p + n + k + 1)
    total_d = min(draft.max_positions, p + n + k + 1)

    stats = SpecStats()
    prompt_ids = jnp.asarray(prompt_ids)
    t0, t_cache = _prefill(target, t_params, prompt_ids, total_t)
    _, d_cache = _prefill(draft, d_params, prompt_ids, total_d)

    out: list[int] = [t0]
    # Per-model bookkeeping: `upto` = cache slots holding VALID
    # accepted content; `pend` = accepted tokens not yet written to
    # that model's cache (their slots start at `upto`). The target's
    # pend is always one token (the round's bonus); the draft's can be
    # two after a fully-accepted round (its k-th proposal was never
    # fed back to it).
    t_upto, t_pend = p, [t0]
    d_upto, d_pend = p, [t0]

    while len(out) < n:
        budget = n - len(out)
        room = (
            t_upto + 1 + k + 1 <= total_t
            and d_upto + len(d_pend) + k <= total_d
        )
        if budget == 1 or not room:
            # One plain target step. The draft is NOT consulted again
            # once fallback starts (budget exhaustion and the room
            # inequalities are both monotone under growing caches and
            # pending lists), so syncing its cache here would be pure
            # waste — accumulate its pending tokens instead, which
            # keeps the consume loop correct in the impossible-return
            # case and costs nothing.
            nxt, t_cache = _step(target, t_params, t_cache,
                                 t_pend[0], t_upto)
            t_upto += 1
            d_pend.append(nxt)
            t_pend = [nxt]
            out.append(nxt)
            stats.fallback_steps += 1
            continue

        # Draft phase — ONE dispatch: consume the pending accepted
        # tokens and chain all k proposals in a single scanned
        # program (the last consume's greedy output is proposal 1).
        d_cache, props, _ = propose_fn(draft, len(d_pend), k)(
            d_params, d_cache,
            jnp.asarray(np.asarray(d_pend, np.int32)),
            jnp.int32(d_upto), jnp.zeros((1,), jnp.int32), _zero_key(),
            jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
            jnp.ones((1,), jnp.float32), jnp.int32(0),
        )
        proposals = np.asarray(props).tolist()
        d_upto += len(d_pend) + k - 1
        # d_upto now covers t0 + proposals[:-1]; proposals[-1] was
        # proposed but never fed back (its slot is unwritten).

        # Verify [t0, d1..dk] in ONE target block: argmax at position
        # i is the target's next token AFTER t0, d1..di.
        block = np.asarray([[t_pend[0], *proposals]], np.int32)
        t_cache, expect = verify_fn(target, k + 1)(
            t_params, t_cache, jnp.asarray(block), jnp.int32(t_upto),
            jnp.zeros((1,), jnp.int32),
        )
        expect = np.asarray(expect)[0]  # [k+1]
        # Only `usable` proposals can be emitted this round (the
        # bonus token takes the last budget slot); drafts beyond it
        # are neither accepted nor rejected — they don't count.
        usable = min(k, budget - 1)
        m = 0
        while m < usable and proposals[m] == int(expect[m]):
            m += 1
        bonus = int(expect[m])
        out.extend(proposals[:m])
        out.append(bonus)
        stats.rounds += 1
        stats.drafted += usable
        stats.accepted += m
        stats.emitted += m + 1
        stats.per_round.append(m + 1)

        t_upto += m + 1  # t0 + m accepted drafts are valid content
        t_pend = [bonus]
        if m == k:
            # Draft never cached its own k-th proposal: it is pending
            # alongside the bonus (consecutive slots from d_upto).
            d_pend = [proposals[-1], bonus]
        else:
            # Rewind over the draft's stale rejected tail; future
            # writes overwrite it and `pos <= upto` masks it until
            # then.
            d_upto = t_upto
            d_pend = [bonus]
    return out[:n], stats


@functools.lru_cache(maxsize=32)
def propose_batched_fn(model, k: int, sampled: bool = False):
    """Jitted BATCHED draft phase with per-row cache positions: every
    row consumes its own pending tokens (``pend_buf [B, 2]``, row
    count ``n_in[b]`` ∈ {1, 2}) and chains ``k`` proposals, writing
    K/V at its OWN slots ``d_pos[b] + i`` (the vmapped
    ``dynamic_update_slice`` path in ``cached_attend``). Rows whose
    pending list is shorter run one trailing extra step; its output
    is never gathered and its stale cache write sits beyond the row's
    valid bound, masked by ``idx <= pos`` until overwritten — the
    same free-rollback property single-row rounds rely on.

    Returns ``(cache, proposals [B, k], q_probs [B, k, V])``, each
    row's proposals gathered from its own scan offsets.
    """

    def _run(params, cache, pend_buf, n_in, d_pos, n_pad, key_data,
             temps, topk, topp, step0):
        def body(carry, i):
            cache, tok = carry
            logits, cache = model.decode_step(
                params, cache, tok[:, None], d_pos + i, n_pad
            )
            if sampled:
                probs = _warped_probs(logits, temps, topk, topp)
                prop_i = jnp.maximum(i - (n_in - 1), 0) + step0  # [B]
                keys = jax.vmap(
                    lambda kd, s: jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.wrap_key_data(kd), _DRAFT_TAG
                        ),
                        s,
                    )
                )(key_data, prop_i)
                nxt = jax.vmap(
                    lambda kk, pr: jax.random.categorical(
                        kk, jnp.log(pr)
                    )
                )(keys, probs).astype(jnp.int32)
            else:
                probs = jnp.zeros((logits.shape[0], 0), jnp.float32)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            feed = jnp.where(
                i + 1 < n_in, pend_buf[:, jnp.minimum(i + 1, 1)], nxt
            )
            return (cache, feed), (nxt, probs)

        (cache, _), (toks, probs) = jax.lax.scan(
            body, (cache, pend_buf[:, 0]), jnp.arange(k + 1)
        )
        toks = toks.T                      # [B, k+1]
        probs = probs.transpose(1, 0, 2)   # [B, k+1, V]
        j = (n_in - 1)[:, None] + jnp.arange(k)[None, :]  # [B, k]
        props = jnp.take_along_axis(toks, j, axis=1)
        q = jnp.take_along_axis(probs, j[:, :, None], axis=1)
        return cache, props, q

    return jax.jit(_run, donate_argnums=(1,))


@functools.lru_cache(maxsize=32)
def sample_verify_batched_fn(model, width: int):
    """Batched SAMPLED verify: one target block forward over every
    row at its OWN cache position (``pos0 [B]``), then the shared
    acceptance-rejection core (:func:`_accept_and_draw`) vmapped per
    row with per-row keys, warps, budgets, and stream offsets.
    Returns ``(cache, packed [B, width + 1])`` — per row the emitted
    tokens then ``m`` (same layout as :func:`sample_verify_fn`)."""
    k = width - 1

    def _run(params, cache, tok0, props, pos0, n_pad, q_probs,
             key_data, temps, topk, topp, step0, usable):
        block = jnp.concatenate([tok0[:, None], props], axis=1)
        cache, logits = model.extend_core(
            params, cache, block, pos0, n_pad,
            jnp.int32(0), jnp.int32(0), all_logits=True,
        )

        # Warp OUTSIDE the per-row vmap: under vmap the
        # no-filter lax.cond would become a select and the two
        # per-row sorts in the top-k/top-p filter would run even
        # when disabled (the batch-wide `need` branch must survive).
        bsz, w, v = logits.shape
        pr_all = _warped_probs(
            logits.reshape(bsz * w, v),
            jnp.repeat(temps, w), jnp.repeat(topk, w),
            jnp.repeat(topp, w),
        ).reshape(bsz, w, v)
        packed = jax.vmap(
            lambda pr, kd, q, pr_, u, s0: _verify_pack_row(
                jax.random.wrap_key_data(kd), pr, q, pr_, u, s0
            )
        )(pr_all, key_data, q_probs, props, usable, step0)
        return cache, packed

    return jax.jit(_run, donate_argnums=(1,))


def speculative_sample_batched(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seeds=None,
) -> tuple[list[list[int]], SpecStats]:
    """SAMPLED speculative generation for a WHOLE BATCH of rows, each
    with its own PRNG stream (``seeds``: one per row, default
    ``0..B-1``) and its own acceptance-driven cache position. Every
    row's emitted stream is byte-identical to its solo
    :func:`speculative_sample_fused` run (same tagged-stream
    discipline, same ``usable = 0`` budget-capped rounds) and hence
    exactly target-distributed for any draft. Same window-headroom
    requirement as the greedy batched variant. ``temperature <= 0``
    delegates to :func:`speculative_generate_batched`."""
    if temperature <= 0.0:
        return speculative_generate_batched(
            target, t_params, draft, d_params, prompt_ids,
            max_new_tokens=max_new_tokens, k=k,
        )
    b, p = prompt_ids.shape
    if target.vocab_size != draft.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    n = int(max_new_tokens)
    k = max(1, min(int(k), n))
    total = p + n + k + 1
    if total > target.max_positions or total > draft.max_positions:
        raise ValueError(
            f"batched speculation needs prompt + max_new_tokens + k + 1 "
            f"(= {total}) cache slots within both model windows; use "
            "speculative_sample per row near the window edge"
        )
    if seeds is None:
        seeds = list(range(b))
    if len(seeds) != b:
        raise ValueError(f"need {b} seeds, got {len(seeds)}")

    stats = SpecStats()
    prompt_ids = jnp.asarray(prompt_ids)
    zb = jnp.zeros((b,), jnp.int32)
    keys = jnp.asarray(
        np.stack([
            np.asarray(jax.random.key_data(jax.random.key(int(s))))
            for s in seeds
        ])
    )
    temps = jnp.full((b,), temperature, jnp.float32)
    topk_v = jnp.full((b,), top_k, jnp.int32)
    topp_v = jnp.full((b,), top_p, jnp.float32)

    from mlapi_tpu.models.gpt import prefill_fn

    first, t_cache = prefill_fn(target, total)(
        t_params, prompt_ids, keys, temps, zb, topk_v, topp_v,
    )
    _, d_cache = prefill_fn(draft, total)(
        d_params, prompt_ids, keys, jnp.zeros((b,), jnp.float32), zb,
        zb, jnp.ones((b,), jnp.float32),
    )
    first = np.asarray(first)

    out = [[int(first[i])] for i in range(b)]
    t_upto = np.full((b,), p, np.int64)
    d_upto = np.full((b,), p, np.int64)
    d_pend = [[int(first[i])] for i in range(b)]

    while any(len(o) < n for o in out):
        pend_buf = np.zeros((b, 2), np.int32)
        n_in = np.ones((b,), np.int32)
        step0 = np.zeros((b,), np.int32)
        usable = np.zeros((b,), np.int32)
        for i in range(b):
            n_in[i] = len(d_pend[i])
            pend_buf[i, : n_in[i]] = d_pend[i]
            step0[i] = len(out[i])
            usable[i] = max(0, min(k, n - len(out[i]) - 1))
        d_cache, props, q_probs = propose_batched_fn(draft, k, True)(
            d_params, d_cache, jnp.asarray(pend_buf),
            jnp.asarray(n_in), jnp.asarray(d_upto.astype(np.int32)),
            zb, keys, temps, topk_v, topp_v, jnp.asarray(step0),
        )
        d_upto += n_in + k - 1

        tok0 = np.asarray([o[-1] for o in out], np.int32)
        t_cache, packed = sample_verify_batched_fn(target, k + 1)(
            t_params, t_cache, jnp.asarray(tok0), props,
            jnp.asarray(t_upto.astype(np.int32)), zb, q_probs, keys,
            temps, topk_v, topp_v, jnp.asarray(step0),
            jnp.asarray(usable),
        )
        packed = np.asarray(packed)
        stats.rounds += 1
        for i in range(b):
            budget = n - len(out[i])
            if budget <= 0:
                d_upto[i] = t_upto[i]
                continue
            m = int(packed[i, k + 1])
            emitted = [int(t) for t in packed[i, : m + 1]]
            out[i].extend(emitted)
            stats.drafted += int(usable[i])
            stats.accepted += m
            stats.emitted += m + 1
            t_upto[i] += m + 1
            if m == k:
                d_pend[i] = [int(packed[i, k - 1]), emitted[-1]]
            else:
                d_upto[i] = t_upto[i]
                d_pend[i] = [emitted[-1]]
    return [o[:n] for o in out], stats


def speculative_generate_batched(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
) -> tuple[list[list[int]], SpecStats]:
    """Greedy speculative generation for a WHOLE BATCH of prompt rows
    — every row's stream byte-identical to its solo plain greedy
    stream.

    The thing that makes this possible is per-row cache positions:
    each round, row ``b`` accepts ``m_b`` proposals and advances by
    ``m_b + 1``, so rows desynchronize immediately. Draft writes land
    at per-row slots via :func:`propose_batched_fn`; the verify block
    (:func:`verify_fn` — the same program, retraced with a ``[B]``
    position vector) extends each row's cache at its own offset. A
    row that exhausts its budget freezes: it keeps riding the batch
    as a dummy (its writes land beyond its valid bound and are
    masked) until every row finishes. Rounds never need plain-step
    fallback — a budget-1 row emits exactly its bonus token
    (``usable = 0``) — but the cache must hold a full final round:
    ``prompt + max_new_tokens + k + 1 <= max_positions`` for both
    models, or ``ValueError`` (tight windows: loop the single-row
    :func:`speculative_generate`, which degrades to plain steps).
    """
    b, p = prompt_ids.shape
    if target.vocab_size != draft.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    n = int(max_new_tokens)
    k = max(1, min(int(k), n))
    total = p + n + k + 1
    if total > target.max_positions or total > draft.max_positions:
        raise ValueError(
            f"batched speculation needs prompt + max_new_tokens + k + 1 "
            f"(= {total}) cache slots within both model windows; use "
            "speculative_generate per row near the window edge"
        )

    stats = SpecStats()
    prompt_ids = jnp.asarray(prompt_ids)
    zb = jnp.zeros((b,), jnp.int32)
    zbf = jnp.zeros((b,), jnp.float32)
    ob = jnp.ones((b,), jnp.float32)
    keys = jnp.asarray(
        np.tile(
            np.asarray(jax.random.key_data(jax.random.key(0)))[None], (b, 1)
        )
    )

    from mlapi_tpu.models.gpt import prefill_fn

    first, t_cache = prefill_fn(target, total)(
        t_params, prompt_ids, keys, zbf, zb, zb, ob,
    )
    _, d_cache = prefill_fn(draft, total)(
        d_params, prompt_ids, keys, zbf, zb, zb, ob,
    )
    first = np.asarray(first)

    out = [[int(first[i])] for i in range(b)]
    t_upto = np.full((b,), p, np.int64)
    d_upto = np.full((b,), p, np.int64)
    d_pend = [[int(first[i])] for i in range(b)]

    while any(len(o) < n for o in out):
        pend_buf = np.zeros((b, 2), np.int32)
        n_in = np.ones((b,), np.int32)
        for i in range(b):
            n_in[i] = len(d_pend[i])
            pend_buf[i, : n_in[i]] = d_pend[i]
        d_cache, props, _ = propose_batched_fn(draft, k)(
            d_params, d_cache, jnp.asarray(pend_buf),
            jnp.asarray(n_in), jnp.asarray(d_upto.astype(np.int32)),
            zb, keys, zbf, zb, ob, zb,
        )
        props = np.asarray(props)
        d_upto += n_in + k - 1

        tok0 = np.asarray([o[-1] for o in out], np.int32)
        block = np.concatenate([tok0[:, None], props], axis=1)
        t_cache, expect = verify_fn(target, k + 1)(
            t_params, t_cache, jnp.asarray(block),
            jnp.asarray(t_upto.astype(np.int32)), zb,
        )
        expect = np.asarray(expect)
        stats.rounds += 1
        for i in range(b):
            budget = n - len(out[i])
            if budget <= 0:
                # Finished row riding as a dummy: freeze its state
                # (the round's writes sit beyond its valid bound).
                d_upto[i] = t_upto[i]
                continue
            usable = min(k, budget - 1)
            m = 0
            while m < usable and props[i, m] == int(expect[i, m]):
                m += 1
            bonus = int(expect[i, m])
            out[i].extend([int(t) for t in props[i, :m]] + [bonus])
            stats.drafted += usable
            stats.accepted += m
            stats.emitted += m + 1
            t_upto[i] += m + 1
            if m == k:
                d_pend[i] = [int(props[i, -1]), bonus]
            else:
                d_upto[i] = t_upto[i]
                d_pend[i] = [bonus]
    return [o[:n] for o in out], stats


# maxsize must dominate the serving engine's fused warm grid
# (buckets x tiers x greedy/sampled — up to ~24 entries on a wide
# config): an evicted entry would rebuild its jax.jit wrapper with an
# EMPTY compile cache, and strict mode would then stall a request on
# a remote recompile for a shape the fused warm set claims is warm.
@functools.lru_cache(maxsize=64)
def fused_spec_fn(target, draft, p: int, n: int, k: int,
                  sampled: bool = False):
    """The ENTIRE speculative generation as ONE XLA program: target +
    draft prefills, then a ``lax.while_loop`` whose body is a full
    round — draft scan (consume pending + chain k proposals), verify
    block, acceptance, accepted-segment scatter into the output
    buffer, cache-position algebra — with no host round-trip
    anywhere. Through a high-RTT attach a generation costs ONE
    dispatch + ONE packed readback regardless of length; on any
    attach it removes the per-round host sync the chunked engine
    pays.

    ``sampled`` is STATIC: the greedy variant argmaxes everywhere;
    the sampled variant draws the first token at the untagged stream
    index 0, proposals from the draft's warped distribution
    (DRAFT-tagged per-token streams), acceptance uniforms and the
    residual/bonus draw from the ACC/RES-tagged streams — the same
    key discipline as the host-loop scheme, so the emitted stream
    keeps the exact target sampling distribution for any draft.

    Compiled per ``(target, draft, prompt_width, n_tier, k,
    sampled)``. ``p`` is the PROMPT WIDTH (a serving bucket: real
    tokens right-aligned, ``n_pad`` left-pad slots masked — pass
    zeros for an exact-length prompt) and ``n`` the OUTPUT TIER: the
    jitted program additionally takes ``(n_pad [1] int32, n_actual
    scalar int32)`` TRACED arguments and emits ``n_actual <= n``
    tokens, so one compile per (bucket, tier) serves every request
    budget — the serving engine's compile-count contract, honoured by
    the fused path. Requires window headroom ``p + n + k + 1 <=
    max_positions`` for both models (rounds never need plain-step
    fallback: a budget-1 round emits exactly its final token via
    ``usable = 0``).

    Returns ``packed [n + 3]``: tokens (first ``n_actual`` valid)
    then (rounds, accepted, drafted).
    """
    kw = k + 1
    total_t = total_d = p + n + k + 1

    def _run(t_params, d_params, prompt_ids, key_data, temps, topk,
             topp, n_pad, n_actual):
        from mlapi_tpu.models.gpt import _pick_token

        key = jax.random.wrap_key_data(key_data[0])
        t_cache, t_logits = target.prefill_core(
            t_params, prompt_ids, n_pad, total_t
        )
        d_cache, _ = draft.prefill_core(
            d_params, prompt_ids, n_pad, total_d
        )
        if sampled:
            t0 = _pick_token(
                temps, t_logits, key_data, 0, topk, topp
            )[0]
        else:
            t0 = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)[0]
        out = jnp.zeros((n + kw,), jnp.int32).at[0].set(t0)

        def body(s):
            t_cache, d_cache, out, n_out, t_upto, d_upto, pend, n_pend = s

            # Draft phase: consume the pending accepted tokens and
            # chain k proposals (same schedule as propose_fn, with
            # the pending width traced).
            def dstep(carry, i):
                d_cache, tok = carry
                logits, d_cache = draft.decode_step(
                    d_params, d_cache, tok[None, None], d_upto + i, n_pad
                )
                if sampled:
                    probs = _warped_probs(logits, temps, topk, topp)
                    prop_i = jnp.maximum(i - (n_pend - 1), 0) + n_out
                    kk = jax.random.fold_in(
                        jax.random.fold_in(key, _DRAFT_TAG), prop_i
                    )
                    nxt = jax.random.categorical(
                        kk, jnp.log(probs[0])
                    ).astype(jnp.int32)
                else:
                    probs = jnp.zeros((1, 0), jnp.float32)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
                feed = jnp.where(
                    i + 1 < n_pend, pend[jnp.minimum(i + 1, 1)], nxt
                )
                return (d_cache, feed), (nxt, probs[0])

            (d_cache, _), (toks, qrows) = jax.lax.scan(
                dstep, (d_cache, pend[0]), jnp.arange(kw)
            )
            j = (n_pend - 1) + jnp.arange(k)
            props = toks[j]                       # [k]
            d_upto = d_upto + n_pend + k - 1

            # Verify: ONE target block forward over the LAST EMITTED
            # token + proposals. `pend` is the DRAFT's pending list;
            # its final entry (index n_pend - 1) is always the
            # previous round's bonus — the target's own pending token
            # (after a full round pend[0] is the draft's unfed k-th
            # proposal, which must NOT head the verify block).
            head = pend[n_pend - 1]
            block = jnp.concatenate([head[None], props])[None]
            t_cache, logits = target.extend_core(
                t_params, t_cache, block, t_upto, n_pad,
                jnp.int32(0), jnp.int32(0), all_logits=True,
            )
            usable = jnp.minimum(k, n_actual - n_out - 1)
            if sampled:
                q_probs = qrows[j]                # [k, V]
                wide = lambda x: jnp.broadcast_to(x, (kw,))
                pr = _warped_probs(
                    logits[0], wide(temps[0]), wide(topk[0]),
                    wide(topp[0]),
                )
                m, bonus = _accept_and_draw(
                    key, pr, q_probs, props, usable, n_out
                )
            else:
                expect = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
                acc = (props == expect[:k]) & (jnp.arange(k) < usable)
                m = jnp.argmin(
                    jnp.concatenate(
                        [acc, jnp.zeros((1,), bool)]
                    ).astype(jnp.int32)
                )
                bonus = expect[m]
            seg = jnp.where(
                jnp.arange(kw) < m,
                jnp.concatenate([props, jnp.zeros((1,), jnp.int32)]),
                bonus,
            )
            out = jax.lax.dynamic_update_slice(out, seg, (n_out,))
            t_upto = t_upto + m + 1
            full = m == k
            pend = jnp.where(
                full,
                jnp.stack([props[k - 1], bonus]),
                jnp.stack([bonus, jnp.int32(0)]),
            )
            n_pend = jnp.where(full, jnp.int32(2), jnp.int32(1))
            d_upto = jnp.where(full, d_upto, t_upto)
            n_out = n_out + m + 1
            return (
                t_cache, d_cache, out, n_out, t_upto, d_upto, pend,
                n_pend,
            )

        def cond2(s):
            return s[0][3] < n_actual

        def body2(s):
            core, rounds, accepted, drafted = s
            usable = jnp.minimum(k, n_actual - core[3] - 1)
            nxt = body(core)
            emitted = nxt[3] - core[3]
            return (nxt, rounds + 1, accepted + emitted - 1,
                    drafted + usable)

        init = (
            t_cache, d_cache, out, jnp.int32(1), jnp.int32(p),
            jnp.int32(p), jnp.stack([t0, jnp.int32(0)]), jnp.int32(1),
        )
        (core, rounds, accepted, drafted) = jax.lax.while_loop(
            cond2, body2, (init, jnp.int32(0), jnp.int32(0),
                           jnp.int32(0))
        )
        # ONE packed readback: tokens + stats in a single transfer
        # (separate scalar fetches each cost a full round trip
        # through a tunneled attach).
        return jnp.concatenate(
            [core[2][:n], jnp.stack([rounds, accepted, drafted])]
        )

    return jax.jit(_run)


@functools.lru_cache(maxsize=32)
def fused_spec_batched_fn(target, draft, p: int, n: int, k: int,
                          sampled: bool = False):
    """The ENTIRE **batched** speculative generation as ONE XLA
    program — the last cell of the fused matrix ({greedy, sampled} ×
    {solo, batched} × {host-loop, fused}). Per-row cache positions
    desynchronize immediately (row ``b`` advances ``m_b + 1`` slots a
    round), which the rank-polymorphic decode/extend cores already
    express: ``decode_step``/``extend_core`` take ``[B]`` position
    vectors, cache writes vmap per row. Rows that exhaust their budget
    FREEZE (``active`` mask pins their positions; their round writes
    overwrite their own dead slots) until every row finishes, so the
    loop trip count is the slowest row's. Through a high-RTT attach
    this replaces the host batched loop's 2 dispatches per round
    (~2·rounds·RTT per batch) with ONE dispatch + ONE packed readback.

    Same compile-key/traced-argument discipline as
    :func:`fused_spec_fn`: static ``(prompt_width, n_tier, k,
    sampled)``; traced ``(n_pad [B], n_actual [B])``. Every row's
    emitted stream is byte-identical to its SOLO fused run (greedy:
    argmax-exact; sampled: per-row keys drive the same tagged
    streams), which is what the tests pin.

    Returns ``packed [B, n + 3]``: per-row tokens (first
    ``n_actual[b]`` valid) then (rounds, accepted, drafted).
    """
    kw = k + 1
    total = p + n + k + 1

    def _run(t_params, d_params, prompt_ids, key_data, temps, topk,
             topp, n_pad, n_actual):
        from mlapi_tpu.models.gpt import _pick_token

        b = prompt_ids.shape[0]
        rows = jnp.arange(b)
        keys = jax.vmap(jax.random.wrap_key_data)(key_data)
        t_cache, t_logits = target.prefill_core(
            t_params, prompt_ids, n_pad, total
        )
        d_cache, _ = draft.prefill_core(d_params, prompt_ids, n_pad, total)
        if sampled:
            t0 = _pick_token(temps, t_logits, key_data, 0, topk, topp)
        else:
            t0 = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        out = jnp.zeros((b, n + kw), jnp.int32).at[:, 0].set(t0)

        def body(s):
            (t_cache, d_cache, out, n_out, t_upto, d_upto, pend,
             n_pend, rounds, accepted, drafted) = s
            active = n_out < n_actual

            def dstep(carry, i):
                d_cache, tok = carry
                logits, d_cache = draft.decode_step(
                    d_params, d_cache, tok[:, None], d_upto + i, n_pad
                )
                if sampled:
                    probs = _warped_probs(logits, temps, topk, topp)
                    prop_i = jnp.maximum(i - (n_pend - 1), 0) + n_out
                    nxt = jax.vmap(
                        lambda kk, pi, pr: jax.random.categorical(
                            jax.random.fold_in(
                                jax.random.fold_in(kk, _DRAFT_TAG), pi
                            ),
                            jnp.log(pr),
                        )
                    )(keys, prop_i, probs).astype(jnp.int32)
                else:
                    probs = jnp.zeros((b, 0), jnp.float32)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                feed = jnp.where(
                    i + 1 < n_pend,
                    pend[rows, jnp.minimum(i + 1, 1)],
                    nxt,
                )
                return (d_cache, feed), (nxt, probs)

            (d_cache, _), (toks, qrows) = jax.lax.scan(
                dstep, (d_cache, pend[:, 0]), jnp.arange(kw)
            )
            # Per-row proposal window: row b's k proposals start at
            # its own pending offset (n_pend[b] - 1) in the scan.
            props = jax.vmap(
                lambda tb, o: jax.lax.dynamic_slice(tb, (o,), (k,))
            )(toks.T, n_pend - 1)                        # [B, k]
            d_upto_n = d_upto + jnp.where(active, n_pend + k - 1, 0)

            head = pend[rows, n_pend - 1]
            block = jnp.concatenate([head[:, None], props], axis=1)
            t_cache, logits = target.extend_core(
                t_params, t_cache, block, t_upto, n_pad,
                jnp.int32(0), jnp.int32(0), all_logits=True,
            )                                            # [B, kw, V]
            usable = jnp.clip(
                jnp.minimum(k, n_actual - n_out - 1), 0, k
            )
            if sampled:
                q_probs = jax.vmap(
                    lambda qb, o: jax.lax.dynamic_slice(
                        qb, (o, 0), (k, qb.shape[-1])
                    )
                )(jnp.swapaxes(qrows, 0, 1), n_pend - 1)  # [B, k, V]
                pr = jax.vmap(
                    lambda lg, t, tk, tp: _warped_probs(
                        lg, jnp.broadcast_to(t, (kw,)),
                        jnp.broadcast_to(tk, (kw,)),
                        jnp.broadcast_to(tp, (kw,)),
                    )
                )(logits, temps, topk, topp)              # [B, kw, V]
                m, bonus = jax.vmap(_accept_and_draw)(
                    keys, pr, q_probs, props, usable, n_out
                )
            else:
                expect = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                acc = (props == expect[:, :k]) & (
                    jnp.arange(k)[None, :] < usable[:, None]
                )
                m = jnp.argmin(
                    jnp.concatenate(
                        [acc, jnp.zeros((b, 1), bool)], axis=1
                    ).astype(jnp.int32),
                    axis=1,
                )
                bonus = expect[rows, m]
            seg = jnp.where(
                jnp.arange(kw)[None, :] < m[:, None],
                jnp.concatenate(
                    [props, jnp.zeros((b, 1), jnp.int32)], axis=1
                ),
                bonus[:, None],
            )
            out = jax.vmap(
                lambda ob, sb, o: jax.lax.dynamic_update_slice(
                    ob, sb, (o,)
                )
            )(out, seg, n_out)
            adv = jnp.where(active, m + 1, 0)
            t_upto_n = t_upto + adv
            full = (m == k) & active
            pend_n = jnp.where(
                full[:, None],
                jnp.stack([props[:, k - 1], bonus], axis=1),
                jnp.stack([bonus, jnp.zeros((b,), jnp.int32)], axis=1),
            )
            n_pend_n = jnp.where(
                active, jnp.where(full, 2, 1), n_pend
            )
            d_upto_n = jnp.where(full, d_upto_n, t_upto_n)
            return (
                t_cache, d_cache, out, n_out + adv, t_upto_n,
                d_upto_n, pend_n, n_pend_n, rounds + 1,
                accepted + jnp.where(active, m, 0),
                drafted + jnp.where(active, usable, 0),
            )

        def cond(s):
            return jnp.any(s[3] < n_actual)

        init = (
            t_cache, d_cache, out, jnp.ones((b,), jnp.int32),
            jnp.full((b,), p, jnp.int32), jnp.full((b,), p, jnp.int32),
            jnp.stack([t0, jnp.zeros((b,), jnp.int32)], axis=1),
            jnp.ones((b,), jnp.int32), jnp.int32(0),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
        )
        s = jax.lax.while_loop(cond, body, init)
        return jnp.concatenate(
            [
                s[2][:, :n],
                jnp.broadcast_to(s[8], (b,))[:, None],
                s[9][:, None],
                s[10][:, None],
            ],
            axis=1,
        )

    return jax.jit(_run)


def _fused_run(target, t_params, draft, d_params, prompt_ids,
               max_new_tokens, k, sampled, key_data, temps, topk, topp):
    """Shared validation + dispatch + packed-stats unpack for both
    fused wrappers (the packed layout and the headroom formula live
    in exactly one place)."""
    b, p = prompt_ids.shape
    if b != 1:
        raise ValueError("speculative decoding is single-row (batch=1)")
    if target.vocab_size != draft.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    n = int(max_new_tokens)
    k = max(1, min(int(k), n))
    total = p + n + k + 1
    if total > target.max_positions or total > draft.max_positions:
        raise ValueError(
            f"fused speculation needs prompt + max_new_tokens + k + 1 "
            f"(= {total}) cache slots within both model windows; use "
            "the host-loop variant near the window edge"
        )
    packed = np.asarray(
        fused_spec_fn(target, draft, p, n, k, sampled)(
            t_params, d_params, jnp.asarray(prompt_ids), key_data,
            temps, topk, topp, jnp.zeros((1,), jnp.int32),
            jnp.int32(n),
        )
    )
    stats = SpecStats(
        rounds=int(packed[n]), drafted=int(packed[n + 2]),
        accepted=int(packed[n + 1]), emitted=n,
    )
    return packed[:n].tolist(), stats


def speculative_generate_fused(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
) -> tuple[list[int], SpecStats]:
    """Greedy speculative generation with the WHOLE loop on device
    (:func:`fused_spec_fn`) — byte-identical to
    :func:`speculative_generate` and plain target greedy decoding,
    at one dispatch + one readback per generation."""
    return _fused_run(
        target, t_params, draft, d_params, prompt_ids,
        max_new_tokens, k, False, _zero_key(),
        jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
        jnp.ones((1,), jnp.float32),
    )


def speculative_sample_fused(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
) -> tuple[list[int], SpecStats]:
    """SAMPLED speculative generation with the WHOLE loop on device
    (:func:`fused_spec_fn` with ``sampled=True``): one dispatch + one
    packed readback per generation, emitted stream distributed
    exactly as plain target sampling under the same warp for ANY
    draft (the same acceptance-rejection scheme and tagged-stream
    key discipline as :func:`speculative_sample`; the two are not
    byte-identical only because the host loop serves budget-1 tails
    with an untagged plain step while the fused loop uses a
    ``usable = 0`` round — both draw from the full target
    distribution). ``temperature <= 0`` delegates to the byte-exact
    greedy :func:`speculative_generate_fused`."""
    if temperature <= 0.0:
        return speculative_generate_fused(
            target, t_params, draft, d_params, prompt_ids,
            max_new_tokens=max_new_tokens, k=k,
        )
    key_data = jnp.asarray(
        np.asarray(jax.random.key_data(jax.random.key(seed)))[None]
    )
    return _fused_run(
        target, t_params, draft, d_params, prompt_ids,
        max_new_tokens, k, True, key_data,
        jnp.asarray(np.asarray([temperature], np.float32)),
        jnp.asarray(np.asarray([top_k], np.int32)),
        jnp.asarray(np.asarray([top_p], np.float32)),
    )


def speculative_sample(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
) -> tuple[list[int], SpecStats]:
    """SAMPLED speculative generation for ONE prompt row (the
    Leviathan/Chen acceptance-rejection scheme — module docstring).

    The emitted stream is distributed exactly as plain target
    sampling under the same ``temperature``/``top_k``/``top_p`` warp
    (``tests/test_speculative_sampling.py`` pins this two ways: a
    synthetic-p/q kernel-level distribution check and an end-to-end
    total-variation bound), deterministic given ``seed``, and
    independent of draft quality — the draft only moves the SPEED
    (acceptance rate), never the distribution. ``temperature <= 0``
    delegates to the byte-exact greedy :func:`speculative_generate`.
    """
    if temperature <= 0.0:
        return speculative_generate(
            target, t_params, draft, d_params, prompt_ids,
            max_new_tokens=max_new_tokens, k=k,
        )
    b, p = prompt_ids.shape
    if b != 1:
        raise ValueError("speculative decoding is single-row (batch=1)")
    if target.vocab_size != draft.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    n = int(max_new_tokens)
    if p + n > target.max_positions or p + n > draft.max_positions:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({n}) exceeds a model window"
        )
    k = max(1, min(int(k), n))
    total_t = min(target.max_positions, p + n + k + 1)
    total_d = min(draft.max_positions, p + n + k + 1)

    from mlapi_tpu.models.gpt import decode_chunk_fn, prefill_fn

    key_data = jnp.asarray(
        np.asarray(jax.random.key_data(jax.random.key(seed)))[None]
    )
    temps = jnp.asarray(np.asarray([temperature], np.float32))
    topk_v = jnp.asarray(np.asarray([top_k], np.int32))
    topp_v = jnp.asarray(np.asarray([top_p], np.float32))
    z_pad = jnp.zeros((1,), jnp.int32)

    stats = SpecStats()
    prompt_ids = jnp.asarray(prompt_ids)
    # Target prefill SAMPLES the first token at stream index 0 —
    # identical to the plain sampled path's first draw.
    first, t_cache = prefill_fn(target, total_t)(
        t_params, prompt_ids, key_data, temps, z_pad, topk_v, topp_v,
    )
    t0 = int(np.asarray(first)[0])
    _, d_cache = _prefill(draft, d_params, prompt_ids, total_d)

    out: list[int] = [t0]
    t_upto, t_pend = p, [t0]
    d_upto, d_pend = p, [t0]

    while len(out) < n:
        budget = n - len(out)
        room = (
            t_upto + 1 + k + 1 <= total_t
            and d_upto + len(d_pend) + k <= total_d
        )
        if budget == 1 or not room:
            # One plain SAMPLED target step at the token's own
            # (untagged) stream index — the same per-token stream
            # discipline as the engine's chunk decoder.
            toks, t_cache, _ = decode_chunk_fn(target, 1)(
                t_params, t_cache,
                jnp.asarray(np.asarray([t_pend[0]], np.int32)),
                jnp.int32(t_upto), z_pad, temps, key_data,
                jnp.int32(len(out)), topk_v, topp_v,
                jnp.int32(0), jnp.int32(0),
            )
            nxt = int(np.asarray(toks)[0, 0])
            t_upto += 1
            d_pend.append(nxt)
            t_pend = [nxt]
            out.append(nxt)
            stats.fallback_steps += 1
            continue

        step0 = len(out)  # stream index of this round's first proposal
        d_cache, props, q_probs = propose_fn(
            draft, len(d_pend), k, True
        )(
            d_params, d_cache,
            jnp.asarray(np.asarray(d_pend, np.int32)),
            jnp.int32(d_upto), z_pad, key_data, temps, topk_v, topp_v,
            jnp.int32(step0),
        )
        d_upto += len(d_pend) + k - 1

        usable = min(k, budget - 1)
        t_cache, packed = sample_verify_fn(target, k + 1)(
            t_params, t_cache, jnp.int32(t_pend[0]), props,
            jnp.int32(t_upto), z_pad, q_probs, key_data, temps,
            topk_v, topp_v, jnp.int32(step0), jnp.int32(usable),
        )
        packed = np.asarray(packed)
        m = int(packed[k + 1])
        emitted = packed[: m + 1].tolist()
        out.extend(emitted)
        stats.rounds += 1
        stats.drafted += usable
        stats.accepted += m
        stats.emitted += m + 1
        stats.per_round.append(m + 1)

        t_upto += m + 1
        t_pend = [emitted[-1]]
        if m == k:
            # The draft never cached its own k-th proposal; it is
            # pending alongside the round's final token.
            d_pend = [int(packed[k - 1]), emitted[-1]]
        else:
            d_upto = t_upto
            d_pend = [emitted[-1]]
    return out[:n], stats
