"""Speculative decoding: a small DRAFT model proposes k tokens, the
TARGET model verifies them all in ONE block forward.

Decode on TPU is one full target-weight read per token; verification
reads the target weights once per ROUND of up to k+1 tokens, so with
an in-domain draft the target's HBM bill drops by the mean accepted
length. Greedy-exact: the emitted stream is byte-identical to plain
target-only greedy decoding (accepted drafts ARE the target's argmax;
the round's last token is the target's own argmax after them) — the
guarantee the tests pin, including with draft == target where every
round must accept the full k+1.

TPU-first mechanics worth noting:

- **Rollback is free.** Rejected draft positions leave stale K/V in
  the target cache, but attention masks ``idx <= pos`` and the next
  round overwrites them — no copies, no cache surgery, static shapes
  throughout.
- The verify block is ``extend_core(all_logits=True)`` — one fused
  program per (k+1) width, position-offset traced, so a generation
  compiles exactly three programs (target prefill, verify block,
  draft step) regardless of length.
- The draft runs single-token steps through the same
  ``decode_chunk_fn`` program the serving engine uses.

Batch-1 only: per-row acceptance lengths desynchronize cache
positions across rows, which the scalar-``pos`` decode layout cannot
express — batched serving gets its parallelism from continuous
batching instead; speculation is the SINGLE-STREAM latency lever.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SpecStats:
    rounds: int = 0
    drafted: int = 0
    accepted: int = 0
    emitted: int = 0
    fallback_steps: int = 0  # first-draft mismatch → plain decode step
    per_round: list = field(default_factory=list)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def tokens_per_round(self) -> float:
        return self.emitted / self.rounds if self.rounds else 0.0


@functools.cache
def _zero_key():
    """Greedy decoding never consumes randomness; one shared dummy
    key avoids rebuilding it in the per-token hot loop."""
    return jnp.asarray(
        np.asarray(jax.random.key_data(jax.random.key(0)))[None]
    )


def _prefill(model, params, prompt_ids, total):
    from mlapi_tpu.models.gpt import prefill_fn

    b, _ = prompt_ids.shape
    first, cache = prefill_fn(model, total)(
        params, prompt_ids, _zero_key(),
        jnp.zeros((b,), jnp.float32), jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.int32), jnp.ones((b,), jnp.float32),
    )
    return int(np.asarray(first)[0]), cache


def _step(model, params, cache, tok, pos):
    """One greedy decode step; returns (next_tok, cache)."""
    from mlapi_tpu.models.gpt import decode_chunk_fn

    toks, cache, _ = decode_chunk_fn(model, 1)(
        params, cache, jnp.asarray(np.asarray([tok], np.int32)),
        jnp.int32(pos), jnp.zeros((1,), jnp.int32),
        jnp.zeros((1,), jnp.float32), _zero_key(), jnp.int32(0),
        jnp.zeros((1,), jnp.int32), jnp.ones((1,), jnp.float32),
        jnp.int32(0), jnp.int32(0),
    )
    return int(np.asarray(toks)[0, 0]), cache


@functools.lru_cache(maxsize=32)
def verify_fn(model, width: int):
    """Jitted verify block: greedy argmax at every position of a
    ``[B, width]`` token block extended onto the target cache at a
    traced offset, honoring per-row left-pad masks (``n_pad``) so the
    serving engine's bucketed rows verify identically to unpadded
    library rows."""

    def _run(params, cache, block, pos0, n_pad):
        cache, logits = model.extend_core(
            params, cache, block, pos0, n_pad,
            jnp.int32(0), jnp.int32(0), all_logits=True,
        )
        return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return jax.jit(_run, donate_argnums=(1,))


def speculative_generate(
    target,
    t_params,
    draft,
    d_params,
    prompt_ids,
    *,
    max_new_tokens: int,
    k: int = 4,
) -> tuple[list[int], SpecStats]:
    """Greedy speculative generation for ONE prompt row.

    ``prompt_ids``: ``[1, P]`` int32 (no padding — callers bucket
    upstream if they care about compile reuse). Returns
    ``(token_ids, stats)``; ``token_ids`` equals plain target greedy
    decoding exactly.
    """
    b, p = prompt_ids.shape
    if b != 1:
        raise ValueError("speculative decoding is single-row (batch=1)")
    if target.vocab_size != draft.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    n = int(max_new_tokens)
    if p + n > target.max_positions or p + n > draft.max_positions:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({n}) exceeds a model window"
        )
    k = max(1, min(int(k), n))
    # Room for a full round's block (t0 + k drafts) past the last
    # needed position keeps every verify the same width.
    total_t = min(target.max_positions, p + n + k + 1)
    total_d = min(draft.max_positions, p + n + k + 1)

    stats = SpecStats()
    prompt_ids = jnp.asarray(prompt_ids)
    t0, t_cache = _prefill(target, t_params, prompt_ids, total_t)
    _, d_cache = _prefill(draft, d_params, prompt_ids, total_d)

    out: list[int] = [t0]
    # Per-model bookkeeping: `upto` = cache slots holding VALID
    # accepted content; `pend` = accepted tokens not yet written to
    # that model's cache (their slots start at `upto`). The target's
    # pend is always one token (the round's bonus); the draft's can be
    # two after a fully-accepted round (its k-th proposal was never
    # fed back to it).
    t_upto, t_pend = p, [t0]
    d_upto, d_pend = p, [t0]

    while len(out) < n:
        budget = n - len(out)
        room = (
            t_upto + 1 + k + 1 <= total_t
            and d_upto + len(d_pend) + k <= total_d
        )
        if budget == 1 or not room:
            # One plain target step. The draft is NOT consulted again
            # once fallback starts (budget exhaustion and the room
            # inequalities are both monotone under growing caches and
            # pending lists), so syncing its cache here would be pure
            # waste — accumulate its pending tokens instead, which
            # keeps the consume loop correct in the impossible-return
            # case and costs nothing.
            nxt, t_cache = _step(target, t_params, t_cache,
                                 t_pend[0], t_upto)
            t_upto += 1
            d_pend.append(nxt)
            t_pend = [nxt]
            out.append(nxt)
            stats.fallback_steps += 1
            continue

        # Draft phase: consume the pending accepted tokens (the last
        # consume's greedy output is the first proposal), then chain
        # k-1 more proposals.
        for tok in d_pend:
            d_tok, d_cache = _step(draft, d_params, d_cache, tok, d_upto)
            d_upto += 1
        proposals = [d_tok]
        while len(proposals) < k:
            d_tok, d_cache = _step(draft, d_params, d_cache, d_tok, d_upto)
            d_upto += 1
            proposals.append(d_tok)
        # d_upto now covers t0 + proposals[:-1]; proposals[-1] was
        # proposed but never fed back (its slot is unwritten).

        # Verify [t0, d1..dk] in ONE target block: argmax at position
        # i is the target's next token AFTER t0, d1..di.
        block = np.asarray([[t_pend[0], *proposals]], np.int32)
        t_cache, expect = verify_fn(target, k + 1)(
            t_params, t_cache, jnp.asarray(block), jnp.int32(t_upto),
            jnp.zeros((1,), jnp.int32),
        )
        expect = np.asarray(expect)[0]  # [k+1]
        # Only `usable` proposals can be emitted this round (the
        # bonus token takes the last budget slot); drafts beyond it
        # are neither accepted nor rejected — they don't count.
        usable = min(k, budget - 1)
        m = 0
        while m < usable and proposals[m] == int(expect[m]):
            m += 1
        bonus = int(expect[m])
        out.extend(proposals[:m])
        out.append(bonus)
        stats.rounds += 1
        stats.drafted += usable
        stats.accepted += m
        stats.emitted += m + 1
        stats.per_round.append(m + 1)

        t_upto += m + 1  # t0 + m accepted drafts are valid content
        t_pend = [bonus]
        if m == k:
            # Draft never cached its own k-th proposal: it is pending
            # alongside the bonus (consecutive slots from d_upto).
            d_pend = [proposals[-1], bonus]
        else:
            # Rewind over the draft's stale rejected tail; future
            # writes overwrite it and `pos <= upto` masks it until
            # then.
            d_upto = t_upto
            d_pend = [bonus]
    return out[:n], stats
