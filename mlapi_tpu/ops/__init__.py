"""TPU-native operator library.

The hot ops behind the model zoo, written against the hardware rather
than any reference implementation (the reference, ``main.py:21-22``,
has exactly one "op": a 1x4 sklearn matmul — everything here is the
capability scaled up TPU-first):

- ``attention``       — stable full softmax attention (the baseline).
- ``ring_attention``  — sequence-parallel blockwise attention with KV
                        rotation over a mesh axis (long-context path).
"""

from mlapi_tpu.ops.attention import full_attention
from mlapi_tpu.ops.ring_attention import ring_attention, ring_self_attention

__all__ = [
    "full_attention",
    "ring_attention",
    "ring_self_attention",
]
