"""TPU-native operator library.

The hot ops behind the model zoo, written against the hardware rather
than any reference implementation (the reference, ``main.py:21-22``,
has exactly one "op": a 1x4 sklearn matmul — everything here is the
capability scaled up TPU-first):

- ``attention``       — stable full softmax attention (the baseline).
- ``ring_attention``  — sequence-parallel blockwise attention with KV
                        rotation over a mesh axis (long-context path).
- ``quant``           — weight-only int8 quantization (serving HBM).
- ``speculative``     — draft-propose / target-verify decoding.
"""

from mlapi_tpu.ops.attention import full_attention
from mlapi_tpu.ops.quant import dequantize_tree, quantize_tree
from mlapi_tpu.ops.ring_attention import ring_attention, ring_self_attention
from mlapi_tpu.ops.speculative import (
    speculative_generate,
    speculative_generate_batched,
    speculative_generate_fused,
    speculative_sample,
    speculative_sample_batched,
    speculative_sample_fused,
)

__all__ = [
    "full_attention",
    "ring_attention",
    "ring_self_attention",
    "quantize_tree",
    "dequantize_tree",
    "speculative_generate",
    "speculative_generate_batched",
    "speculative_generate_fused",
    "speculative_sample",
    "speculative_sample_batched",
    "speculative_sample_fused",
]
