"""Ring attention — sequence-parallel softmax attention over a mesh axis.

Long context is a first-class capability here even though the
reference has no sequence models at all (SURVEY §2: max "sequence" is
4 tabular features, ``main.py:10-14``): a sequence too long for one
chip's HBM is split into per-device blocks along a ``seq`` mesh axis,
and attention runs blockwise with the K/V blocks rotating around the
ring via ``lax.ppermute`` — ICI-neighbor traffic only, overlapped by
XLA with the per-block matmuls. Softmax is accumulated online
(running max / denominator / numerator, the flash-attention
recurrence), so no device ever materialises an ``[L, L]`` score
matrix: per-device memory is O(L·L/n) score blocks and O(L/n·D)
activations.

Two entry points:

- ``ring_attention``       — the per-device computation, for use
                             inside an existing ``shard_map`` (axis
                             name + size passed in).
- ``ring_self_attention``  — convenience wrapper that shard_maps over
                             a mesh for you, given globally-sharded
                             ``[B, L, H, D]`` arrays.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mlapi_tpu.ops.attention import NEG


def _varying_like(x, like):
    """Cast ``x`` to carry ``like``'s varying-manual-axes (vma) type.

    Constants minted inside shard_map are "unvarying"; mixing them
    with varying values in loop carries / lax.switch branches is a
    type mismatch in jax 0.9's vma checker. ``lax.pcast`` refuses
    axes a value already varies over, so cast only the missing ones.
    """
    # jax.typeof / vma / lax.pcast exist only on newer jax; on older
    # releases (no vma checker) the cast is a no-op by construction.
    typeof = getattr(jax, "typeof", None)
    if typeof is None or not hasattr(jax.lax, "pcast"):
        return x
    want = getattr(typeof(like), "vma", None) or frozenset()
    have = getattr(typeof(x), "vma", None) or frozenset()
    missing = tuple(a for a in want if a not in have)
    if not missing:
        return x
    return jax.lax.pcast(x, missing, to="varying")


def ring_attention(
    q,
    k,
    v,
    mask=None,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = False,
    scale=None,
    block_impl: str = "einsum",
    zigzag: bool = False,
):
    """Blockwise ring attention for ONE device's sequence block.

    Call inside ``shard_map`` over ``axis_name``. ``q, k, v`` are the
    local blocks ``[B, Lb, H, D]`` (global L = Lb * axis_size, blocks
    laid out in ring order), ``mask`` the local binary key mask
    ``[B, Lb]``. Returns the local output block ``[B, Lb, H, D]`` in
    ``q.dtype``.

    ``axis_size`` must be the static size of ``axis_name`` (it sets
    the ring-step count; ``lax.axis_index`` is traced so it cannot).

    ``block_impl`` picks the per-block attention: ``"einsum"`` (XLA,
    the default) or ``"flash"`` — each ring step runs the Pallas
    flash kernel on its local block and the per-block (out, lse)
    pairs are merged exactly (SP × kernel composition). Both are
    differentiable (the flash VJP carries lse cotangents).

    Int8-KV boundary policy: a quantized ``{"q", "scale"}`` K/V
    operand dequantizes HERE, at the ring entry, before the blocks
    start rotating — the ppermute'd K/V blocks and the online-softmax
    state stay full-precision (rotating payload+scale pairs and
    dequantizing per ring step would re-do the multiply axis_size
    times for zero HBM savings: the blocks live on-device either
    way). See ``ops/quant.maybe_dequant_kv`` for the full rationale.
    """
    from mlapi_tpu.ops.quant import maybe_dequant_kv

    k = maybe_dequant_kv(k, q.dtype)
    v = maybe_dequant_kv(v, q.dtype)
    if zigzag:
        if not (causal and block_impl == "flash"):
            raise ValueError(
                "zigzag layout applies to causal flash-block ring "
                "attention (it balances causal work; non-causal work "
                "is already balanced)"
            )
        return _ring_flash_zigzag(
            q, k, v, mask, axis_name=axis_name, axis_size=axis_size,
            scale=scale,
        )
    if block_impl == "flash":
        return _ring_flash(
            q, k, v, mask, axis_name=axis_name, axis_size=axis_size,
            causal=causal, scale=scale,
        )
    if block_impl != "einsum":
        raise ValueError(f"unknown block_impl {block_impl!r}")
    b, lb, h, d = q.shape
    scale = (1.0 / d**0.5) if scale is None else scale
    if mask is None:
        mask = jnp.ones((b, lb), jnp.float32)
    mask = mask.astype(jnp.float32)

    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def update(src, kb, vb, maskb, m, l, o):
        """One online-softmax block update: fold the K/V block that
        originated on device ``src`` into (m, l, o) — running max
        [B,H,Lb], denominator [B,H,Lb], numerator [B,Lb,H,D]. Matmuls
        take native-dtype (bf16) inputs with f32 accumulation — the
        MXU recipe; only the softmax bookkeeping lives in f32."""
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q, kb,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        keep = maskb[:, None, None, :]  # [B,1,1,Lk] binary
        if causal:
            q_pos = my_idx * lb + jnp.arange(lb)
            k_pos = src * lb + jnp.arange(lb)
            keep = keep * (q_pos[:, None] >= k_pos[None, :])[None, None, :, :]
        scores = scores + (1.0 - keep) * NEG

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # exp(NEG - m_new) saturates to exp(0)=1 when a whole block is
        # masked — the explicit * keep zeroes those lanes, keeping the
        # recurrence NaN-free with finite masking (see ops.attention).
        p = jnp.exp(scores - m_new[..., None]) * keep
        corr = jnp.exp(m - m_new)  # [B,H,Lq]
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(q.dtype), vb,
            preferred_element_type=jnp.float32,
        )
        return m_new, l, o

    # The accumulators must carry q's varying-manual-axes type (JAX
    # tracks which mesh axes a value varies over inside shard_map;
    # fresh zeros are "unvarying" and would mismatch the loop carry).
    def varying(x):
        return _varying_like(x, q)

    # Block 0 (our own K/V) outside the loop, then rotate-and-fold
    # axis_size-1 times — permute first, so no rotation result is ever
    # computed and discarded (XLA can't DCE a collective in the body).
    m, l, o = update(
        my_idx, k, v, mask,
        varying(jnp.full((b, h, lb), NEG, jnp.float32)),
        varying(jnp.zeros((b, h, lb), jnp.float32)),
        varying(jnp.zeros((b, lb, h, d), jnp.float32)),
    )

    def body(t, carry):
        m, l, o, kb, vb, maskb = carry
        kb, vb, maskb = jax.lax.ppermute(
            (kb, vb, maskb), axis_name, perm=perm
        )
        # After t rotations we hold the block originally on device
        # (my_idx - t) mod n.
        m, l, o = update((my_idx - t) % axis_size, kb, vb, maskb, m, l, o)
        return m, l, o, kb, vb, maskb

    _, l, o, *_ = jax.lax.fori_loop(1, axis_size, body, (m, l, o, k, v, mask))

    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]  # [B,Lq,H,1]
    return (o / denom).astype(q.dtype)


def zigzag_perm(length: int, n: int) -> "np.ndarray":
    """Global→zigzag index permutation: the sequence splits into
    ``2n`` stripes and device ``i`` gets stripes ``(i, 2n-1-i)``.

    Why: under the plain layout, causal ring attention is load-
    imbalanced — device 0's block attends 1 block while device n-1's
    attends all n, and since devices run in lockstep between
    ``ppermute`` steps, wall time is ~n full-block flash units. With
    the zigzag pairing every (holder, source) step costs EXACTLY two
    half-block units on every device:

    - past   (src < self): both local stripes attend the source's
      EARLY stripe only (its late stripe is entirely in their future)
      → ``flash(q, k_early)``: 2 half-units.
    - future (src > self): only the local LATE stripe attends, but it
      attends BOTH source stripes → ``flash(q_late, k)``: 2 half-units.
    - diagonal: local causal flash over the pair (local order is
      globally ascending, so plain causal masking is exact): ~2.

    Total causal wall time: n × 2 half-units ≈ half of the plain
    layout — the standard zigzag/striped ring-attention trick,
    expressed as one gather before ``shard_map`` and its inverse
    after.
    """
    import numpy as np

    if length % (2 * n):
        raise ValueError(
            f"zigzag needs length divisible by 2*n ({2 * n}), got {length}"
        )
    ls = length // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * ls, (i + 1) * ls))
        order.extend(range((2 * n - 1 - i) * ls, (2 * n - i) * ls))
    return np.asarray(order, np.int32)


def _ring_flash_zigzag(q, k, v, mask, *, axis_name, axis_size, scale):
    """Causal ring attention over the ZIGZAG layout: the local block
    is two stripes (early half E at global stripe ``i``, late half L
    at stripe ``2n-1-i``). See :func:`zigzag_perm` for the balance
    argument. Inputs/outputs are in zigzag order; callers permute.
    """
    from mlapi_tpu.ops.pallas import flash_attention_with_lse

    b, lb, h, d = q.shape
    half = lb // 2

    def varying(x):
        return _varying_like(x, q)

    if mask is None:
        mask = varying(jnp.ones((b, lb), jnp.float32))
    mask = mask.astype(jnp.float32)
    interpret = jax.default_backend() != "tpu"
    flash = functools.partial(
        flash_attention_with_lse, scale=scale, interpret=interpret
    )

    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def block(src, kb, vb, maskb):
        """(out, lse) of the local stripe-pair against source ``src``'s
        stripe-pair. Each branch costs two half-block flash units."""

        def past(args):
            kb, vb, maskb = args
            # Source's early stripe is past for BOTH local stripes;
            # its late stripe is future for both.
            return flash(q, kb[:, :half], vb[:, :half], maskb[:, :half])

        def diag(args):
            kb, vb, maskb = args
            return flash(q, kb, vb, maskb, causal=True)

        def future(args):
            kb, vb, maskb = args
            # Only the local LATE stripe attends (both source stripes
            # precede it); the early stripe sees nothing here.
            o_l, lse_l = flash(q[:, half:], kb, vb, maskb)
            o = jnp.concatenate(
                [varying(jnp.zeros((b, half, h, d), q.dtype)), o_l], axis=1
            )
            lse = jnp.concatenate(
                [varying(jnp.full((b, h, half), NEG, jnp.float32)), lse_l],
                axis=-1,
            )
            return o, lse

        return jax.lax.switch(
            jnp.sign(src - my_idx) + 1, [past, diag, future], (kb, vb, maskb)
        )

    def merge(o1, s1, o2, s2):
        m = jnp.maximum(s1, s2)
        w1 = jnp.exp(s1 - m)
        w2 = jnp.exp(s2 - m)
        wsum = jnp.maximum(w1 + w2, 1e-30)
        w1t = (w1 / wsum).transpose(0, 2, 1)[..., None]
        w2t = (w2 / wsum).transpose(0, 2, 1)[..., None]
        o = o1.astype(jnp.float32) * w1t + o2.astype(jnp.float32) * w2t
        return o.astype(o1.dtype), m + jnp.log(wsum)

    o_acc, lse_acc = block(my_idx, k, v, mask)
    o_acc, lse_acc = varying(o_acc), varying(lse_acc)

    def body(t, carry):
        o_acc, lse_acc, kb, vb, maskb = carry
        kb, vb, maskb = jax.lax.ppermute(
            (kb, vb, maskb), axis_name, perm=perm
        )
        o_b, lse_b = block((my_idx - t) % axis_size, kb, vb, maskb)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_b, lse_b)
        return o_acc, lse_acc, kb, vb, maskb

    o_acc, *_ = jax.lax.fori_loop(
        1, axis_size, body, (o_acc, lse_acc, k, v, mask)
    )
    return o_acc.astype(q.dtype)


def _ring_flash(q, k, v, mask, *, axis_name, axis_size, causal, scale):
    """Ring attention whose per-block computation is the Pallas flash
    kernel: each step computes ``flash(q, k_block, v_block)`` with its
    log-sum-exp, and blocks merge by the exact lse-weighted average

        m = max(s1, s2); o = (o1·e^{s1-m} + o2·e^{s2-m}) / (e^{s1-m}+e^{s2-m})

    Causal structure is whole-block: a K/V block strictly in the past
    attends fully (plain flash), the diagonal block runs causal flash
    (positions align — both offsets are ``my_idx·Lb``), and future
    blocks are skipped via an lse of -inf-like ``NEG`` so they carry
    zero merge weight. ``lax.switch`` on the traced block origin keeps
    it one compiled program.
    """
    from mlapi_tpu.ops.pallas import flash_attention_with_lse

    b, lb, h, d = q.shape

    # Everything entering flash / the lax.switch must carry q's
    # varying-manual-axes type: constants minted inside shard_map
    # (the default mask, the future-branch zeros) are "unvarying"
    # and would mismatch varying branch outputs / kernel operands.
    def varying(x):
        return _varying_like(x, q)

    if mask is None:
        mask = varying(jnp.ones((b, lb), jnp.float32))
    mask = mask.astype(jnp.float32)
    interpret = jax.default_backend() != "tpu"
    flash = functools.partial(
        flash_attention_with_lse, scale=scale, interpret=interpret
    )

    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def block(src, kb, vb, maskb):
        """(out, lse) of q against one K/V block."""
        if not causal:
            return flash(q, kb, vb, maskb)

        def past(args):
            kb, vb, maskb = args
            return flash(q, kb, vb, maskb)

        def diag(args):
            kb, vb, maskb = args
            return flash(q, kb, vb, maskb, causal=True)

        def future(args):
            return (
                varying(jnp.zeros((b, lb, h, d), q.dtype)),
                varying(jnp.full((b, h, lb), NEG, jnp.float32)),
            )

        # sign(src - my_idx): -1 past, 0 diagonal, +1 future.
        return jax.lax.switch(
            jnp.sign(src - my_idx) + 1, [past, diag, future], (kb, vb, maskb)
        )

    def merge(o1, s1, o2, s2):
        m = jnp.maximum(s1, s2)
        w1 = jnp.exp(s1 - m)
        w2 = jnp.exp(s2 - m)
        wsum = jnp.maximum(w1 + w2, 1e-30)
        w1t = (w1 / wsum).transpose(0, 2, 1)[..., None]  # [B,Lb,H,1]
        w2t = (w2 / wsum).transpose(0, 2, 1)[..., None]
        o = o1.astype(jnp.float32) * w1t + o2.astype(jnp.float32) * w2t
        return o.astype(o1.dtype), m + jnp.log(wsum)

    o_acc, lse_acc = block(my_idx, k, v, mask)
    o_acc, lse_acc = varying(o_acc), varying(lse_acc)

    def body(t, carry):
        o_acc, lse_acc, kb, vb, maskb = carry
        kb, vb, maskb = jax.lax.ppermute(
            (kb, vb, maskb), axis_name, perm=perm
        )
        o_b, lse_b = block((my_idx - t) % axis_size, kb, vb, maskb)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_b, lse_b)
        return o_acc, lse_acc, kb, vb, maskb

    o_acc, *_ = jax.lax.fori_loop(
        1, axis_size, body, (o_acc, lse_acc, k, v, mask)
    )
    return o_acc.astype(q.dtype)


def ring_self_attention(
    mesh,
    q,
    k,
    v,
    mask=None,
    *,
    seq_axis: str = "seq",
    batch_axis: str | None = "data",
    head_axis: str | None = None,
    causal: bool = False,
    scale=None,
    block_impl: str = "einsum",
    zigzag: bool = False,
):
    """Ring attention over globally-shaped ``[B, L, H, D]`` arrays.

    Shards L over ``mesh``'s ``seq_axis`` (and B over ``batch_axis``
    when the mesh has it), runs :func:`ring_attention` per device, and
    returns the global ``[B, L, H, D]`` result. L must divide evenly
    by the seq-axis size; pad upstream (padded keys masked out via
    ``mask``).

    ``head_axis`` additionally shards the head dim (tensor parallel —
    attention is independent per head, so SP x TP composes with no
    extra communication: K/V rotation stays within each head shard).

    ``zigzag=True`` (causal flash only) interleaves the sequence so
    each device holds stripes ``(i, 2n-1-i)`` — balancing causal work
    to two half-block flash units per ring step on EVERY device
    (~2x wall-time win over the plain layout; see :func:`zigzag_perm`).
    The permutation is one gather before ``shard_map`` and its
    inverse after; callers see plain global order.

    Quantized ``{"q", "scale"}`` K/V operands dequantize at THIS
    boundary, before the shard_map (specs and the ring payload are
    full-precision arrays — see :func:`ring_attention`).
    """
    from mlapi_tpu.ops.quant import maybe_dequant_kv

    k = maybe_dequant_kv(k, q.dtype)
    v = maybe_dequant_kv(v, q.dtype)
    n = mesh.shape[seq_axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{seq_axis!r} of size {n}; pad first"
        )
    bspec = batch_axis if batch_axis in mesh.axis_names else None
    if bspec and q.shape[0] % mesh.shape[bspec]:
        bspec = None  # e.g. a single-request serving batch on a DP mesh
    hspec = head_axis if head_axis in mesh.axis_names else None
    if hspec and q.shape[2] % mesh.shape[hspec]:
        hspec = None
    qkv_spec = P(bspec, seq_axis, hspec, None)
    mask_spec = P(bspec, seq_axis)

    inner = functools.partial(
        ring_attention,
        axis_name=seq_axis,
        axis_size=n,
        causal=causal,
        scale=scale,
        block_impl=block_impl,
        zigzag=zigzag,
    )
    # jax.shard_map graduated from jax.experimental between releases;
    # accept either spelling so the SP path runs on both. The old
    # experimental checker has no replication rule for pallas_call
    # (the vma type system that replaced it handles this), so it
    # needs check_rep=False to admit the flash block kernels.
    if hasattr(jax, "shard_map"):
        _shard_map = jax.shard_map
        extra = {}
    else:
        from jax.experimental.shard_map import shard_map as _shard_map

        extra = {"check_rep": False}
    mapped = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        **extra,
    )
    if mask is None:
        mask = jnp.ones(q.shape[:2], jnp.float32)
    if zigzag:
        # Interleave so each device's CONTIGUOUS shard_map slice is
        # its stripe pair; undo on the way out. One gather each way.
        perm = jnp.asarray(zigzag_perm(q.shape[1], n))
        inv = jnp.argsort(perm)
        out = mapped(
            q[:, perm], k[:, perm], v[:, perm], mask[:, perm]
        )
        return out[:, inv]
    # shard_map reshards inputs to in_specs itself, eagerly or under
    # jit — no explicit placement needed here.
    return mapped(q, k, v, mask)
