"""Weight-only int8 quantization for serving.

Decode is weight-bandwidth-bound on TPU (every generated token re-reads
every matmul weight from HBM), so halving the bytes per weight is worth
up to ~2x decode throughput and exactly 2x parameter HBM — which is
also the difference between a model fitting one chip or not. This is
*weight-only* quantization: activations stay in the model's compute
dtype, and the dequantized product `q * scale` feeds the matmul inside
the jitted program, where XLA fuses the convert+multiply into the dot's
operand read — the full-precision weight tensor is never materialized
in HBM.

Scheme: symmetric per-channel int8 over the LAST axis (for an
``[in, out]`` kernel that is per-output-channel — the standard choice;
for an ``[vocab, hidden]`` embedding it is per-hidden-column). A
quantized leaf is replaced by ``{"q": int8[...], "scale": f32[...,1]}``
(scale keeps the reduced axes at length 1 so dequantization is one
broadcast multiply). Vectors (layernorm scales, biases) and small
tensors stay float — they are noise in both HBM and accuracy terms.

The reference (`/root/reference/main.py`) serves a pickled sklearn
model with no numeric-format control at all; this module exists for
the generative/serving scale the reference never reaches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Leaves smaller than this stay float: quantizing a 1 KB bias saves
# nothing and costs accuracy.
MIN_QUANT_SIZE = 4096


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x) == {"q", "scale"}


def quantize_tree(params, *, min_size: int = MIN_QUANT_SIZE):
    """Quantize every float leaf with ``ndim >= 2`` and
    ``size >= min_size`` to per-channel symmetric int8; other leaves
    pass through unchanged. Host-side, one pass, no device programs —
    call once at checkpoint load."""

    def leaf(x):
        a = np.asarray(x)
        if (
            a.ndim < 2
            or a.size < min_size
            or not np.issubdtype(a.dtype, np.floating)
        ):
            return x
        amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)),
                      keepdims=True)
        scale = (amax / 127.0).astype(np.float32)
        scale = np.where(scale == 0.0, 1.0, scale)
        q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
        return {"q": q, "scale": scale}

    return jax.tree.map(leaf, params)


def dequantize_tree(params, dtype=jnp.float32):
    """Traced inverse: expand every quantized leaf back to ``dtype``
    inside a jitted program. XLA fuses the convert+multiply into each
    weight's consumer, so the expansion costs no extra HBM round
    trip."""

    def leaf(x):
        if _is_quant_leaf(x):
            return x["q"].astype(dtype) * x["scale"].astype(dtype)
        return x

    return jax.tree.map(leaf, params, is_leaf=_is_quant_leaf)


# --- int8 KV-cache quantization ----------------------------------------
#
# Decode at generation scale is CACHE-bandwidth-bound, not just
# weight-bound: every decoded token re-reads every layer's [B, L, H, D]
# K and V from HBM, and past modest batch x context the cache bytes
# dominate the weights. The same move that halved weight HBM applies:
# store the cache as int8 with SYMMETRIC PER-TOKEN-PER-HEAD scales
# (amax over the head_dim axis), quantize fused into the append path,
# dequantize fused into the attention read — the full-precision cache
# is never materialized in HBM. A quantized cache layer is
# ``{"k_q": int8[B, L, H, D], "k_scale": f32[B, L, H, 1], "v_q": ...,
# "v_scale": ...}`` (this repo's cache layout is [B, L, H, D]; the
# scale keeps the reduced axis at length 1 so dequantization is one
# broadcast multiply, exactly like the weight scheme above).
#
# Per-token-per-head granularity is the accuracy sweet spot for KV:
# per-tensor scales are wrecked by attention-sink outlier tokens, while
# finer-than-head granularity buys nothing the f32 softmax doesn't
# already absorb. The f32 scale costs 4 bytes per (token, head) next
# to D int8 payload bytes — <= 2x total reduction asymptotically in D.

KV_FORMATS = ("none", "int8")


def kv_is_quantized_layer(layer: dict) -> bool:
    """Is this per-layer cache dict in the quantized format?"""
    return "k_q" in layer


# --- paged KV-cache layout ---------------------------------------------
#
# The contiguous layouts above allocate one [B, L, H, D] buffer per
# batch slot, sized to the slot's whole cache TIER — every sequence
# pays for its padded tier length, and a shared prefix is COPIED into
# every row. The paged layout breaks the cache into fixed-size pages
# and adds one indirection: a per-layer device POOL of pages plus a
# per-row PAGE TABLE mapping virtual tiles to pool pages. A paged
# layer reuses the contiguous key names with pool-shaped leaves and
# carries the table alongside:
#
#   ``{"k": [P, page, H, D], "v": ..., "table": int32[B, NP]}``
#   (int8: the payload+scale quartet with the same pool leading dims)
#
# so ``kv_is_quantized_layer`` keeps working and the presence of
# ``"table"`` is the ONE paged predicate. Virtual slot ``v`` of row
# ``b`` lives at ``pool[table[b, v // page], v % page]``; page id 0 is
# the permanently-reserved NULL page — unallocated table entries point
# at it, its reads are always masked (a row only reads slots it
# wrote), and dummy/finished rows write their dead tokens into it.
# Allocation, refcounts, sharing and copy-on-write are HOST metadata
# (serving/paged_pool.py); these seams only do the device arithmetic.

KV_PAGED_NULL = 0  # reserved pool page id: unallocated / dead writes


def kv_is_paged_layer(layer: dict) -> bool:
    """Is this per-layer cache dict in the paged (pool + page-table)
    layout?"""
    return isinstance(layer, dict) and "table" in layer


def kv_layer_page_size(layer: dict) -> int:
    """Tokens per page of a paged layer (pool dim 1)."""
    leaf = layer["k_q"] if kv_is_quantized_layer(layer) else layer["k"]
    return leaf.shape[1]


def _paged_coords(layer: dict, pos, u: int):
    """``(pids, offs)`` both ``[B, u]`` for virtual slots
    ``[pos, pos+u)`` (``pos`` scalar or ``[B]``) of every row."""
    table = layer["table"]
    page = kv_layer_page_size(layer)
    b = table.shape[0]
    posv = pos[:, None] if jnp.ndim(pos) else pos
    vpos = jnp.broadcast_to(posv + jnp.arange(u)[None, :], (b, u))
    pids = jnp.take_along_axis(table, vpos // page, axis=1)
    return pids, vpos % page


def make_paged_pools(model, num_pages: int, page_size: int) -> dict:
    """Device page pools for every layer of ``model``'s cache format:
    each contiguous ``[1, page, H, D]``-shaped leaf becomes a
    ``[num_pages, page, H, D]`` pool (scales ride along for int8).
    Page 0 is the null page — callers must never allocate it."""
    proto = jax.eval_shape(lambda: model.init_cache(1, page_size))
    return {
        ln: {
            name: jnp.zeros((num_pages,) + leaf.shape[1:], leaf.dtype)
            for name, leaf in layer.items()
        }
        for ln, layer in proto.items()
    }


def paged_cache_tree(pools: dict, table) -> dict:
    """Assemble the paged cache pytree a decode/extend program takes:
    every layer's pool leaves plus that layer's page-table mirror.
    ``table`` is the HOST ``[B, NP]`` int32 array (the source of
    truth); each layer gets its OWN device upload — donated programs
    reject the same buffer appearing twice, and per-layer ``[B, NP]``
    int32 uploads are noise next to one cache read. ``pools`` may be
    bare pool layers or a previous program's returned cache (stale
    tables are replaced)."""
    host = np.asarray(table, np.int32)
    return {
        ln: {
            **{n: a for n, a in layer.items() if n != "table"},
            "table": jnp.asarray(host),
        }
        for ln, layer in pools.items()
    }


def paged_pools_of(cache: dict) -> dict:
    """Inverse of :func:`paged_cache_tree`: strip the table mirrors,
    keeping the (possibly donated-updated) pool arrays."""
    return {
        ln: {n: a for n, a in layer.items() if n != "table"}
        for ln, layer in cache.items()
    }


def kv_page_bytes(model, page_size: int) -> int:
    """Exact per-page device bytes across every layer — pure
    dtype/shape arithmetic (the capacity-model unit the paged bench
    asserts against, never wall-clock)."""
    proto = jax.eval_shape(lambda: model.init_cache(1, page_size))
    return sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for layer in proto.values()
        for leaf in layer.values()
    )


def kv_tree_bytes(tree) -> int:
    """Exact device bytes of a cache pytree from dtype/shape
    arithmetic alone — the unit the adopt-copy accounting uses
    (``generate.prefill_adopt_bytes``): an adopt scatter moves exactly
    the bytes of the contiguous tree it copies into pool pages, so the
    gauge is deterministic, never wall-clock."""
    return sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(tree)
    )


def kv_quantize(x):
    """``[..., D]`` float K or V block → ``(q int8[..., D],
    scale f32[..., 1])``, symmetric per-token-per-head (amax over the
    last axis). Runs inside the jitted append, so XLA fuses the
    abs-max/divide/round into the cache write."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q, scale, dtype):
    """Traced inverse: int8 payload x broadcast scale → ``dtype``.
    WHERE this expansion happens decides whether the full-precision
    tensor crosses HBM — on the einsum read path (``kv_cache_kv``)
    the dequantized operand materializes at the read seam, so int8
    saves storage but not read traffic there; only the flash
    decode/extend kernels (``ops/pallas/decode_attention``), which
    run this exact arithmetic per tile in registers, keep int8 on
    the bus for the read — since r11 that covers every cache-reading
    span (decode steps AND multi-token extends), not just decode.
    See :func:`maybe_dequant_kv` for the full three-way policy."""
    return q.astype(dtype) * scale.astype(dtype)


def kv_cache_append(layer: dict, k_new, v_new, pos, cdt) -> dict:
    """Write a ``[B, U, H, D]`` K/V block into a fixed-shape cache
    layer at slot ``pos`` — THE append seam both cache formats share
    (every decoder family's prefill/decode/extend writes through it).

    ``pos`` scalar: one fused slice-update writes every row at the
    same slot (the serving layout). ``pos`` per-row ``[B]``: the write
    vmaps over rows so each lands at its own slot (batched
    speculation's desynchronized layout). For a quantized layer the
    block is quantized first and the int8 payload + f32 scale written
    by the same slice-updates — quantization is fused into the append,
    and the full-precision block dies in registers.
    """
    if kv_is_quantized_layer(layer):
        kq, ks = kv_quantize(k_new)
        vq, vs = kv_quantize(v_new)
        updates = {"k_q": kq, "k_scale": ks, "v_q": vq, "v_scale": vs}
    else:
        updates = {"k": k_new.astype(cdt), "v": v_new.astype(cdt)}

    if kv_is_paged_layer(layer):
        # Paged write: ONE scatter per leaf lands every row's block at
        # its table-mapped pool coordinates — scalar and per-row pos,
        # single-token and U-token blocks, all through the same index
        # arithmetic (a block may span pages; the [B, U] coordinate
        # arrays express that for free). Rows whose table entry is the
        # null page (dummies, finished rows) scatter their dead tokens
        # there; null-page slots are never read unmasked.
        pids, offs = _paged_coords(layer, pos, k_new.shape[1])
        out = {"table": layer["table"]}
        for name, upd in updates.items():
            out[name] = layer[name].at[pids, offs].set(
                upd.astype(layer[name].dtype)
            )
        return out

    if jnp.ndim(pos):
        row_write = jax.vmap(
            lambda c, n, p: jax.lax.dynamic_update_slice(
                c, n, (p,) + (0,) * (c.ndim - 1)
            )
        )
        return {
            name: row_write(layer[name], upd, pos)
            for name, upd in updates.items()
        }
    return {
        name: jax.lax.dynamic_update_slice(
            layer[name], upd, (0, pos) + (0,) * (upd.ndim - 2)
        )
        for name, upd in updates.items()
    }


def kv_cache_kv(layer: dict, cdt):
    """The attention-read seam: a cache layer → ``(k, v)`` in the
    compute dtype. Quantized layers dequantize here, INSIDE the jitted
    program, right at the einsum operand — see :func:`kv_dequantize`
    for why this reads int8 from HBM, not floats. Paged layers GATHER
    their pool pages into the contiguous ``[B, L, H, D]`` oracle
    layout first (``pool[table]`` + reshape) — the einsum decode path
    over a paged cache is the contiguous reference with one extra
    gather, which is exactly what makes it the parity oracle for the
    page-table flash kernel (the kernel reads the pages in place)."""
    if kv_is_paged_layer(layer):
        table = layer["table"]

        def gather(pool):
            g = pool[table]  # [B, NP, page, ...]
            return g.reshape((g.shape[0], -1) + g.shape[3:])

        if kv_is_quantized_layer(layer):
            return (
                kv_dequantize(
                    gather(layer["k_q"]), gather(layer["k_scale"]), cdt
                ),
                kv_dequantize(
                    gather(layer["v_q"]), gather(layer["v_scale"]), cdt
                ),
            )
        return gather(layer["k"]), gather(layer["v"])
    if kv_is_quantized_layer(layer):
        return (
            kv_dequantize(layer["k_q"], layer["k_scale"], cdt),
            kv_dequantize(layer["v_q"], layer["v_scale"], cdt),
        )
    return layer["k"], layer["v"]


def kv_cache_seq_len(cache: dict) -> int:
    """Static sequence capacity of a cache pytree, any layout: the
    contiguous buffer length, or pages-per-row x page size for the
    paged layout (the VIRTUAL length every mask/position helper sees —
    paging changes where bytes live, never the slot arithmetic)."""
    layer = cache["layer_0"]
    leaf = layer["k_q"] if kv_is_quantized_layer(layer) else layer["k"]
    if kv_is_paged_layer(layer):
        return layer["table"].shape[1] * leaf.shape[1]
    return leaf.shape[1]


def init_kv_cache(batch: int, max_len: int, heads: int, head_dim: int,
                  cdt, kv_quant: str = "none") -> dict:
    """One layer's fixed-shape KV buffers in the requested format —
    the single definition of both cache layouts (each decoder family's
    ``init_cache`` maps it over its layers)."""
    if kv_quant == "int8":
        return {
            "k_q": jnp.zeros((batch, max_len, heads, head_dim), jnp.int8),
            "k_scale": jnp.zeros((batch, max_len, heads, 1), jnp.float32),
            "v_q": jnp.zeros((batch, max_len, heads, head_dim), jnp.int8),
            "v_scale": jnp.zeros((batch, max_len, heads, 1), jnp.float32),
        }
    if kv_quant != "none":
        raise ValueError(
            f"unknown kv_quant {kv_quant!r}; expected one of {KV_FORMATS}"
        )
    return {
        "k": jnp.zeros((batch, max_len, heads, head_dim), cdt),
        "v": jnp.zeros((batch, max_len, heads, head_dim), cdt),
    }


@functools.lru_cache(maxsize=32)
def _forced_argmax_fn(model, n_steps: int):
    """Jitted teacher-forced decode: prefill the prompt, then feed a
    FIXED token stream through ``decode_step`` and emit each step's
    argmax — the per-step top-1 prediction of the model's cache
    format, decoupled from error compounding (a free-running
    comparison is meaningless past the first divergence)."""

    def _run(params, prompt_ids, forced, n_pad):
        p = prompt_ids.shape[1]
        cache, _ = model.prefill_core(
            params, prompt_ids, n_pad, p + n_steps + 1
        )

        def step(carry, tok):
            cache, pos = carry
            logits, cache = model.decode_step(
                params, cache, tok[:, None], pos, n_pad
            )
            return (cache, pos + 1), jnp.argmax(
                logits, axis=-1
            ).astype(jnp.int32)

        (_, _), outs = jax.lax.scan(
            step, (cache, jnp.int32(p)), forced.T
        )
        return outs.T

    return jax.jit(_run)


def kv_greedy_agreement(model, params, prompt_ids, max_new_tokens: int,
                        pad_lens=None, quant_overrides=None) -> float:
    """The decode-quality guard for int8 KV caches: greedy top-1
    token agreement of the int8-cache decode vs the full-precision
    cache, TEACHER-FORCED on the full-precision greedy stream.

    The reference stream is the ``kv_quant="none"`` model's greedy
    generation; both cache formats then replay that exact stream and
    the per-step argmaxes are compared. The first token is excluded —
    it comes from the prefill forward, which attends full-precision
    in-register under BOTH formats and cannot disagree — so every
    compared position actually read the quantized cache. ``model`` is
    the base decoder config (any decoder family with the ``kv_quant``
    field); returns the agreement fraction in ``[0, 1]``.

    ``quant_overrides``: extra dataclass fields replaced on the
    QUANTIZED side only — e.g. ``{"decode_attn_impl": "flash"}`` pins
    the flash-decode kernel's int8 tile path against the
    full-precision EINSUM reference (the oracle both decode impls
    answer to), so the guard then covers kernel math and quantization
    error together.
    """
    import dataclasses

    if max_new_tokens < 2:
        # Position 0 comes from the prefill forward and is excluded,
        # so a 1-token window would compare nothing (NaN, not 1.0).
        raise ValueError("kv_greedy_agreement needs max_new_tokens >= 2")
    base = dataclasses.replace(model, kv_quant="none")
    quant = dataclasses.replace(
        model, kv_quant="int8", **(quant_overrides or {})
    )
    b, p = prompt_ids.shape
    n_pad = (
        jnp.zeros((b,), jnp.int32) if pad_lens is None
        else jnp.asarray(pad_lens, jnp.int32)
    )
    ref = base.generate(
        params, prompt_ids, max_new_tokens=max_new_tokens,
        pad_lens=None if pad_lens is None else pad_lens,
    )
    forced = jnp.asarray(ref)[:, :-1]  # step t predicts ref[:, t+1]
    got = _forced_argmax_fn(quant, max_new_tokens - 1)(
        params, jnp.asarray(prompt_ids), forced, n_pad
    )
    return float(
        np.mean(np.asarray(got) == np.asarray(ref)[:, 1:])
    )


def maybe_dequant_kv(x, dtype=None):
    """Kernel-boundary leg of the THREE-WAY int8-KV dequant policy.
    Where a quantized ``{"q", "scale"}`` K/V operand expands depends
    on which path is reading and what bounds it:

    1. **Prefill / full-sequence kernels (here — Pallas flash,
       ring)**: dequantize AT THE KERNEL BOUNDARY, one fused
       convert+multiply feeding the first tile load. These shapes are
       MXU-bound (O(L²) FLOPs over O(L) bytes), so teaching them an
       int8 tile path would complicate every kernel for a read that
       isn't the bottleneck. (These kernels attend a LIVE full
       sequence; cache-backed spans are leg 2's.)
    2. **Cache reads, ``decode_attn_impl="flash"``
       (``ops/pallas/decode_attention``)**: dequantize PER TILE
       IN-KERNEL — int8 payload + scale tiles DMA to VMEM and expand
       in registers. Cache reads are bandwidth-bound (O(U·L) FLOPs
       over O(L) bytes at small U), so the byte format of the read
       IS the lever: this is the only leg where int8 crosses HBM on
       the attention read. Since r11 this leg covers single-token
       decode steps AND multi-token extend spans (chunked prefill,
       admission, speculative verify) — flash-extend is the same
       tile path with a U-row Q tile.
    3. **Cache reads, ``decode_attn_impl="einsum"``
       (``kv_cache_kv``)**: dequantize at the read seam feeding the
       decode/extend einsum — the reference oracle. The
       full-precision operand materializes between the dequant and
       the einsum, so this leg realizes the int8 saving in storage
       only.

    Anything that is neither an array nor a quant pair is rejected
    loudly."""
    if isinstance(x, dict):
        if _is_quant_leaf(x):
            return kv_dequantize(
                x["q"], x["scale"], dtype or x["scale"].dtype
            )
        raise TypeError(
            "attention kernels take arrays or {'q', 'scale'} quantized "
            f"pairs, got dict with keys {sorted(x)}"
        )
    return x


def is_quantized(params) -> bool:
    found = False

    def leaf(x):
        nonlocal found
        found = found or _is_quant_leaf(x)
        return x

    jax.tree.map(leaf, params, is_leaf=_is_quant_leaf)
    return found


def quantized_bytes(params) -> tuple[int, int]:
    """(bytes as stored, bytes if fully f32) — the HBM story."""
    stored = full = 0

    def leaf(x):
        nonlocal stored, full
        if _is_quant_leaf(x):
            stored += x["q"].size + 4 * x["scale"].size
            full += 4 * x["q"].size
        else:
            a = np.asarray(x)
            stored += a.nbytes
            full += a.nbytes
        return x

    jax.tree.map(leaf, params, is_leaf=_is_quant_leaf)
    return stored, full
