"""Weight-only int8 quantization for serving.

Decode is weight-bandwidth-bound on TPU (every generated token re-reads
every matmul weight from HBM), so halving the bytes per weight is worth
up to ~2x decode throughput and exactly 2x parameter HBM — which is
also the difference between a model fitting one chip or not. This is
*weight-only* quantization: activations stay in the model's compute
dtype, and the dequantized product `q * scale` feeds the matmul inside
the jitted program, where XLA fuses the convert+multiply into the dot's
operand read — the full-precision weight tensor is never materialized
in HBM.

Scheme: symmetric per-channel int8 over the LAST axis (for an
``[in, out]`` kernel that is per-output-channel — the standard choice;
for an ``[vocab, hidden]`` embedding it is per-hidden-column). A
quantized leaf is replaced by ``{"q": int8[...], "scale": f32[...,1]}``
(scale keeps the reduced axes at length 1 so dequantization is one
broadcast multiply). Vectors (layernorm scales, biases) and small
tensors stay float — they are noise in both HBM and accuracy terms.

The reference (`/root/reference/main.py`) serves a pickled sklearn
model with no numeric-format control at all; this module exists for
the generative/serving scale the reference never reaches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Leaves smaller than this stay float: quantizing a 1 KB bias saves
# nothing and costs accuracy.
MIN_QUANT_SIZE = 4096


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x) == {"q", "scale"}


def quantize_tree(params, *, min_size: int = MIN_QUANT_SIZE):
    """Quantize every float leaf with ``ndim >= 2`` and
    ``size >= min_size`` to per-channel symmetric int8; other leaves
    pass through unchanged. Host-side, one pass, no device programs —
    call once at checkpoint load."""

    def leaf(x):
        a = np.asarray(x)
        if (
            a.ndim < 2
            or a.size < min_size
            or not np.issubdtype(a.dtype, np.floating)
        ):
            return x
        amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)),
                      keepdims=True)
        scale = (amax / 127.0).astype(np.float32)
        scale = np.where(scale == 0.0, 1.0, scale)
        q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
        return {"q": q, "scale": scale}

    return jax.tree.map(leaf, params)


def dequantize_tree(params, dtype=jnp.float32):
    """Traced inverse: expand every quantized leaf back to ``dtype``
    inside a jitted program. XLA fuses the convert+multiply into each
    weight's consumer, so the expansion costs no extra HBM round
    trip."""

    def leaf(x):
        if _is_quant_leaf(x):
            return x["q"].astype(dtype) * x["scale"].astype(dtype)
        return x

    return jax.tree.map(leaf, params, is_leaf=_is_quant_leaf)


def is_quantized(params) -> bool:
    found = False

    def leaf(x):
        nonlocal found
        found = found or _is_quant_leaf(x)
        return x

    jax.tree.map(leaf, params, is_leaf=_is_quant_leaf)
    return found


def quantized_bytes(params) -> tuple[int, int]:
    """(bytes as stored, bytes if fully f32) — the HBM story."""
    stored = full = 0

    def leaf(x):
        nonlocal stored, full
        if _is_quant_leaf(x):
            stored += x["q"].size + 4 * x["scale"].size
            full += 4 * x["q"].size
        else:
            a = np.asarray(x)
            stored += a.nbytes
            full += a.nbytes
        return x

    jax.tree.map(leaf, params, is_leaf=_is_quant_leaf)
    return stored, full
