"""Stable softmax attention — the single-device baseline op.

Layout convention (shared by every attention impl in ``ops``):
``q, k, v`` are ``[B, L, H, D]`` (batch, sequence, heads, head_dim),
``mask`` is a binary ``[B, L]`` key-validity mask (1 = attend). Scores
and the softmax run in float32 regardless of input dtype — bfloat16
accumulation visibly degrades softmax tails on TPU — and the output is
cast back to the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Finite large-negative instead of -inf: keeps exp() NaN-free when an
# entire key block is masked (exp(NEG - NEG) == 1 is then zeroed by the
# explicit binary-mask multiply in the online-softmax update).
# A Python float, deliberately NOT a jax array: a module-level jax
# array gets captured by traced functions as an implicit argument
# ("captured constants"), which both bloats signatures and trips a
# fastpath buffer-count bug in this JAX version on repeat calls.
NEG = -1e30


def full_attention(q, k, v, mask=None, *, causal: bool = False, scale=None):
    """Softmax attention over the full sequence.

    ``q, k, v``: ``[B, L, H, D]``; ``mask``: optional binary ``[B, L]``
    over keys; returns ``[B, L, H, D]`` in ``q.dtype``.
    """
    *_, d = q.shape
    scale = (1.0 / d**0.5) if scale is None else scale
    # Matmuls take native-dtype (bf16) inputs with f32 accumulation —
    # the MXU recipe; only the softmax itself lives in f32.
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if mask is not None:
        scores = scores + (1.0 - mask.astype(jnp.float32))[:, None, None, :] * NEG
    if causal:
        l = q.shape[1]
        keep = jnp.tril(jnp.ones((l, l), jnp.bool_))
        scores = jnp.where(keep[None, None, :, :], scores, NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    if mask is not None:
        # Softmax is shift-invariant, so a query row whose keys are
        # ALL masked would otherwise attend uniformly (additive NEG
        # cancels out). Zero those contributions explicitly so the
        # full/flash/ring implementations agree: fully-masked rows
        # return zeros everywhere.
        probs = probs * mask.astype(jnp.float32)[:, None, None, :]
    probs = probs.astype(q.dtype)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
