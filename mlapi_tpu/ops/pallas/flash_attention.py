"""Fused attention kernels (flash-attention) in Pallas, both passes.

Why a kernel at all: stock XLA materialises the ``[B, H, L, L]``
score tensor in HBM between the two attention matmuls once L is big
enough that fusion gives up — at L=2048, BERT-base shapes, that is
256 MB of HBM traffic per layer. Here scores never exist at full
size anywhere: the forward streams K/V through VMEM in ``block_k``
tiles with the online-softmax recurrence (running max ``m``, running
normaliser ``l``, rescaled accumulator), and the backward recomputes
probabilities tile-by-tile from the saved log-sum-exp instead of
storing them. HBM sees Q/K/V/O (+ per-row LSE) only, in both
directions — no ``[L, L]`` tensor in the compiled HLO.

Grid layout (TPU: the grid is iterated sequentially, last dimension
innermost; VMEM scratch persists across grid steps, which is what
carries the online-softmax state between K tiles):

- forward:   ``(B, H, L/block_q, L/block_k)`` — one q-tile's output
  accumulates across the inner k-steps, written at the last k-step.
- backward dq: ``(B, H, L/block_q, k-tiles)``; dq accumulates across
  the (window-shrunken, when windowed) k-steps.
- backward dk/dv: ``(B, KVH, L/block_k, group × q-tiles)`` — one kv
  head's whole query group accumulates consecutively into its
  KVH-wide dk/dv block (GQA-native; no repeated K/V in either pass),
  with the inner q-range window-shrunken when windowed.

Causal masking skips whole tiles above the diagonal (``pl.when``
predication), so causal attention does ~half the work.

Per-program VMEM is a few ``block×block`` f32 tiles (~2-3 MB at the
default 512/512 blocks — measured 2x faster than 128/128 at L=8192
on v5e, where the sequential grid's per-step overhead dominates small
tiles) — inside the ~16 MB budget at any L.
Longer sequences belong to the sequence-parallel path
(``mlapi_tpu.ops.ring_attention``).

Layout convention matches ``mlapi_tpu.ops.attention``: ``q, k, v``
are ``[B, L, H, D]``, ``mask`` is binary ``[B, L]`` over keys; fully
masked query rows return zeros (all three attention impls agree).
Grouped-query attention is native on the forward: ``k``/``v`` may
carry ``H / group`` heads and the kv BlockSpec indexes ``hi //
group`` — the repeated K/V tensor never exists in HBM.
Matmuls run native-dtype inputs with f32 accumulation on the MXU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Python float (not a jax scalar — kernels may not capture traced
# constants); same finite large-negative as mlapi_tpu.ops.attention.NEG.
_NEG = -1e30
# Scratch lane width: TPU vector lanes are 128 wide; the row-state
# scratch (m, l) is kept lane-replicated so reads/writes stay aligned.
_LANES = 128


def _keep_tile(mask_ref, causal, qi, ki, block_q, block_k, shape,
               window=None):
    """Binary keep-mask for one (q-tile, k-tile) score block.
    ``window`` (causal-only) keeps keys within the last ``window``
    positions of each query: ``q_pos - k_pos < window``."""
    keep = mask_ref[0, 0][None, :].astype(jnp.float32)
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        keep = keep * (q_pos >= k_pos)
        if window is not None:
            keep = keep * (q_pos - k_pos < window)
    return keep


def _live_k_tiles(block_q, block_k, window):
    """Exact worst-case number of k-tiles any q-tile can see under a
    causal window — enumerated over the gcd residue classes of q-tile
    alignments (all static at trace time). Single source of truth for
    the forward and dq shrunken grids."""
    g = math.gcd(block_q, block_k)
    best = 0
    for r in range(0, block_k, g):
        first = (r - window + 1) // block_k  # floor; may be < 0
        last = (r + block_q - 1) // block_k
        best = max(best, last - first + 1)
    return best


def _live_q_tiles(block_q, block_k, window):
    """Exact worst-case number of q-tiles any k-tile can feed (the
    dkv grid's inner extent), offset from the k-tile's first live
    q-tile ``(ki * block_k) // block_q``."""
    g = math.gcd(block_q, block_k)
    best = 0
    for r in range(0, block_q, g):
        best = max(best, (r + block_k + window - 2) // block_q + 1)
    return best


def _window_k_tile(qi, ki, block_q, block_k, nkw):
    """Physical k-tile index for window-relative step ``ki`` of a
    shrunken k-grid: the last ``nkw`` tiles ending at the q-tile's
    diagonal tile. May be negative (caller clamps + skips)."""
    last = (qi * block_q + block_q - 1) // block_k
    return last - (nkw - 1) + ki


def _tile_live(causal, window, qi, ki, block_q, block_k):
    """Static-shape predicate: does this (q-tile, k-tile) pair contain
    ANY attendable position? Causal skips tiles above the diagonal;
    a window additionally skips tiles entirely older than the oldest
    key any query in the tile can see. Windowed kernels normally
    bypass this predicate — all three grids shrink to the live tiles
    (``_live_k_tiles`` / ``_live_q_tiles``), so steady-state tiles do
    O(window/block) steps in compute AND copies — and fall back to
    the full grid + this predicate when the window covers most of the
    sequence."""
    live = (qi + 1) * block_q > ki * block_k if causal else True
    if causal and window is not None:
        live = jnp.logical_and(
            live, (ki + 1) * block_k + window > qi * block_q + 1
        )
    return live


def _fwd_kernel(
    q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, m_s, l_s, acc_s,
    *, scale, causal, block_q, block_k, window=None, windowed_grid=False,
):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    if windowed_grid:
        # Shrunken k-grid: ki is WINDOW-RELATIVE. The physical k-tile
        # is the same expression the BlockSpec index map uses; tiles
        # whose unclamped index is negative are duplicates of tile 0
        # (index maps can't go below 0) and must not contribute twice.
        kb_raw = _window_k_tile(qi, ki, block_q, block_k, nk)
        kb = jnp.maximum(kb_raw, 0)
        run = kb_raw >= 0
    else:
        kb = ki
        # Causal/window: tiles with no attendable position are skipped.
        run = _tile_live(causal, window, qi, ki, block_q, block_k)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = (
            jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k]
        keep = _keep_tile(
            mask_ref, causal, qi, kb, block_q, block_k, s.shape, window
        )
        s = s + (1.0 - keep) * _NEG

        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # exp(NEG - NEG) == 1 on lanes with no valid key; * keep zeroes
        # them so fully-masked rows come out 0, not NaN.
        p = jnp.exp(s - m_new) * keep
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        o_ref[0, 0] = (acc_s[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # [block_q, 1] column write — sublane-aligned, no relayout.
        lse_ref[0, 0] = m_s[:, :1] + jnp.log(jnp.maximum(l_s[:, :1], 1e-30))


def _jnp_flash(q, k, v, mask, causal, scale, window=None):
    """Pure-jnp (out, lse) with the kernel's exact conventions —
    identical masking/NEG/lse semantics, differentiable by plain
    autodiff (the lse cotangent flows through ``jnp.log``).

    Exists because the Pallas HLO *interpreter* cannot run inside a
    vma-checked ``shard_map`` (jax 0.9: its internal block slicing
    mixes the interpreter's unvarying loop indices with varying
    operands — ``dynamic_slice requires varying manual axes to
    match``). On CPU tests of the ring x flash composition this path
    carries the math; the kernels themselves are interpreter-tested
    outside shard_map (tests/test_flash_attention.py), and on TPU the
    real kernels run everywhere, shard_map included.
    """
    if k.shape[2] != q.shape[2]:  # GQA: broadcast kv heads
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    keep = mask.astype(jnp.float32)[:, None, None, :]
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        dist = jnp.arange(lq)[:, None] - jnp.arange(lk)[None, :]
        tri = dist >= 0
        if window is not None:
            tri = tri & (dist < window)
        keep = keep * tri[None, None]
    s = s + (1.0 - keep) * _NEG
    m = jnp.max(s, axis=-1)                      # [B,H,Lq]
    p = jnp.exp(s - m[..., None]) * keep
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    out = (o / denom).astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, lse


def _vma_of(x):
    """The varying-manual-axes of ``x``'s aval, or None. jax.typeof
    (and the vma type system) only exist on newer jax; on releases
    without it there is no vma checker to satisfy."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", None)


def _inside_vma_shard_map(x):
    """True when tracing inside a vma-checked shard_map (the aval
    carries varying-manual-axes) — static at trace time."""
    return bool(_vma_of(x))


def _out_struct(shape, dtype, like):
    # Inside shard_map, pallas_call outputs must declare which mesh
    # axes they vary over (vma); mirror the query operand's type so
    # the kernels compose with the ring/sequence-parallel paths.
    vma = _vma_of(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _fwd(q, k, v, mask, causal, scale, block_q, block_k, interpret,
         window=None):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    # GQA: k/v may carry fewer heads than q (validated in _prepare);
    # the kv BlockSpec indexes `hi // group`, so each query head
    # streams its group's K/V block straight from HBM — no repeated
    # K/V tensor is ever materialised.
    group = h // k.shape[2]
    # [B, 1, L]: TPU lowering wants the last two block dims tile-
    # aligned or equal to the array dims; a (1, 1, block_k) block
    # satisfies that where a (1, block_k) block over [B, L] cannot
    # when B > 1.
    mask3 = mask.astype(jnp.float32)[:, None, :]
    # [B, L, H, D] -> [B, H, L, D]: heads become a grid dimension.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    nk_full = lk // block_k
    # Sliding window: walk only the k-tiles a q-tile can see — the
    # last nkw tiles ending at its diagonal tile. nkw is the EXACT
    # worst case over q-tile alignments (enumerated over the
    # gcd(block_q, block_k) residue classes — everything here is
    # static at trace time), so for aligned blocks no q-tile pays a
    # spare inner step. Early q-tiles whose unclamped tile index is
    # negative still occupy their grid steps (the index map clamps to
    # tile 0 and its copy happens; only the compute is skipped) — the
    # O(L·window) claim is about the common steady-state q-tiles.
    if causal and window is not None:
        nkw = min(nk_full, _live_k_tiles(block_q, block_k, window))
    else:
        nkw = nk_full
    windowed_grid = nkw < nk_full
    grid = (b, h, lq // block_q, nkw)
    q_spec = pl.BlockSpec(
        (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )
    if windowed_grid:
        def _kmap(bi, hi, qi, ki):
            kb = _window_k_tile(qi, ki, block_q, block_k, nkw)
            return (bi, hi // group, jnp.maximum(kb, 0), 0)

        def _mmap(bi, hi, qi, ki):
            kb = _window_k_tile(qi, ki, block_q, block_k, nkw)
            return (bi, 0, jnp.maximum(kb, 0))

        kv_spec = pl.BlockSpec((1, 1, block_k, d), _kmap)
        mask_spec = pl.BlockSpec((1, 1, block_k), _mmap)
    else:
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, d),
            lambda bi, hi, qi, ki: (bi, hi // group, ki, 0),
        )
        mask_spec = pl.BlockSpec(
            (1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)
        )
    # LSE rides as [B, H, L, 1]: Mosaic requires the last two block
    # dims tile-aligned (8, 128) or equal to the array dims; a
    # (1, 1, block_q) block over [B, H, L] fails that for H > 1,
    # while (1, 1, block_q, 1) passes (block_q % 8 == 0, trailing
    # 1 == array dim) and keeps the row state sublane-aligned.
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )

    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window,
            windowed_grid=windowed_grid,
        ),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec, mask_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            _out_struct(qt.shape, q.dtype, q),
            _out_struct((b, h, lq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum l
            pltpu.VMEM((block_q, d), jnp.float32),       # output acc
        ],
        interpret=interpret,
    )(qt, kt, vt, mask3)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dq_ref,
    dq_s, *, scale, causal, block_q, block_k, window=None,
    windowed_grid=False,
):
    qi, kr = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kr == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    if windowed_grid:
        # Shrunken inner k-grid, same mapping as the forward.
        kb_raw = _window_k_tile(qi, kr, block_q, block_k, nk)
        ki = jnp.maximum(kb_raw, 0)
        run = kb_raw >= 0
    else:
        ki = kr
        run = _tile_live(causal, window, qi, ki, block_q, block_k)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                   # [block_q, 1] column
        delta = delta_ref[0, 0]               # [block_q, 1] column

        # All matmuls take native-dtype (bf16) operands with f32
        # accumulation — the MXU recipe; f32 lives only in the
        # softmax-recompute elementwise math.
        s = (
            jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        keep = _keep_tile(
            mask_ref, causal, qi, ki, block_q, block_k, s.shape, window
        )
        s = s + (1.0 - keep) * _NEG
        # Recompute probabilities from the saved LSE. Masked lanes give
        # exp(NEG - lse) — large but finite (lse >= NEG + log(eps)) —
        # then * keep zeroes them, so no NaN even for fully-masked rows.
        p = jnp.exp(s - lse) * keep
        dp = jax.lax.dot_general(
            do, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = p * (dp - delta) * scale
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kr == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_s, dv_s, *, scale, causal, block_q, block_k,
    window=None, nq_eff, nq_total, windowed_grid=False,
):
    """dk/dv for ONE kv head: the grid is (B, KVH, k-tiles, inner)
    with inner = group * nq_eff — all of a kv head's query heads and
    q-tiles accumulate consecutively into its dk/dv block (the
    revisit pattern Pallas requires), which is what makes the
    backward GQA-native with no repeated K/V tensor. With a window,
    nq_eff is the exact per-k-tile live q-tile bound and the q index
    map offsets from the k-tile's first live q-tile."""
    ki, gq = pl.program_id(2), pl.program_id(3)
    n_inner = pl.num_programs(3)

    @pl.when(gq == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    qr = gq % nq_eff
    if windowed_grid:
        qt_raw = (ki * block_k) // block_q + qr
        qi = jnp.minimum(qt_raw, nq_total - 1)
        run = jnp.logical_and(
            qt_raw < nq_total,
            _tile_live(causal, window, qi, ki, block_q, block_k),
        )
    else:
        qi = qr
        run = _tile_live(causal, window, qi, ki, block_q, block_k)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                   # [block_q, 1] column
        delta = delta_ref[0, 0]               # [block_q, 1] column

        # Native-dtype matmul operands, f32 accumulation (MXU recipe).
        s = (
            jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        keep = _keep_tile(
            mask_ref, causal, qi, ki, block_q, block_k, s.shape, window
        )
        s = s + (1.0 - keep) * _NEG
        p = jnp.exp(s - lse) * keep            # [block_q, block_k]
        # dv += pᵀ · dO ; dk += dsᵀ · q — contractions over the q dim,
        # no explicit transpose materialised.
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(gq == n_inner - 1)
    def _finish():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


def _bwd(q, k, v, mask, out, lse, g, causal, scale, block_q, block_k,
         interpret, g_lse=None, window=None):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    kvh = k.shape[2]
    group = h // kvh
    mask3 = mask.astype(jnp.float32)[:, None, :]
    qt, ot, gt = (x.transpose(0, 2, 1, 3) for x in (q, out, g))
    kt, vt = (x.transpose(0, 2, 1, 3) for x in (k, v))  # [B, KVH, L, D]
    # delta_i = Σ_d dO_i · O_i — one cheap fused elementwise+reduce in
    # XLA; saves the backward kernels a dot each per tile. A cotangent
    # on the LSE output folds in here exactly: ∂lse_i/∂s_ij = p_ij, so
    # ds_ij = p_ij·(dp_ij - (delta_i - g_lse_i))·scale — the kernels
    # need no change.
    delta = jnp.sum(
        gt.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1
    )  # [B, H, L]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    # Row vectors ride as [B, H, L, 1] (same Mosaic tiling reason as
    # the forward's LSE output — see _fwd's lse_spec comment).
    lse4 = lse[..., None]
    delta4 = delta[..., None]

    nq = lq // block_q
    nk_full = lk // block_k
    windowed = causal and window is not None

    # -- dq: q-tiles accumulate over (a shrunken set of) k-tiles ------
    nkq = (
        min(nk_full, _live_k_tiles(block_q, block_k, window))
        if windowed
        else nk_full
    )
    dq_windowed = nkq < nk_full

    def _kb(qi, kr):
        if dq_windowed:
            return jnp.maximum(_window_k_tile(qi, kr, block_q, block_k, nkq), 0)
        return kr

    q_spec = pl.BlockSpec(
        (1, 1, block_q, d), lambda bi, hi, qi, kr: (bi, hi, qi, 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, d),
        lambda bi, hi, qi, kr: (bi, hi // group, _kb(qi, kr), 0),
    )
    mask_spec = pl.BlockSpec(
        (1, 1, block_k), lambda bi, hi, qi, kr: (bi, 0, _kb(qi, kr))
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda bi, hi, qi, kr: (bi, hi, qi, 0)
    )

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window,
            windowed_grid=dq_windowed,
        ),
        grid=(b, h, nq, nkq),
        in_specs=[q_spec, kv_spec, kv_spec, mask_spec, q_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        out_shape=_out_struct(qt.shape, q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, mask3, gt, lse4, delta4)

    # -- dk/dv: GQA-native grid (B, KVH, k-tiles, group * q-tiles) ----
    # Every (query head, q-tile) of one kv head accumulates
    # CONSECUTIVELY into its dk/dv block — the revisit pattern Pallas
    # requires — so no repeated K/V tensor is needed. With a window,
    # the inner q-range shrinks to the exact per-alignment bound of
    # live q-tiles, offset from each k-tile's first.
    nq_eff = (
        min(nq, _live_q_tiles(block_q, block_k, window))
        if windowed
        else nq
    )
    dkv_windowed = nq_eff < nq

    def _hq(kvi, gq):
        return kvi * group + gq // nq_eff

    def _qt(ki, gq):
        if dkv_windowed:
            return jnp.minimum(
                (ki * block_k) // block_q + gq % nq_eff, nq - 1
            )
        return gq % nq_eff

    q_spec_T = pl.BlockSpec(
        (1, 1, block_q, d),
        lambda bi, kvi, ki, gq: (bi, _hq(kvi, gq), _qt(ki, gq), 0),
    )
    kv_spec_T = pl.BlockSpec(
        (1, 1, block_k, d), lambda bi, kvi, ki, gq: (bi, kvi, ki, 0)
    )
    mask_spec_T = pl.BlockSpec(
        (1, 1, block_k), lambda bi, kvi, ki, gq: (bi, 0, ki)
    )
    row_spec_T = pl.BlockSpec(
        (1, 1, block_q, 1),
        lambda bi, kvi, ki, gq: (bi, _hq(kvi, gq), _qt(ki, gq), 0),
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, window=window,
            nq_eff=nq_eff, nq_total=nq, windowed_grid=dkv_windowed,
        ),
        grid=(b, kvh, nk_full, group * nq_eff),
        in_specs=[q_spec_T, kv_spec_T, kv_spec_T, mask_spec_T, q_spec_T,
                  row_spec_T, row_spec_T],
        out_specs=[kv_spec_T, kv_spec_T],
        out_shape=[
            _out_struct(kt.shape, k.dtype, q),
            _out_struct(vt.shape, v.dtype, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, mask3, gt, lse4, delta4)

    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, mask, causal, scale, block_q, block_k, interpret,
           window=None):
    """(out, lse) with a joint VJP — lse cotangents cost nothing extra
    (they fold into the delta term, see ``_bwd``), which is what lets
    ring attention compose flash blocks and still train through the
    log-sum-exp merge."""
    return _fwd(
        q, k, v, mask, causal, scale, block_q, block_k, interpret, window
    )


def _flash_fwd(q, k, v, mask, causal, scale, block_q, block_k, interpret,
               window=None):
    out, lse = _fwd(
        q, k, v, mask, causal, scale, block_q, block_k, interpret, window
    )
    return (out, lse), (q, k, v, mask, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, res, g):
    q, k, v, mask, out, lse = res
    g_o, g_lse = g
    # GQA is native in BOTH backward kernels now: the dkv grid runs
    # per kv head with its whole group accumulating consecutively, so
    # no repeated K/V tensor exists in the backward either.
    dq, dk, dv = _bwd(
        q, k, v, mask, out, lse, g_o, causal, scale, block_q, block_k,
        interpret, g_lse=g_lse, window=window,
    )
    return dq, dk, dv, jnp.zeros_like(mask)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(requested: int, length: int) -> int:
    b = min(requested, length)
    while length % b:
        b //= 2  # terminates: 1 divides everything
    return b


def _prepare(q, k, v, mask, causal, scale, block_q, block_k,
             window=None):
    """Shared wrapper preamble: validation, scale default, block
    clamping, default mask. Returns (mask, scale, block_q, block_k)."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if causal and lq != lk:
        raise ValueError(
            f"causal attention needs aligned q/k lengths, got {lq} vs {lk}"
        )
    if window is not None and (not causal or window < 1):
        raise ValueError(
            "window requires causal=True and window >= 1 "
            f"(got causal={causal}, window={window})"
        )
    if k.shape[2] != v.shape[2]:
        raise ValueError(
            f"k and v head counts disagree: {k.shape[2]} vs {v.shape[2]}"
        )
    if h % k.shape[2]:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads "
            f"({k.shape[2]}) for grouped-query attention"
        )
    scale = (1.0 / d**0.5) if scale is None else scale
    # Fit each block to its sequence: clamp, then halve until it
    # divides (512 → 256 → …) so any L a smaller power-of-two block
    # handles keeps working when the default grows (L=768 runs at 256,
    # not a ValueError). Explicitly-passed non-divisible blocks also
    # degrade to the nearest dividing halving rather than erroring.
    block_q = _fit_block(block_q, lq)
    block_k = _fit_block(block_k, lk)
    if mask is None:
        mask = jnp.ones((b, lk), jnp.float32)
    return mask, scale, block_q, block_k


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_q", "block_k", "interpret", "window"
    ),
)
def flash_attention(
    q,
    k,
    v,
    mask=None,
    *,
    causal: bool = False,
    scale=None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    window: int | None = None,
):
    """Fused softmax attention. ``q, k, v``: ``[B, L, H, D]``;
    ``mask``: optional binary ``[B, L]`` over keys. Returns
    ``[B, L, H, D]`` in ``q.dtype``.

    Differentiable end to end in Pallas: the forward streams K/V in
    ``block_k`` tiles with the online-softmax recurrence and saves the
    per-row log-sum-exp; the backward recomputes probability tiles
    from it and accumulates dq (k-inner grid) and dk/dv (q-inner
    grid) — no ``[L, L]`` tensor in HBM in either pass.
    ``interpret=True`` runs the Pallas interpreter (CPU testing).

    Int8-KV policy (the three-way split, see
    ``ops/quant.maybe_dequant_kv``): quantized ``{"q", "scale"}`` K/V
    operands dequantize AT THIS BOUNDARY (one fused convert+multiply
    feeding the kernel's first tile load) — full-sequence
    prefill/training shapes are MXU-bound, so the byte format of the
    operand read is not the lever here. The DECODE read, which IS
    bandwidth-bound, runs as its own kernel
    (``ops/pallas/decode_attention``) that DMAs int8 payload+scale
    tiles to VMEM and dequantizes per tile in registers; the einsum
    decode path dequantizes at the read seam (``kv_cache_kv``).
    """
    from mlapi_tpu.ops.quant import maybe_dequant_kv

    k = maybe_dequant_kv(k, q.dtype)
    v = maybe_dequant_kv(v, q.dtype)
    mask, scale, block_q, block_k = _prepare(
        q, k, v, mask, causal, scale, block_q, block_k, window
    )
    if interpret and _inside_vma_shard_map(q):
        out, _ = _jnp_flash(q, k, v, mask, causal, scale, window)
        return out
    out, _ = _flash(
        q, k, v, mask.astype(jnp.float32), causal, scale, block_q, block_k,
        interpret, window,
    )
    return out


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_q", "block_k", "interpret", "window"
    ),
)
def flash_attention_with_lse(
    q,
    k,
    v,
    mask=None,
    *,
    causal: bool = False,
    scale=None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    window: int | None = None,
):
    """Like :func:`flash_attention` but also returns the per-row
    log-sum-exp ``[B, H, L]`` — the quantity that lets independently
    computed attention blocks be merged exactly (numerically safe
    weighted average). Used by ``ring_attention``'s flash block mode;
    differentiable through BOTH outputs. Same int8-KV policy as
    :func:`flash_attention`: quantized K/V pairs dequantize at entry
    (full-sequence shapes are MXU-bound; the in-kernel int8 tile path
    belongs to the decode kernel, ``decode_attention``)."""
    from mlapi_tpu.ops.quant import maybe_dequant_kv

    k = maybe_dequant_kv(k, q.dtype)
    v = maybe_dequant_kv(v, q.dtype)
    mask, scale, block_q, block_k = _prepare(
        q, k, v, mask, causal, scale, block_q, block_k, window
    )
    if interpret and _inside_vma_shard_map(q):
        return _jnp_flash(q, k, v, mask, causal, scale, window)
    return _flash(
        q, k, v, mask.astype(jnp.float32), causal, scale, block_q, block_k,
        interpret, window,
    )
