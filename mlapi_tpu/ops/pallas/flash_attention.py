"""Fused attention kernel (flash-attention style) in Pallas.

Why a kernel at all: stock XLA materialises the ``[B, H, L, L]``
score tensor in HBM between the two attention matmuls once L is big
enough that fusion gives up — at L=2048, BERT-base shapes, that is
256 MB of HBM traffic per layer. Here the grid is
``(B, H, L/block_q)`` and each program computes one q-block's output
with scores, softmax and the probs·V contraction all resident in
VMEM: HBM sees only Q/K/V/O.

Per-program VMEM footprint is ``block_q·L`` f32 scores plus the K/V
blocks — ~5 MB at L=4096, ``block_q=128``, ``D=64`` — inside the
~16 MB budget. Longer sequences belong to the sequence-parallel path
(``mlapi_tpu.ops.ring_attention``), which composes: each ring step's
local block attention can itself be this kernel.

Layout convention matches ``mlapi_tpu.ops.attention``: ``q, k, v``
are ``[B, L, H, D]``, ``mask`` is binary ``[B, L]`` over keys; both
matmuls run native-dtype inputs with f32 accumulation on the MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Python float (not a jax scalar — kernels may not capture traced
# constants); same finite large-negative as mlapi_tpu.ops.attention.NEG.
_NEG = -1e30


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale, causal, block_q):
    # Block shapes: q [1,1,block_q,D]; k/v [1,1,L,D]; mask [1,1,L].
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    key_mask = mask_ref[0, 0]  # [L] binary

    scores = (
        jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [block_q, L]
    keep = key_mask[None, :].astype(jnp.float32)
    if causal:
        i = pl.program_id(2)
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0
        )
        k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        keep = keep * (q_pos >= k_pos)
    scores = scores + (1.0 - keep) * _NEG

    m = jnp.max(scores, axis=-1, keepdims=True)
    # exp(NEG - NEG) == 1 when a row sees no valid key; * keep zeroes
    # those lanes so fully-masked rows come out 0, not NaN.
    p = jnp.exp(scores - m) * keep
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(q.dtype), v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / jnp.maximum(l, 1e-30)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _forward(q, k, v, mask, causal, scale, block_q, interpret):
    b, l, h, d = q.shape
    # [B, 1, L]: TPU lowering wants the last two block dims tile-
    # aligned or equal to the array dims; a (1, 1, L) block satisfies
    # that where a (1, L) block over [B, L] cannot when B > 1.
    mask3 = mask.astype(jnp.float32)[:, None, :]

    # [B, L, H, D] -> [B, H, L, D]: heads become a grid dimension.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    grid = (b, h, l // block_q)
    qo_spec = pl.BlockSpec(
        (1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)
    )
    kv_spec = pl.BlockSpec((1, 1, l, d), lambda bi, hi, qi: (bi, hi, 0, 0))
    mask_spec = pl.BlockSpec((1, 1, l), lambda bi, hi, qi: (bi, 0, 0))

    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, causal=causal, block_q=block_q
        ),
        grid=grid,
        in_specs=[qo_spec, kv_spec, kv_spec, mask_spec],
        out_specs=qo_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(qt, kt, vt, mask3)
    return out.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, mask, causal, scale, block_q, interpret):
    return _forward(q, k, v, mask, causal, scale, block_q, interpret)


def _flash_fwd(q, k, v, mask, causal, scale, block_q, interpret):
    out = _forward(q, k, v, mask, causal, scale, block_q, interpret)
    return out, (q, k, v, mask)


def _flash_bwd(causal, scale, block_q, interpret, res, g):
    # Backward via the differentiable XLA reference (recompute-from-
    # residuals, flash-attention style): training pays the [L, L]
    # materialisation only in the grad pass; the serving-critical
    # forward keeps the fused kernel. A Pallas backward kernel can
    # replace this without touching callers.
    from mlapi_tpu.ops.attention import full_attention

    q, k, v, mask = res

    def ref(q, k, v):
        return full_attention(q, k, v, mask, causal=causal, scale=scale)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "interpret")
)
def flash_attention(
    q,
    k,
    v,
    mask=None,
    *,
    causal: bool = False,
    scale=None,
    block_q: int = 128,
    interpret: bool = False,
):
    """Fused softmax attention. ``q, k, v``: ``[B, L, H, D]``;
    ``mask``: optional binary ``[B, L]`` over keys. Returns
    ``[B, L, H, D]`` in ``q.dtype``.

    Differentiable: the forward runs the Pallas kernel, the backward
    runs the XLA reference via a custom VJP (see ``_flash_bwd``).
    ``interpret=True`` runs the Pallas interpreter (CPU testing).
    """
    b, l, h, d = q.shape
    scale = (1.0 / d**0.5) if scale is None else scale
    block_q = min(block_q, l)
    if l % block_q:
        raise ValueError(
            f"sequence length {l} not divisible by block_q {block_q}"
        )
    if mask is None:
        mask = jnp.ones((b, l), jnp.float32)
    return _flash(
        q, k, v, mask.astype(jnp.float32), causal, scale, block_q, interpret
    )
