"""Fused flash-decode/flash-extend kernels: split-K attention that
reads the KV cache — int8 payload included — in-kernel, for
single-token decode steps AND multi-token extend spans.

Why decode gets its own kernel: the serving hot path is the decode
step, and it is memory-bound, not compute-bound. Every generated
token re-reads every layer's ``[B, L, KVH, D]`` K and V from HBM to
do O(B·H·L·D) FLOPs — an arithmetic intensity of ~1 FLOP/byte, three
orders below the MXU's knee. The only lever is bytes moved, and the
einsum decode path moves the wrong ones: with an int8 cache it
dequantizes at the read seam (``ops/quant.kv_cache_kv``), so the
full-precision cache materializes between the dequant and the einsum
and the int8 format's 2x HBM saving is realized in *storage* only.
This kernel is where the saving reaches the read: int8 payload +
per-token-per-head f32 scale tiles are DMA'd to VMEM and dequantized
per tile in registers — int8 is what crosses HBM on the decode read.

Shape of the computation (one query per row, against a long cache):

- **Split-K over the cache length.** The grid is ``(B, L/block_k)``:
  each program owns one k-tile of one batch row and computes a
  partial ``(acc, m, l)`` triple — un-normalized output, running max,
  running normalizer — for EVERY query head (the whole ``[H, D]``
  query block rides into each program; it is tiny). A second,
  pure-jnp stage merges the per-tile triples with the standard
  log-sum-exp algebra. No ``[B, L]`` probability tensor and no
  full-precision cache ever exist in HBM: HBM sees q, the stored
  cache tiles, the ``[B, L]`` key mask, and ``[B, nk, H, D + 2]``
  f32 partials (acc ``D`` + m + l per head-tile — noise next to one
  cache read).
- **GQA-native.** K/V stay at ``KVH`` heads in their STORED
  ``[B, L, KVH, D]`` layout (no transpose — a transposed copy of the
  cache would cost the very read we are saving); queries are grouped
  in-register, ``group = H // KVH`` consecutive query heads per KV
  head, and each KV head's tile is loaded once for its whole group.
- **Both cache formats through one seam.** ``k``/``v`` operands are
  either plain arrays (bf16/f32 tiles load directly) or the
  ``{"q" int8, "scale" f32}`` pairs of the int8 cache format
  (``ops/quant``), dequantized per tile with exactly
  ``kv_dequantize``'s arithmetic. Same operand convention as
  ``flash_attention``'s quantized K/V — but handled IN-kernel, not at
  the boundary.
- **Masking = ``decode_valid_and_shift`` semantics.** The ``[B, L]``
  binary key mask carries everything the decode layout encodes —
  per-row ``pos``, ``n_pad`` pad holes, shared-prefix regions,
  optional windows — so the kernel needs no position algebra of its
  own. Tiles whose mask is entirely zero (cache slots beyond ``pos``)
  skip their compute under ``pl.when``: a half-full cache does half
  the dot-products, the split-K analog of causal tile skipping.

Dead-tile DMA note: the BlockSpec copy of a skipped tile still
happens (the predicate gates compute, not the pipelined copy), so the
byte win of skipping is bounded; the format win (int8 vs full
precision) applies to every tile.

**Flash-extend (the U-token variant).** Every multi-token attention
span the server runs — chunked prefill blocks, admission
mini-prefills, shared-prefix suffixes, speculative verify blocks —
is the SAME computation with a Q tile of U rows instead of one:
still bandwidth-bound (U is a chunk width or ``k+1``, tiny next to
the cache length), still a read of the whole stored cache per
dispatch. :func:`extend_attention` / :func:`paged_extend_attention`
keep the decode kernels' grid ``(B, L/block_k)`` (paged: ``(B, NP)``
with the same scalar-prefetched table index map), ride a
``[B, U, L]`` key mask — ``extend_positions_and_mask`` already
encodes the causal intra-span structure (query ``u`` sees cache
slots ``<= pos0 + u``), so the kernel again needs no position
algebra — and emit per-tile partials for all ``U x H`` query rows,
merged by the SAME pure-jnp log-sum-exp stage 2. Rows are laid out
``[KVH, U, group]``-flat so each KV head's whole query group is one
contiguous slice per program (one k-tile load serves U·group rows),
and the post-merge transpose back to ``[B, U, H, D]`` touches a tiny
f32 tensor. With this kernel the int8 read saving (and GQA's
KV-width read) covers EVERY token the server processes, not just
decode steps — the einsum extend path materialized a full-precision,
query-head-width cache operand per chunk.

``interpret=True`` runs the Pallas interpreter (CPU CI). In interpret
mode the grid lowers to plain traced JAX, so the kernel composes with
GSPMD-partitioned decode programs on virtual meshes — that is what
the multichip dry run proves. The COMPILED kernel under a model-axis
mesh is NOT yet hardware-validated: a compiled ``pallas_call`` is an
opaque custom call to GSPMD, which may all-gather head-sharded cache
operands around it instead of running the kernel per shard (negating
the byte saving) — verifying that, and adding a ``shard_map`` wrapper
if needed, is an open item for the next TPU window (ROADMAP).
Single-chip TPU serving — where the bandwidth claim lives — needs no
partitioning.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Same finite large-negative as the sibling kernels (a kernel may not
# capture traced constants; -inf breaks the masked-row algebra).
_NEG = -1e30


def _decode_kernel(
    q_ref, *refs, scale, kv_heads, group, quantized,
):
    """One (batch row, k-tile) program: partial ``(acc, m, l)`` for
    all H = kv_heads * group query heads against this tile.

    ``refs`` is the remaining (inputs..., outputs...) ref list; the
    scale refs exist only in the quantized signature — the bf16/f32
    path carries no scale operands at all (a dead operand would still
    be DMA'd per tile, taxing the exact bandwidth-bound read this
    kernel optimizes)."""
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, mask_ref, acc_ref, m_ref, l_ref = refs
    else:
        k_ref, v_ref, mask_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    keep = mask_ref[0, 0]  # [block_k]
    # Split-K tile skipping: a tile with no valid key (every slot
    # beyond pos, or inside a pad hole spanning the tile) contributes
    # the identity triple; the dots are skipped.
    live = jnp.any(keep > 0)

    @pl.when(jnp.logical_not(live))
    def _dead():
        acc_ref[0, 0] = jnp.zeros_like(acc_ref[0, 0])
        m_ref[0, 0] = jnp.full_like(m_ref[0, 0], _NEG)
        l_ref[0, 0] = jnp.zeros_like(l_ref[0, 0])

    @pl.when(live)
    def _step():
        q = q_ref[0, 0]  # [H, D]
        if quantized:
            # The int8 tile path: payload + scales were DMA'd to VMEM
            # by the BlockSpec copies; dequantize in registers with
            # kv_dequantize's exact arithmetic (convert to the compute
            # dtype, broadcast-multiply by the per-(token, head)
            # scale) — the full-precision tile never exists in HBM.
            k = k_ref[0].astype(q.dtype) * ks_ref[0].astype(q.dtype)
            v = v_ref[0].astype(q.dtype) * vs_ref[0].astype(q.dtype)
        else:
            k = k_ref[0]  # [block_k, KVH, D]
            v = v_ref[0]
        nkeep = (1.0 - keep) * _NEG  # [block_k]

        # Per-KV-head 2D dots (kv_heads/group are static: the loop
        # unrolls at trace time). Grouped queries: KV head j serves
        # query heads [j*group, (j+1)*group) — jnp.repeat's layout,
        # shared with every attention impl in ops/.
        for j in range(kv_heads):
            rows = slice(j * group, (j + 1) * group)
            s = (
                jax.lax.dot_general(
                    q[rows], k[:, j, :],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [group, block_k]
            s = s + nkeep[None, :]
            m = jnp.max(s, axis=-1, keepdims=True)  # [group, 1]
            # exp(NEG - NEG) == 1 on lanes with no valid key; * keep
            # zeroes them (no NaN for fully-masked rows).
            p = jnp.exp(s - m) * keep[None, :]
            l = jnp.sum(p, axis=-1, keepdims=True)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v[:, j, :],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [group, D]
            acc_ref[0, 0, rows, :] = acc
            m_ref[0, 0, rows, :] = m
            l_ref[0, 0, rows, :] = l


def _extend_kernel(
    q_ref, *refs, scale, kv_heads, group, u, quantized,
):
    """One (batch row, k-tile) program of the U-token extend grid:
    partial ``(acc, m, l)`` for ALL ``U x H`` query rows against this
    tile. The decode kernel's body with a Q tile of U rows: the
    per-KV-head loop is unchanged, each KV head's tile is loaded once
    and serves its whole query group across all U span positions
    (``U * group`` rows per 2D dot — still one small matmul against
    one streamed tile). Rows land ``[KVH, U, group]``-flat in the
    partials so each head's slice is contiguous; the caller transposes
    back after the merge. ``mask_ref`` carries a PER-QUERY-ROW
    ``[U, block_k]`` mask — the causal intra-span structure (span
    position ``u`` attends cache slots ``<= pos0 + u``) arrives
    encoded in it, exactly as pads/prefixes/windows do."""
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, mask_ref, acc_ref, m_ref, l_ref = refs
    else:
        k_ref, v_ref, mask_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    keep = mask_ref[0]  # [U, block_k]
    # A tile dead for EVERY span position skips its dots (leading
    # tiles of a mostly-empty cache, pad holes spanning the tile).
    live = jnp.any(keep > 0)

    @pl.when(jnp.logical_not(live))
    def _dead():
        acc_ref[0, 0] = jnp.zeros_like(acc_ref[0, 0])
        m_ref[0, 0] = jnp.full_like(m_ref[0, 0], _NEG)
        l_ref[0, 0] = jnp.zeros_like(l_ref[0, 0])

    @pl.when(live)
    def _step():
        q = q_ref[0]  # [U, H, D]
        if quantized:
            k = k_ref[0].astype(q.dtype) * ks_ref[0].astype(q.dtype)
            v = v_ref[0].astype(q.dtype) * vs_ref[0].astype(q.dtype)
        else:
            k = k_ref[0]  # [block_k, KVH, D]
            v = v_ref[0]
        # Per-row mask penalties, repeated group-wise to match the
        # u-major [U * group] row layout of each KV head's dot.
        nkeep = jnp.repeat((1.0 - keep) * _NEG, group, axis=0)
        keep_g = jnp.repeat(keep, group, axis=0)  # [U*group, block_k]

        for j in range(kv_heads):
            qj = q[:, j * group:(j + 1) * group, :].reshape(
                u * group, -1
            )  # [U*group, D], row = u*group + g
            s = (
                jax.lax.dot_general(
                    qj, k[:, j, :],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [U*group, block_k]
            s = s + nkeep
            m = jnp.max(s, axis=-1, keepdims=True)
            # exp(NEG - NEG) == 1 on fully-masked rows; * keep zeroes
            # them (no NaN for span positions with no valid key —
            # all-pad query rows exist in ragged chunks).
            p = jnp.exp(s - m) * keep_g
            l = jnp.sum(p, axis=-1, keepdims=True)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v[:, j, :],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [U*group, D]
            rows = slice(j * u * group, (j + 1) * u * group)
            acc_ref[0, 0, rows, :] = acc
            m_ref[0, 0, rows, :] = m
            l_ref[0, 0, rows, :] = l


def _fit_block(requested: int, length: int) -> int:
    """Largest halving of ``requested`` that divides ``length``. Any
    dividing block >= 8 (the f32 sublane) is kept — a small legal
    blocking beats one whole-length tile, which loses the split-K
    grid and can blow VMEM at long L. Only truly awkward lengths
    (odd test-harness totals like ``p + n_steps + 1``, where the
    halvings bottom out at < 8) fall back to a single block equal to
    the array dim (always legal, and those lengths are small).
    Serving cache tiers are ``bucket + 2^k * chunk``, which fit real
    tiles."""
    b = min(requested, length)
    while length % b:
        b //= 2
    if b < 8 and b < length:
        return length
    return b


def _unpack(x):
    """An operand is a plain ``[B, L, KVH, D]`` array or an int8
    ``{"q", "scale"}`` pair (``ops/quant``'s format, ONE definition —
    the same predicate ``maybe_dequant_kv`` uses). Returns
    ``(payload, scale_or_None)``."""
    from mlapi_tpu.ops.quant import _is_quant_leaf

    if isinstance(x, dict):
        if _is_quant_leaf(x):
            return x["q"], x["scale"]
        raise TypeError(
            "decode_attention takes arrays or {'q', 'scale'} quantized "
            f"pairs, got dict with keys {sorted(x)}"
        )
    return x, None


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret")
)
def decode_attention(
    q,
    k,
    v,
    mask,
    *,
    scale=None,
    block_k: int = 512,
    interpret: bool = False,
):
    """Single-query flash-decode attention over a stored KV cache.

    ``q``: ``[B, 1, H, D]``; ``k``/``v``: ``[B, L, KVH, D]`` arrays
    (any float dtype) or int8 ``{"q", "scale"}`` pairs
    (``scale f32[B, L, KVH, 1]``); ``mask``: binary ``[B, L]`` over
    keys (build it with ``models.gpt.decode_valid_and_shift`` for the
    serving layout). Returns ``[B, 1, H, D]`` in ``q.dtype``.

    Numerics match the einsum decode oracle (``gpt.cached_attend``) to
    reassociation error: f32 accumulation on every dot, probabilities
    cast to the value dtype for the PV contraction, normalization by
    the merged ``l`` after the split-K reduction.
    """
    kq, ks = _unpack(k)
    vq, vs = _unpack(v)
    quantized = ks is not None
    if quantized != (vs is not None):
        raise ValueError("k and v must share one cache format")
    b, one, h, d = q.shape
    if one != 1:
        # U-token dispatch (r11): block extends no longer fall to the
        # einsum path — they are the same bandwidth-bound read with a
        # taller Q tile. The only thing the kernel genuinely cannot
        # tile is a span whose mask lacks the per-query-row (causal
        # intra-span) structure, so that stays a loud error.
        if mask.ndim != 3 or mask.shape[:2] != (b, one):
            raise ValueError(
                f"multi-token q {q.shape} needs a per-query-row "
                f"[B, U, L] mask (got {mask.shape}): a [B, L] decode "
                "mask cannot express the causal intra-span structure"
            )
        return extend_attention(
            q, k, v, mask, scale=scale, block_k=block_k,
            interpret=interpret,
        )
    lk, kvh = kq.shape[1], kq.shape[2]
    if kq.shape != vq.shape or kq.shape[3] != d:
        raise ValueError(
            f"cache shapes disagree with q: k {kq.shape}, v {vq.shape}, "
            f"q {q.shape}"
        )
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    group = h // kvh
    scale = (1.0 / d**0.5) if scale is None else scale
    bk = _fit_block(block_k, lk)
    nk = lk // bk

    mask3 = mask.astype(jnp.float32)[:, None, :]  # [B, 1, L]

    q_spec = pl.BlockSpec((1, 1, h, d), lambda bi, ki: (bi, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, bk, kvh, d), lambda bi, ki: (bi, ki, 0, 0))
    sc_spec = pl.BlockSpec((1, bk, kvh, 1), lambda bi, ki: (bi, ki, 0, 0))
    mask_spec = pl.BlockSpec((1, 1, bk), lambda bi, ki: (bi, 0, ki))
    part_spec = pl.BlockSpec((1, 1, h, d), lambda bi, ki: (bi, ki, 0, 0))
    row_spec = pl.BlockSpec((1, 1, h, 1), lambda bi, ki: (bi, ki, 0, 0))

    # Scale operands exist ONLY on the quantized path: the kernel
    # signature (and its BlockSpec copies) carries exactly what the
    # cache format stores.
    if quantized:
        operands = (q, kq, ks, vq, vs, mask3)
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec, mask_spec]
    else:
        operands = (q, kq, vq, mask3)
        in_specs = [q_spec, kv_spec, kv_spec, mask_spec]

    acc, m, l = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, kv_heads=kvh, group=group,
            quantized=quantized,
        ),
        grid=(b, nk),
        in_specs=in_specs,
        out_specs=[part_spec, row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, nk, h, d), jnp.float32),
            jax.ShapeDtypeStruct((b, nk, h, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, nk, h, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    return _splitk_merge(acc, m, l, q.dtype)


def _splitk_merge_rows(acc, m, l):
    """Split-K reduction: merge the per-tile (acc, m, l) triples with
    the log-sum-exp algebra, per row. All-dead rows (l == 0
    everywhere) come out exactly zero — a decode step always has
    >= 1 valid key (the token it just wrote); an extend span's
    all-pad query rows come out zero and are never read. Shared
    verbatim by the contiguous and paged kernels AND by the decode
    and extend row layouts: the page table changes WHERE a tile's
    bytes live and the Q-tile height changes how many rows merge —
    never the merge arithmetic."""
    m_max = jnp.max(m, axis=1)                       # [B, R, 1]
    alpha = jnp.exp(m - m_max[:, None])              # [B, nk, R, 1]
    l_tot = jnp.sum(alpha * l, axis=1)               # [B, R, 1]
    acc_tot = jnp.sum(alpha * acc, axis=1)           # [B, R, D]
    return acc_tot / jnp.maximum(l_tot, 1e-30)


def _splitk_merge(acc, m, l, dtype):
    """Decode-layout stage 2: rows ARE the query heads."""
    out = _splitk_merge_rows(acc, m, l)
    return out.astype(dtype)[:, None]                # [B, 1, H, D]


def _splitk_merge_extend(acc, m, l, dtype, u, kvh, group):
    """Extend-layout stage 2: rows are ``[KVH, U, group]``-flat (each
    KV head's query group contiguous per program); un-flatten back to
    the caller's ``[B, U, H, D]`` — a transpose of a tiny f32 tensor,
    noise next to the cache read the kernel just did."""
    out = _splitk_merge_rows(acc, m, l)              # [B, KVH*U*g, D]
    b, _, d = out.shape
    out = out.reshape(b, kvh, u, group, d).transpose(0, 2, 1, 3, 4)
    return out.reshape(b, u, kvh * group, d).astype(dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret")
)
def extend_attention(
    q,
    k,
    v,
    mask,
    *,
    scale=None,
    block_k: int = 512,
    interpret: bool = False,
):
    """Flash-extend: U-token-query split-K attention over a stored KV
    cache — the multi-token twin of :func:`decode_attention`.

    ``q``: ``[B, U, H, D]``; ``k``/``v``: ``[B, L, KVH, D]`` arrays
    (any float dtype) or int8 ``{"q", "scale"}`` pairs; ``mask``:
    binary ``[B, U, L]`` over keys PER SPAN POSITION (build it with
    ``models.gpt.extend_positions_and_mask`` — its causal intra-span
    structure is what lets U positions attend correctly inside one
    program). Returns ``[B, U, H, D]`` in ``q.dtype``.

    Same grid, same per-tile int8 in-register dequant, same GQA
    grouping, same log-sum-exp merge as the decode kernel — the Q
    tile just carries U rows, so chunked prefill / admission /
    speculative-verify spans stream the cache at its STORED byte
    format, like decode steps do.
    """
    kq, ks = _unpack(k)
    vq, vs = _unpack(v)
    quantized = ks is not None
    if quantized != (vs is not None):
        raise ValueError("k and v must share one cache format")
    b, u, h, d = q.shape
    lk, kvh = kq.shape[1], kq.shape[2]
    if kq.shape != vq.shape or kq.shape[3] != d:
        raise ValueError(
            f"cache shapes disagree with q: k {kq.shape}, v {vq.shape}, "
            f"q {q.shape}"
        )
    if mask.shape != (b, u, lk):
        raise ValueError(
            f"extend mask {mask.shape} must be [B, U, L] = "
            f"[{b}, {u}, {lk}] (per-span-position key validity)"
        )
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    group = h // kvh
    scale = (1.0 / d**0.5) if scale is None else scale
    bk = _fit_block(block_k, lk)
    nk = lk // bk
    rows = kvh * u * group  # the [KVH, U, group]-flat partial layout

    maskf = mask.astype(jnp.float32)  # [B, U, L]

    q_spec = pl.BlockSpec((1, u, h, d), lambda bi, ki: (bi, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, bk, kvh, d), lambda bi, ki: (bi, ki, 0, 0))
    sc_spec = pl.BlockSpec((1, bk, kvh, 1), lambda bi, ki: (bi, ki, 0, 0))
    mask_spec = pl.BlockSpec((1, u, bk), lambda bi, ki: (bi, 0, ki))
    part_spec = pl.BlockSpec((1, 1, rows, d), lambda bi, ki: (bi, ki, 0, 0))
    row_spec = pl.BlockSpec((1, 1, rows, 1), lambda bi, ki: (bi, ki, 0, 0))

    if quantized:
        operands = (q, kq, ks, vq, vs, maskf)
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec, mask_spec]
    else:
        operands = (q, kq, vq, maskf)
        in_specs = [q_spec, kv_spec, kv_spec, mask_spec]

    acc, m, l = pl.pallas_call(
        functools.partial(
            _extend_kernel, scale=scale, kv_heads=kvh, group=group,
            u=u, quantized=quantized,
        ),
        grid=(b, nk),
        in_specs=in_specs,
        out_specs=[part_spec, row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, nk, rows, d), jnp.float32),
            jax.ShapeDtypeStruct((b, nk, rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, nk, rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    return _splitk_merge_extend(acc, m, l, q.dtype, u, kvh, group)


def _paged_kernel(table_ref, q_ref, *refs, scale, kv_heads, group,
                  quantized):
    """The paged grid's kernel body IS the contiguous kernel body: the
    scalar-prefetched page table is consumed entirely by the BlockSpec
    index maps (it decides which pool page each program's k-tile DMA
    reads); the math never sees it."""
    del table_ref
    _decode_kernel(
        q_ref, *refs, scale=scale, kv_heads=kv_heads, group=group,
        quantized=quantized,
    )


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q,
    k,
    v,
    table,
    mask,
    *,
    scale=None,
    interpret: bool = False,
):
    """Page-table flash-decode: split-K single-query attention whose
    k-tiles are POOL PAGES selected per program by a scalar-prefetched
    page table — the ROADMAP's "a page table is one more BlockSpec
    index map", literally.

    ``q``: ``[B, 1, H, D]``; ``k``/``v``: ``[P, page, KVH, D]`` pool
    arrays (any float dtype) or int8 ``{"q", "scale"}`` pool pairs
    (``scale f32[P, page, KVH, 1]``); ``table``: int32 ``[B, NP]``
    pool-page ids per virtual tile; ``mask``: binary ``[B, NP*page]``
    over VIRTUAL key slots (the same ``decode_valid_and_shift`` mask
    the contiguous kernel takes — paging is invisible to the slot
    algebra). Returns ``[B, 1, H, D]`` in ``q.dtype``.

    The grid is ``(B, NP)`` — one program per (row, virtual tile), the
    tile size pinned to the page size so the BlockSpec copy of tile
    ``ki`` is exactly ``pool[table[b, ki]]``: sequences scattered
    across non-contiguous pages stream through the SAME kernel body as
    the contiguous layout, with the int8 in-register dequantization
    and dead-tile ``pl.when`` skipping intact. Null-page tiles
    (unallocated table entries) DMA the reserved page and are fully
    masked — their programs take the dead-tile branch.
    """
    from jax.experimental.pallas import tpu as pltpu

    kq, ks = _unpack(k)
    vq, vs = _unpack(v)
    quantized = ks is not None
    if quantized != (vs is not None):
        raise ValueError("k and v must share one cache format")
    b, one, h, d = q.shape
    if one != 1:
        # U-token dispatch (r11) — the paged twin of the extend
        # dispatch in :func:`decode_attention`.
        if mask.ndim != 3 or mask.shape[:2] != (b, one):
            raise ValueError(
                f"multi-token q {q.shape} needs a per-query-row "
                f"[B, U, NP*page] mask (got {mask.shape})"
            )
        return paged_extend_attention(
            q, k, v, table, mask, scale=scale, interpret=interpret,
        )
    page, kvh = kq.shape[1], kq.shape[2]
    np_tiles = table.shape[1]
    if kq.shape != vq.shape or kq.shape[3] != d:
        raise ValueError(
            f"pool shapes disagree with q: k {kq.shape}, v {vq.shape}, "
            f"q {q.shape}"
        )
    if mask.shape != (b, np_tiles * page):
        raise ValueError(
            f"mask {mask.shape} must cover the virtual layout "
            f"[{b}, {np_tiles * page}]"
        )
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    group = h // kvh
    scale = (1.0 / d**0.5) if scale is None else scale

    mask3 = mask.astype(jnp.float32)[:, None, :]  # [B, 1, NP*page]

    q_spec = pl.BlockSpec((1, 1, h, d), lambda bi, ki, t: (bi, 0, 0, 0))
    # THE page-table indirection: tile ki of row bi is pool page
    # t[bi, ki]. Everything else is the contiguous kernel's spec set
    # with the table ref riding as a trailing index-map argument.
    kv_spec = pl.BlockSpec(
        (1, page, kvh, d), lambda bi, ki, t: (t[bi, ki], 0, 0, 0)
    )
    sc_spec = pl.BlockSpec(
        (1, page, kvh, 1), lambda bi, ki, t: (t[bi, ki], 0, 0, 0)
    )
    mask_spec = pl.BlockSpec((1, 1, page), lambda bi, ki, t: (bi, 0, ki))
    part_spec = pl.BlockSpec((1, 1, h, d), lambda bi, ki, t: (bi, ki, 0, 0))
    row_spec = pl.BlockSpec((1, 1, h, 1), lambda bi, ki, t: (bi, ki, 0, 0))

    if quantized:
        operands = (kq, ks, vq, vs, mask3)
        in_specs = [kv_spec, sc_spec, kv_spec, sc_spec, mask_spec]
    else:
        operands = (kq, vq, mask3)
        in_specs = [kv_spec, kv_spec, mask_spec]

    acc, m, l = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=scale, kv_heads=kvh, group=group,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, np_tiles),
            in_specs=[q_spec, *in_specs],
            out_specs=[part_spec, row_spec, row_spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, np_tiles, h, d), jnp.float32),
            jax.ShapeDtypeStruct((b, np_tiles, h, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, np_tiles, h, 1), jnp.float32),
        ],
        interpret=interpret,
    )(table, q, *operands)

    return _splitk_merge(acc, m, l, q.dtype)


def _paged_extend_kernel(table_ref, q_ref, *refs, scale, kv_heads,
                         group, u, quantized):
    """The paged extend grid's kernel body IS the contiguous extend
    body — the scalar-prefetched table is consumed by the BlockSpec
    index maps, exactly as in the decode pair."""
    del table_ref
    _extend_kernel(
        q_ref, *refs, scale=scale, kv_heads=kv_heads, group=group,
        u=u, quantized=quantized,
    )


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_extend_attention(
    q,
    k,
    v,
    table,
    mask,
    *,
    scale=None,
    interpret: bool = False,
):
    """Page-table flash-extend: U-token split-K attention whose
    k-tiles are POOL PAGES selected per program by the scalar-
    prefetched page table — :func:`paged_decode_attention` with a Q
    tile of U rows. A span may START mid-page and CROSS page
    boundaries freely: the ``[B, U, NP*page]`` virtual-slot mask
    (``extend_positions_and_mask`` over the virtual layout) carries
    all of that, the same way paging is invisible to the decode
    kernel's slot algebra.

    ``q``: ``[B, U, H, D]``; ``k``/``v``: ``[P, page, KVH, D]`` pool
    arrays or int8 ``{"q", "scale"}`` pool pairs; ``table``: int32
    ``[B, NP]``. Returns ``[B, U, H, D]`` in ``q.dtype``.
    """
    from jax.experimental.pallas import tpu as pltpu

    kq, ks = _unpack(k)
    vq, vs = _unpack(v)
    quantized = ks is not None
    if quantized != (vs is not None):
        raise ValueError("k and v must share one cache format")
    b, u, h, d = q.shape
    page, kvh = kq.shape[1], kq.shape[2]
    np_tiles = table.shape[1]
    if kq.shape != vq.shape or kq.shape[3] != d:
        raise ValueError(
            f"pool shapes disagree with q: k {kq.shape}, v {vq.shape}, "
            f"q {q.shape}"
        )
    if mask.shape != (b, u, np_tiles * page):
        raise ValueError(
            f"extend mask {mask.shape} must cover the virtual layout "
            f"[{b}, {u}, {np_tiles * page}]"
        )
    if h % kvh:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({kvh})"
        )
    group = h // kvh
    scale = (1.0 / d**0.5) if scale is None else scale
    rows = kvh * u * group

    maskf = mask.astype(jnp.float32)  # [B, U, NP*page]

    q_spec = pl.BlockSpec((1, u, h, d), lambda bi, ki, t: (bi, 0, 0, 0))
    kv_spec = pl.BlockSpec(
        (1, page, kvh, d), lambda bi, ki, t: (t[bi, ki], 0, 0, 0)
    )
    sc_spec = pl.BlockSpec(
        (1, page, kvh, 1), lambda bi, ki, t: (t[bi, ki], 0, 0, 0)
    )
    mask_spec = pl.BlockSpec((1, u, page), lambda bi, ki, t: (bi, 0, ki))
    part_spec = pl.BlockSpec(
        (1, 1, rows, d), lambda bi, ki, t: (bi, ki, 0, 0)
    )
    row_spec = pl.BlockSpec(
        (1, 1, rows, 1), lambda bi, ki, t: (bi, ki, 0, 0)
    )

    if quantized:
        operands = (kq, ks, vq, vs, maskf)
        in_specs = [kv_spec, sc_spec, kv_spec, sc_spec, mask_spec]
    else:
        operands = (kq, vq, maskf)
        in_specs = [kv_spec, kv_spec, mask_spec]

    acc, m, l = pl.pallas_call(
        functools.partial(
            _paged_extend_kernel, scale=scale, kv_heads=kvh,
            group=group, u=u, quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, np_tiles),
            in_specs=[q_spec, *in_specs],
            out_specs=[part_spec, row_spec, row_spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, np_tiles, rows, d), jnp.float32),
            jax.ShapeDtypeStruct((b, np_tiles, rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, np_tiles, rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(table, q, *operands)

    return _splitk_merge_extend(acc, m, l, q.dtype, u, kvh, group)


def _head_sharded_call(mesh, fn, q, k, v, head_axis_specs, extras):
    """shard_map a decode kernel over the TP ``model`` axis so the
    compiled ``pallas_call`` — an opaque custom call GSPMD cannot see
    into — runs PER SHARD on its local head slice instead of risking
    an all-gather of the head-sharded cache operands around it (the
    ROADMAP open item this wrapper closes). ``head_axis_specs`` maps
    each of (q, k, v) — arrays or {"q","scale"} pairs — to its
    PartitionSpec; ``extras`` are replicated operands (mask, table).

    Every per-KV-head loop iteration in the kernel is independent, so
    sharding heads is exact: each shard computes its own query-head
    group's full softmax (m/l normalizers are per head) and the
    outputs concatenate back over the head axis."""
    # jax.shard_map graduated from jax.experimental between releases;
    # accept either spelling (the experimental checker needs
    # check_rep=False to admit pallas_call — same note as
    # ring_attention).
    if hasattr(jax, "shard_map"):
        _shard_map = jax.shard_map
        extra = {}
    else:
        from jax.experimental.shard_map import shard_map as _shard_map

        extra = {"check_rep": False}

    q_spec, kv_spec = head_axis_specs
    rep = jax.sharding.PartitionSpec()

    def tree_spec(operand):
        if isinstance(operand, dict):
            return {name: kv_spec for name in operand}
        return kv_spec

    mapped = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(q_spec, tree_spec(k), tree_spec(v),
                  *([rep] * len(extras))),
        out_specs=q_spec,
        **extra,
    )
    return mapped(q, k, v, *extras)


def decode_attention_tp(
    mesh, q, k, v, mask, *, scale=None, block_k: int = 512,
    interpret: bool = False, axis: str = "model",
):
    """:func:`decode_attention` under model-axis tensor parallelism:
    q ``[B, 1, H, D]`` and the cache operands ``[B, L, KVH, D]`` are
    head-sharded over ``axis``; the mask is replicated. Requires the
    axis size to divide KVH (the caller falls back to the unwrapped
    kernel otherwise — GSPMD then decides, as before)."""
    P = jax.sharding.PartitionSpec
    return _head_sharded_call(
        mesh,
        lambda q_, k_, v_, m_: decode_attention(
            q_, k_, v_, m_, scale=scale, block_k=block_k,
            interpret=interpret,
        ),
        q, k, v,
        (P(None, None, axis, None), P(None, None, axis, None)),
        (mask,),
    )


def paged_decode_attention_tp(
    mesh, q, k, v, table, mask, *, scale=None, interpret: bool = False,
    axis: str = "model",
):
    """:func:`paged_decode_attention` under model-axis TP: the pools
    ``[P, page, KVH, D]`` shard on their head axis, the page table and
    mask replicate (page ids are head-invariant — every shard walks
    the same table over its own head slice of the pool)."""
    P = jax.sharding.PartitionSpec
    return _head_sharded_call(
        mesh,
        lambda q_, k_, v_, t_, m_: paged_decode_attention(
            q_, k_, v_, t_, m_, scale=scale, interpret=interpret,
        ),
        q, k, v,
        (P(None, None, axis, None), P(None, None, axis, None)),
        (table, mask),
    )


def extend_attention_tp(
    mesh, q, k, v, mask, *, scale=None, block_k: int = 512,
    interpret: bool = False, axis: str = "model",
):
    """:func:`extend_attention` under model-axis TP — the extend leg
    of :func:`_head_sharded_call`. Sharding is identical to the
    decode wrapper's (q ``[B, U, H, D]`` and the cache operands
    head-sharded over ``axis``, the ``[B, U, L]`` mask replicated):
    the Q tile's extra rows change nothing about head independence —
    every shard computes full per-head softmaxes for its own query
    group across all U span positions. This is what lets speculative
    verify and chunked prefill run kernel-native over MESH-SHARDED
    caches (the last paged x spec decline's mesh half)."""
    P = jax.sharding.PartitionSpec
    return _head_sharded_call(
        mesh,
        lambda q_, k_, v_, m_: extend_attention(
            q_, k_, v_, m_, scale=scale, block_k=block_k,
            interpret=interpret,
        ),
        q, k, v,
        (P(None, None, axis, None), P(None, None, axis, None)),
        (mask,),
    )


def paged_extend_attention_tp(
    mesh, q, k, v, table, mask, *, scale=None, interpret: bool = False,
    axis: str = "model",
):
    """:func:`paged_extend_attention` under model-axis TP: pools
    shard on their head axis, the table and the ``[B, U, NP*page]``
    mask replicate — the composition the mesh-sharded-pool
    speculative-verify path dispatches."""
    P = jax.sharding.PartitionSpec
    return _head_sharded_call(
        mesh,
        lambda q_, k_, v_, t_, m_: paged_extend_attention(
            q_, k_, v_, t_, m_, scale=scale, interpret=interpret,
        ),
        q, k, v,
        (P(None, None, axis, None), P(None, None, axis, None)),
        (table, mask),
    )
