"""Pallas TPU kernels — the hand-scheduled hot ops.

XLA fusion covers most of this framework (SURVEY §2: the reference's
only native code is transitive BLAS, so "native" here means kernels
against the TPU's own memory hierarchy). These kernels exist where
hand control of VMEM/MXU beats the XLA default:

- ``flash_attention`` — fused attention: scores, softmax and the
  probability-value contraction stay in VMEM per q-block; the [L, L]
  score matrix never touches HBM.
- ``decode_attention`` — split-K flash-decode for the serving hot
  path: single-query attention over the stored KV cache, int8
  payload + scale tiles dequantized per tile in registers — int8 is
  what crosses HBM on the decode read.
- ``extend_attention`` — flash-extend, the U-token-query twin: every
  multi-token span (chunked prefill, admission mini-prefills,
  speculative verify) streams the stored cache through the same
  split-K grid, so the byte saving covers every token the server
  processes, not just decode steps.
"""

from mlapi_tpu.ops.pallas.decode_attention import (
    decode_attention,
    decode_attention_tp,
    extend_attention,
    extend_attention_tp,
    paged_decode_attention,
    paged_decode_attention_tp,
    paged_extend_attention,
    paged_extend_attention_tp,
)
from mlapi_tpu.ops.pallas.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
)

__all__ = [
    "decode_attention",
    "decode_attention_tp",
    "extend_attention",
    "extend_attention_tp",
    "paged_decode_attention",
    "paged_decode_attention_tp",
    "paged_extend_attention",
    "paged_extend_attention_tp",
    "flash_attention",
    "flash_attention_with_lse",
]
