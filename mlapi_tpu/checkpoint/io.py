"""Checkpoint I/O: orbax param pytrees + a versioned JSON manifest.

Replaces the reference's train→serve handoff — ``pickle.dump`` of a
whole sklearn estimator in the notebook, ``pickle.load`` on **every
request** at ``main.py:19`` — which had no versioning, no integrity
check, and (being pickle) executed arbitrary code from an untrusted
file. Here:

- Params are an orbax (tensorstore) pytree checkpoint — zero pickle,
  atomic commit, works with sharded arrays across a mesh/multi-host.
- A ``MANIFEST.json`` sidecar carries format version, step, training
  config + its hash, the label vocab, and a structural signature of
  the param tree (paths/shapes/dtypes) so a mismatched restore fails
  loudly instead of silently mis-predicting.
- The manifest is written *after* the params commit and via
  tmp+rename, so a manifest's existence implies a complete
  checkpoint.

Layout::

    <root>/step_00000500/
        MANIFEST.json
        params/            # orbax checkpoint
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax

from mlapi_tpu import __version__ as _framework_version
from mlapi_tpu.utils.vocab import LabelVocab

FORMAT_VERSION = 1
_MANIFEST = "MANIFEST.json"
_PARAMS_DIR = "params"


def _stable_hash(obj: Any) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def tree_signature(params) -> str:
    """Structural signature of a pytree: key paths + shapes + dtypes.

    Cheap (no data read) and catches the silent killers: renamed
    layers, transposed weights, wrong dtype, wrong model for the
    checkpoint.
    """
    leaves = [
        (jax.tree_util.keystr(path), tuple(leaf.shape), str(leaf.dtype))
        for path, leaf in jax.tree_util.tree_leaves_with_path(params)
    ]
    return _stable_hash(leaves)


@dataclass(frozen=True)
class CheckpointMeta:
    """Everything about a checkpoint except the weights."""

    format_version: int
    framework_version: str
    step: int
    created_unix: float
    config: dict
    config_hash: str
    tree_signature: str
    vocab: LabelVocab | None

    def to_json(self) -> dict:
        return {
            "format_version": self.format_version,
            "framework_version": self.framework_version,
            "step": self.step,
            "created_unix": self.created_unix,
            "config": self.config,
            "config_hash": self.config_hash,
            "tree_signature": self.tree_signature,
            "vocab": self.vocab.to_json() if self.vocab else None,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "CheckpointMeta":
        return cls(
            format_version=obj["format_version"],
            framework_version=obj["framework_version"],
            step=obj["step"],
            created_unix=obj["created_unix"],
            config=obj["config"],
            config_hash=obj["config_hash"],
            tree_signature=obj["tree_signature"],
            vocab=LabelVocab.from_json(obj["vocab"]) if obj.get("vocab") else None,
        )


# Seams for the commit-barrier logic (tests mock these to exercise the
# multi-process paths without a real jax.distributed runtime; orbax
# reads jax.process_count() itself, so patching jax globally breaks it).
def _process_count() -> int:
    return jax.process_count()


def _process_index() -> int:
    return jax.process_index()


def save_checkpoint(
    path: str | os.PathLike,
    params,
    *,
    step: int = 0,
    config: dict | None = None,
    vocab: LabelVocab | None = None,
) -> Path:
    """Write a complete checkpoint at ``path`` (a single step dir)."""
    import orbax.checkpoint as ocp

    # resolve(), not absolute(): the path string feeds the multi-host
    # barrier keys below, so symlinked mounts / '..' segments / cwd
    # differences across processes must normalise to one spelling.
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    config = dict(config or {})

    ckptr = ocp.StandardCheckpointer()
    params_path = path / _PARAMS_DIR
    ckptr.save(params_path, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()

    # Multi-host: every process reaches here after *its own* shards
    # landed, but the manifest is the commit marker for the WHOLE
    # checkpoint — so barrier, let only process 0 write it, then
    # barrier again so no process returns (and e.g. reads the path
    # back or reports success) until the manifest actually exists.
    # Barrier keys must be HOST-INVARIANT: processes may mount the
    # shared checkpoint filesystem at different points (or resolve
    # through different symlinks), so the local resolved path cannot
    # feed the key — each host would derive a different one and
    # deadlock. The key is built from what every process agrees on:
    # leaf dir name, step, config hash, and the param-tree signature.
    # (Two *concurrent* saves of the same config+step into different
    # roots would cross-match — a far narrower hazard than the
    # mount-point mismatch, and one no sane launcher produces.)
    # Known limitation: if process 0 dies between the two barriers
    # (manifest write failure, disk full), the other processes block in
    # ckpt_post until the distributed runtime propagates the abort —
    # the same contract as any collective, and strictly safer than
    # returning success without a committed manifest.
    multi = _process_count() > 1
    cfg_hash = _stable_hash(config)
    tree_sig = tree_signature(params)
    if multi:
        from jax.experimental import multihost_utils

        key = _stable_hash([path.name, int(step), cfg_hash, tree_sig])
        multihost_utils.sync_global_devices(f"ckpt_pre:{key}")
        if _process_index() != 0:
            multihost_utils.sync_global_devices(f"ckpt_post:{key}")
            return path

    meta = CheckpointMeta(
        format_version=FORMAT_VERSION,
        framework_version=_framework_version,
        step=int(step),
        created_unix=time.time(),
        config=config,
        config_hash=cfg_hash,
        tree_signature=tree_sig,
        vocab=vocab,
    )
    # Manifest last, atomically: its presence is the commit marker.
    tmp = path / f".{_MANIFEST}.tmp"
    tmp.write_text(json.dumps(meta.to_json(), indent=2, sort_keys=True))
    tmp.rename(path / _MANIFEST)
    if multi:
        multihost_utils.sync_global_devices(f"ckpt_post:{key}")
    return path


def read_manifest(path: str | os.PathLike) -> CheckpointMeta:
    """Read a checkpoint's metadata WITHOUT touching the tensors.

    Cheap (one small JSON file) — use it to validate a checkpoint
    before paying for the orbax/tensorstore restore.
    """
    path = Path(path).absolute()
    manifest = path / _MANIFEST
    if not manifest.exists():
        raise FileNotFoundError(
            f"{path} is not a committed checkpoint (no {_MANIFEST})"
        )
    meta = CheckpointMeta.from_json(json.loads(manifest.read_text()))
    if meta.format_version > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format v{meta.format_version} is newer than this "
            f"framework understands (v{FORMAT_VERSION})"
        )
    return meta


def load_checkpoint(
    path: str | os.PathLike,
    abstract_params=None,
) -> tuple[Any, CheckpointMeta]:
    """Restore ``(params, meta)`` from a checkpoint dir.

    ``abstract_params`` (a pytree of ``jax.ShapeDtypeStruct`` — may
    carry ``sharding`` to restore directly onto a mesh) both selects
    the restore layout and is validated against the manifest's tree
    signature, so loading the wrong model's checkpoint raises instead
    of mis-predicting.

    Leaves WITHOUT a sharding restore onto the default device
    explicitly: orbax would otherwise read the sharding recorded at
    SAVE time, which names devices of the saving topology — a
    checkpoint trained on the TPU must load on a CPU-attached server
    (train-on-chip, serve-anywhere), and did not before this pinned
    the restore layout locally.

    CONTRACT: this pin applies to every sharding-less leaf, including
    the ``abstract_params=None`` path (abstracts built from checkpoint
    metadata). Callers that want the save-time sharding back on a
    multi-device topology — e.g. a model-parallel tree larger than one
    device — must pass ``abstract_params`` with explicit shardings
    (training resume does: it passes the live train-state layout);
    relying on orbax's recorded sharding is no longer supported.
    """
    import jax
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    meta = read_manifest(path)

    if abstract_params is not None:
        expect = tree_signature(abstract_params)
        if expect != meta.tree_signature:
            raise ValueError(
                "checkpoint/model mismatch: expected param tree signature "
                f"{expect}, checkpoint has {meta.tree_signature} "
                f"(step {meta.step}, config {meta.config})"
            )
    else:
        # No layout given: build one from the checkpoint's own array
        # metadata (shapes/dtypes) so the topology pin below applies
        # to this path too — not just to callers that know the tree.
        with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as mck:
            im = mck.metadata(path / _PARAMS_DIR)
        # Orbax's metadata container changed across releases: newer
        # versions wrap the tree in .item_metadata (sometimes again in
        # .tree), older ones return the metadata pytree directly.
        im = getattr(im, "item_metadata", im)
        abstract_params = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
            im.tree if hasattr(im, "tree") else im,
        )
    local = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract_params = jax.tree.map(
        lambda a: (
            a
            if getattr(a, "sharding", None) is not None
            else jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=local)
        ),
        abstract_params,
    )

    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(path / _PARAMS_DIR, abstract_params)
    ckptr.close()
    return params, meta


def latest_step(root: str | os.PathLike) -> Path | None:
    """Newest committed ``step_*`` dir under ``root`` (resume point)."""
    root = Path(root)
    if not root.exists():
        return None
    best: tuple[int, Path] | None = None
    for child in root.iterdir():
        if child.name.startswith("step_") and (child / _MANIFEST).exists():
            try:
                n = int(child.name.removeprefix("step_"))
            except ValueError:
                continue
            if best is None or n > best[0]:
                best = (n, child)
    return best[1] if best else None


def step_dir(root: str | os.PathLike, step: int) -> Path:
    return Path(root) / f"step_{step:08d}"


def gc_checkpoints(root: str | os.PathLike, keep_last: int) -> list[Path]:
    """Delete all but the newest ``keep_last`` COMMITTED ``step_*``
    dirs under ``root``; returns the deleted paths.

    Only committed checkpoints (manifest present) are touched: an
    uncommitted dir might be a save in progress on another process —
    its writer owns it, not the collector. Deletion de-commits first
    (manifest unlinked before the tree is removed) so a crash
    mid-delete can never leave a "committed" half-checkpoint behind;
    multi-host callers run this on process 0 only (the same process
    that owns manifest writes).
    """
    import shutil

    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    root = Path(root)
    if not root.exists():
        return []
    committed: list[tuple[int, Path]] = []
    for child in root.iterdir():
        if child.name.startswith("step_") and (child / _MANIFEST).exists():
            try:
                committed.append((int(child.name.removeprefix("step_")), child))
            except ValueError:
                continue
    committed.sort()
    doomed = [p for _, p in committed[:-keep_last]]
    for p in doomed:
        (p / _MANIFEST).unlink()
        shutil.rmtree(p, ignore_errors=True)
    return doomed
