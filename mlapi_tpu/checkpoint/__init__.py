"""Versioned, atomic, pickle-free checkpoints."""

from mlapi_tpu.checkpoint.io import (  # noqa: F401
    CheckpointMeta,
    gc_checkpoints,
    latest_step,
    load_checkpoint,
    read_manifest,
    save_checkpoint,
    tree_signature,
)
