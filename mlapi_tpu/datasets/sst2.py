"""SST-2 sentiment — config 5 of the ladder (``BASELINE.json:11``).

Reads the GLUE TSV files (``sentence<TAB>label`` with a header) from
``$MLAPI_TPU_DATA_DIR/sst2/`` or ``data/sst2/`` when present;
air-gapped fallback is a deterministic synthetic sentiment corpus:
sentences of neutral filler words with planted polarity words, which
a BERT (with hash-tokenized ids) can only classify by learning token
embeddings — the full text pipeline, end to end.

Rows are pre-tokenized to fixed-length int32 id vectors so the
standard ``SupervisedSplits`` train path applies unchanged; the
attention mask is recomputed inside the model (``ids != pad``).
"""

from __future__ import annotations

import csv
import os
from pathlib import Path

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits, register_dataset
from mlapi_tpu.utils.vocab import LabelVocab

LABELS = ("negative", "positive")

_POSITIVE = (
    "wonderful", "delightful", "charming", "moving", "brilliant",
    "captivating", "superb", "heartfelt", "masterful", "joyous",
)
_NEGATIVE = (
    "dreadful", "tedious", "clumsy", "hollow", "grating",
    "lifeless", "shoddy", "dismal", "incoherent", "stale",
)
_FILLER = (
    "the", "movie", "film", "story", "plot", "acting", "scene",
    "director", "script", "ending", "a", "with", "and", "of", "was",
    "that", "this", "its", "on", "in",
)


def _synthetic_corpus(n: int, rng) -> tuple[list[str], np.ndarray]:
    texts, labels = [], np.empty(n, np.int32)
    for i in range(n):
        label = int(rng.integers(0, 2))
        words = list(rng.choice(_FILLER, size=int(rng.integers(6, 14))))
        pool = _POSITIVE if label else _NEGATIVE
        for _ in range(int(rng.integers(1, 3))):
            words.insert(int(rng.integers(0, len(words))), str(rng.choice(pool)))
        texts.append(" ".join(words))
        labels[i] = label
    return texts, labels


def _read_tsv(path: Path) -> tuple[list[str], np.ndarray]:
    texts, labels = [], []
    with open(path, newline="", encoding="utf-8") as f:
        reader = csv.reader(f, delimiter="\t", quoting=csv.QUOTE_NONE)
        header = next(reader)
        s_col = header.index("sentence")
        l_col = header.index("label")
        for row in reader:
            texts.append(row[s_col])
            labels.append(int(row[l_col]))
    return texts, np.asarray(labels, np.int32)


def load_sst2(
    *,
    max_len: int = 128,
    tokenizer=None,
    vocab_size: int = 30522,
    n_train: int = 8192,
    n_test: int = 1024,
    seed: int = 11,
) -> SupervisedSplits:
    from mlapi_tpu.text import load_tokenizer

    tokenizer = tokenizer or load_tokenizer(vocab_size)

    data_dir = None
    for root in (os.environ.get("MLAPI_TPU_DATA_DIR"), "data"):
        if root and (Path(root) / "sst2" / "train.tsv").exists():
            data_dir = Path(root) / "sst2"
            break

    if data_dir is not None:
        train_texts, y_train = _read_tsv(data_dir / "train.tsv")
        test_texts, y_test = _read_tsv(data_dir / "dev.tsv")
        source = "tsv"
    else:
        train_texts, y_train = _synthetic_corpus(
            n_train, np.random.default_rng((seed, 1))
        )
        test_texts, y_test = _synthetic_corpus(
            n_test, np.random.default_rng((seed, 2))
        )
        source = "synthetic"

    def encode_all(texts):
        return np.stack([tokenizer.encode(t, max_len)[0] for t in texts])

    return SupervisedSplits(
        x_train=encode_all(train_texts),
        y_train=y_train,
        x_test=encode_all(test_texts),
        y_test=y_test,
        vocab=LabelVocab(labels=LABELS),
        source=source,
        extras={"tokenizer": tokenizer.fingerprint(), "max_len": max_len},
    )


register_dataset("sst2")(load_sst2)
