"""Dataset loaders for the config ladder.

All loaders return a :class:`SupervisedSplits` of host-side numpy
arrays plus the string-label vocab. Loaders never hit the network:
Iris ships with scikit-learn; MNIST-family loaders read local IDX
files when present and otherwise fall back to a clearly-labelled
deterministic synthetic generator (this build environment is
air-gapped); Criteo and SST-2 use synthetic generators sized by
config. Replaces the reference's in-notebook
``pd.read_csv(<UCI URL>)`` ingestion (``Logistic Regression.ipynb``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from mlapi_tpu.utils.vocab import LabelVocab


@dataclass(frozen=True)
class SupervisedSplits:
    """Train/test split of a supervised dataset, labels already encoded.

    ``source`` records provenance: ``"real"`` / ``"idx"`` for actual
    dataset files, ``"synthetic"`` for the air-gapped stand-ins.
    """

    x_train: np.ndarray
    y_train: np.ndarray  # int32 class ids
    x_test: np.ndarray
    y_test: np.ndarray  # int32 class ids
    vocab: LabelVocab
    feature_names: tuple[str, ...] = ()
    source: str = "real"
    # Loader-specific metadata that must travel into checkpoints
    # (e.g. the text pipeline's tokenizer fingerprint + max_len).
    extras: dict = field(default_factory=dict)

    @property
    def num_features(self) -> int:
        return int(np.prod(self.x_train.shape[1:]))

    @property
    def num_classes(self) -> int:
        return self.vocab.size


from mlapi_tpu.utils.registry import Registry

_LOADERS: Registry = Registry("dataset")
register_dataset = _LOADERS.register


def get_dataset(name: str, **kwargs) -> SupervisedSplits:
    """Load a dataset by registry name (``iris``, ``mnist``, …)."""
    return _LOADERS.get(name)(**kwargs)


def get_dataset_loader(name: str):
    """The registered loader CALLABLE (callers introspect its
    signature — e.g. the train CLI only injects a ``tokenizer``
    kwarg into loaders that declare one)."""
    return _LOADERS.get(name)


def dataset_registered(name: str) -> bool:
    return name in _LOADERS


def registered_datasets() -> list[str]:
    return _LOADERS.names()


from mlapi_tpu.datasets.iris import load_iris  # noqa: E402,F401
from mlapi_tpu.datasets.mnist import (  # noqa: E402,F401
    load_fashion_mnist,
    load_mnist,
)

register_dataset("iris")(load_iris)
register_dataset("mnist")(load_mnist)
register_dataset("fashion_mnist")(load_fashion_mnist)

from mlapi_tpu.datasets.criteo import load_criteo  # noqa: E402,F401  (self-registers)
from mlapi_tpu.datasets.digits import load_digits  # noqa: E402,F401  (self-registers)
from mlapi_tpu.datasets.sst2 import load_sst2  # noqa: E402,F401  (self-registers)
from mlapi_tpu.datasets.textlm import load_docs_text  # noqa: E402,F401  (self-registers)
from mlapi_tpu.datasets.docs_clf import load_docs_clf  # noqa: E402,F401  (self-registers)
