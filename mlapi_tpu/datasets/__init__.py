"""Dataset loaders for the config ladder.

All loaders return a :class:`SupervisedSplits` of host-side numpy
arrays plus the string-label vocab. Loaders never hit the network:
Iris ships with scikit-learn; MNIST-family loaders read local IDX
files when present and otherwise fall back to a clearly-labelled
deterministic synthetic generator (this build environment is
air-gapped); Criteo and SST-2 use synthetic generators sized by
config. Replaces the reference's in-notebook
``pd.read_csv(<UCI URL>)`` ingestion (``Logistic Regression.ipynb``).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from mlapi_tpu.utils.vocab import LabelVocab


@dataclass(frozen=True)
class SupervisedSplits:
    """Train/test split of a supervised dataset, labels already encoded."""

    x_train: np.ndarray
    y_train: np.ndarray  # int32 class ids
    x_test: np.ndarray
    y_test: np.ndarray  # int32 class ids
    vocab: LabelVocab
    feature_names: tuple[str, ...] = ()

    @property
    def num_features(self) -> int:
        return int(np.prod(self.x_train.shape[1:]))

    @property
    def num_classes(self) -> int:
        return self.vocab.size


from mlapi_tpu.datasets.iris import load_iris  # noqa: E402,F401
