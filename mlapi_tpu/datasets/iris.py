"""Iris dataset — config 1 of the ladder.

The reference ingests the UCI Iris CSV over HTTPS with explicit column
names and string labels, then splits 80/20 with
``train_test_split(test_size=0.20, random_state=1, shuffle=True)``
(``Logistic Regression.ipynb``, single cell). This loader reproduces
the same data and the exact same split offline: scikit-learn bundles
the UCI copy of Iris (including UCI's two errata rows), and we reuse
sklearn's ``train_test_split`` with the same arguments so held-out
accuracy is comparable against the reference's published
0.9666666666666667.

Labels are restored to the UCI string form (``Iris-setosa`` …) because
that is what the reference's ``/predict`` returns (``main.py:24-27``).
"""

from __future__ import annotations

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits
from mlapi_tpu.utils.vocab import LabelVocab

FEATURE_NAMES = (
    "sepal_length",
    "sepal_width",
    "petal_length",
    "petal_width",
)


def load_iris(*, test_fraction: float = 0.20, seed: int = 1) -> SupervisedSplits:
    """Load Iris with the reference's split (150 rows → 120 train / 30 test)."""
    from sklearn.datasets import load_iris as _sk_load_iris
    from sklearn.model_selection import train_test_split as _sk_split

    raw = _sk_load_iris()
    x = raw.data.astype(np.float32)  # [150, 4]
    # sklearn names are 'setosa' etc.; UCI / the reference use 'Iris-setosa'.
    labels = np.asarray([f"Iris-{raw.target_names[t]}" for t in raw.target])
    vocab = LabelVocab.from_labels(labels)
    y = vocab.encode(labels)

    # Same splitter, same arguments as the reference notebook → same rows.
    x_train, x_test, y_train, y_test = _sk_split(
        x, y, test_size=test_fraction, random_state=seed, shuffle=True
    )
    return SupervisedSplits(
        x_train=x_train,
        y_train=y_train.astype(np.int32),
        x_test=x_test,
        y_test=y_test.astype(np.int32),
        vocab=vocab,
        feature_names=FEATURE_NAMES,
    )
