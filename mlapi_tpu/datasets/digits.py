"""Handwritten digits (sklearn's bundled UCI optdigits) — the one
REAL image-classification dataset available in this air-gapped build.

The ladder's configs 2-3 (MNIST softmax, Fashion-MNIST MLP) fall back
to synthetic generators when their IDX files are absent
(``datasets/mnist.py``), which makes their accuracy numbers
incomparable to anything. This dataset exists to anchor those model
families against real data anyway: 1,797 genuine 8x8 grayscale digit
scans (UCI ML hand-written digits, shipped inside scikit-learn — zero
network), same 10-class problem shape, run through the SAME linear /
MLP models and train loop. Published in ``BASELINE.md``.
"""

from __future__ import annotations

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits, register_dataset
from mlapi_tpu.utils.vocab import LabelVocab


@register_dataset("digits")
def load_digits(
    *, test_fraction: float = 0.20, seed: int = 1
) -> SupervisedSplits:
    """1,797 real 8x8 digit scans → 64 features in [0, 1], split
    80/20 with the same splitter convention as the Iris config."""
    from sklearn.datasets import load_digits as _sk_load_digits
    from sklearn.model_selection import train_test_split as _sk_split

    raw = _sk_load_digits()
    x = (raw.data / 16.0).astype(np.float32)  # [1797, 64], pixel max 16
    labels = np.asarray([str(t) for t in raw.target])
    vocab = LabelVocab.from_labels(labels)
    y = vocab.encode(labels)

    x_train, x_test, y_train, y_test = _sk_split(
        x, y, test_size=test_fraction, random_state=seed, shuffle=True,
        stratify=y,
    )
    return SupervisedSplits(
        x_train=x_train,
        y_train=y_train.astype(np.int32),
        x_test=x_test,
        y_test=y_test.astype(np.int32),
        vocab=vocab,
        feature_names=tuple(f"px_{i}" for i in range(x.shape[1])),
        source="real",
    )
