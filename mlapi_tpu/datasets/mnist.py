"""MNIST / Fashion-MNIST — configs 2-3 of the ladder.

Reads the standard IDX files (``train-images-idx3-ubyte`` etc., the
format both datasets are distributed in) from
``$MLAPI_TPU_DATA_DIR/<name>/`` or ``./data/<name>/``, optionally
gzipped. This environment is air-gapped, so when the files are absent
the loader falls back to a **deterministic synthetic stand-in** —
class-conditional templates plus noise at the same shapes/dtypes —
clearly marked via ``source="synthetic"``. The synthetic sets
exercise the exact same training/serving code paths (784 features, 10
classes); published accuracy claims only apply to runs with the real
files present.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits
from mlapi_tpu.utils.vocab import LabelVocab

MNIST_CLASSES = tuple(str(d) for d in range(10))
FASHION_CLASSES = (
    "T-shirt/top", "Trouser", "Pullover", "Dress", "Coat",
    "Sandal", "Shirt", "Sneaker", "Bag", "Ankle boot",
)

_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _open_maybe_gz(path: Path):
    gz = path.with_name(path.name + ".gz")
    if path.exists():
        return open(path, "rb")
    if gz.exists():
        return gzip.open(gz, "rb")
    raise FileNotFoundError(path)


def read_idx(path: Path) -> np.ndarray:
    """Parse one IDX file (images uint8 [n,r,c]; labels uint8 [n])."""
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _data_dir(name: str) -> Path | None:
    for root in (os.environ.get("MLAPI_TPU_DATA_DIR"), "data"):
        if root is None:
            continue
        d = Path(root) / name
        if d.is_dir():
            return d
    return None


def _load_idx_splits(d: Path, classes: tuple[str, ...]) -> SupervisedSplits:
    x_train = read_idx(d / _FILES["train_images"]).reshape(-1, 784)
    y_train = read_idx(d / _FILES["train_labels"])
    x_test = read_idx(d / _FILES["test_images"]).reshape(-1, 784)
    y_test = read_idx(d / _FILES["test_labels"])
    vocab = LabelVocab(labels=classes)
    return SupervisedSplits(
        x_train=(x_train.astype(np.float32) / 255.0),
        y_train=y_train.astype(np.int32),
        x_test=(x_test.astype(np.float32) / 255.0),
        y_test=y_test.astype(np.int32),
        vocab=vocab,
        source="idx",
    )


def _synthetic_splits(
    classes: tuple[str, ...],
    *,
    seed: int,
    n_train: int,
    n_test: int,
    noise: float = 0.35,
) -> SupervisedSplits:
    """Class-template + Gaussian-noise images, fixed by seed.

    Learnable but not trivially so (templates overlap through noise),
    so optimizer/parallelism regressions still show up as accuracy
    regressions in tests.
    """
    k = len(classes)
    rng = np.random.default_rng(seed)
    templates = rng.uniform(0.0, 1.0, size=(k, 784)).astype(np.float32)

    def make(n: int, rng):
        y = rng.integers(0, k, size=n).astype(np.int32)
        x = templates[y] + rng.normal(0.0, noise, size=(n, 784)).astype(np.float32)
        return np.clip(x, 0.0, 1.0), y

    x_train, y_train = make(n_train, np.random.default_rng((seed, 1)))
    x_test, y_test = make(n_test, np.random.default_rng((seed, 2)))
    return SupervisedSplits(
        x_train=x_train,
        y_train=y_train,
        x_test=x_test,
        y_test=y_test,
        vocab=LabelVocab(labels=classes),
        source="synthetic",
    )


def load_mnist(
    *, seed: int = 0, synthetic_train: int = 8192, synthetic_test: int = 1024
) -> SupervisedSplits:
    d = _data_dir("mnist")
    if d is not None:
        return _load_idx_splits(d, MNIST_CLASSES)
    return _synthetic_splits(
        MNIST_CLASSES, seed=seed, n_train=synthetic_train, n_test=synthetic_test
    )


def load_fashion_mnist(
    *, seed: int = 1, synthetic_train: int = 8192, synthetic_test: int = 1024
) -> SupervisedSplits:
    d = _data_dir("fashion_mnist")
    if d is not None:
        return _load_idx_splits(d, FASHION_CLASSES)
    return _synthetic_splits(
        FASHION_CLASSES, seed=seed, n_train=synthetic_train, n_test=synthetic_test
    )
