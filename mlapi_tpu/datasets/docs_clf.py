"""Real-data TEXT CLASSIFICATION from the repo's own docs — the
strongest config-5 proxy constructible in a zero-egress image
(VERDICT r03 "Next" #9).

Config 5 (BERT on SST-2) has never run on real data here: the GLUE
TSVs and pretrained weights need egress. What CAN be fully real
locally is the *pipeline*: real English prose → tokenize → BERT
classifier → held-out accuracy. This dataset provides it: fixed-length
byte-id windows over the repo's documentation files, labeled by WHICH
FILE each window came from. The classes are genuinely learnable only
from the text (README prose vs design-doc prose vs survey prose differ
in vocabulary and register), the data is 100% real, and the task shape
is exactly SST-2's (short text → class id).

The residual gap to real SST-2 — pretrained weights + the actual GLUE
labels — is documented in BASELINE.md; the ``--from-hf`` train path
closes it the moment a local HF checkpoint appears.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits, register_dataset
from mlapi_tpu.utils.vocab import LabelVocab

# Corpus files, snapshot location, layout fallback, and provenance
# all live in datasets/_corpus.py — shared with docs_text so the two
# doc-driven datasets read the same bytes by construction.
from mlapi_tpu.datasets._corpus import (
    DOC_SOURCES as _DOC_SOURCES,
    corpus_provenance as _corpus_provenance,
    resolve_doc as _resolve_doc,
    resolve_root as _resolve_root,
)


@register_dataset("docs_clf")
def load_docs_clf(
    *,
    seq_len: int = 64,
    stride: int | None = None,
    test_fraction: float = 0.2,
    root: str | None = None,
) -> SupervisedSplits:
    """Byte-id windows over the repo docs, labeled by source file.

    With non-overlapping windows (``stride >= seq_len``, the default)
    the test split is a per-class STRATIFIED RANDOM sample — no token
    appears in both splits, and the split is free of the head-vs-tail
    register shift a positional split would add on top of the task.
    With overlapping windows (``stride < seq_len``) adjacent windows
    share bytes, so the split falls back to each file's TAIL to keep
    train/test disjoint.

    ``root`` selects the corpus: ``None`` (default) reads the FROZEN
    commit-pinned snapshot shipped in ``docs_corpus/`` so measured
    accuracies reproduce; ``"live"`` reads the repo's current docs
    (the old behavior — drifts every round); any other value is a
    directory of the four files (flat or repo-layout).
    """
    from mlapi_tpu.text import ByteTokenizer

    tok = ByteTokenizer()
    stride = stride or seq_len
    base = _resolve_root(root)

    per_class: list[tuple[str, np.ndarray]] = []
    for rel in _DOC_SOURCES:
        p = _resolve_doc(base, rel)
        if p is None:
            continue
        ids = np.asarray(
            tok.token_ids(p.read_text(errors="replace")), np.int32
        )
        if len(ids) < 2 * seq_len:
            continue
        windows = np.stack([
            ids[s: s + seq_len]
            for s in range(0, len(ids) - seq_len + 1, stride)
        ])
        per_class.append((Path(rel).name, windows))
    if len(per_class) < 2:
        raise FileNotFoundError(
            f"docs_clf needs >= 2 documentation files under {base}; "
            f"found {[n for n, _ in per_class]}"
        )

    rng_split = np.random.default_rng(11)
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for label, (name, windows) in enumerate(per_class):
        n_test = max(1, int(len(windows) * test_fraction))
        if stride >= seq_len:
            order = rng_split.permutation(len(windows))
            test_idx, train_idx = order[:n_test], order[n_test:]
            if len(train_idx) == 0:
                raise ValueError(
                    f"docs_clf: class {name!r} yields only "
                    f"{len(windows)} window(s) at seq_len={seq_len} — "
                    f"the test split takes them all and training "
                    f"would silently see zero examples of it; shrink "
                    f"seq_len or test_fraction"
                )
        else:
            # Tail split with overlapping windows: drop train windows
            # whose span reaches into the first test window's bytes,
            # or the boundary pair would share stride..seq_len bytes.
            split = len(windows) - n_test
            test_start_byte = split * stride
            test_idx = np.arange(split, len(windows))
            train_idx = np.asarray(
                [i for i in range(split)
                 if i * stride + seq_len <= test_start_byte],
                np.int64,
            )
            if len(train_idx) == 0:
                raise ValueError(
                    f"docs_clf: class {name!r} has no train windows "
                    f"left after the overlap filter (stride={stride} "
                    f"<< seq_len={seq_len} for a short document) — "
                    f"training would silently see zero examples of "
                    f"it; raise stride or shrink test_fraction"
                )
        xs_tr.append(windows[train_idx])
        ys_tr.append(np.full(len(train_idx), label, np.int32))
        xs_te.append(windows[test_idx])
        ys_te.append(np.full(len(test_idx), label, np.int32))

    # Interleave classes deterministically so full-batch or sequential
    # minibatch training sees every class early.
    rng = np.random.default_rng(7)
    x_train = np.concatenate(xs_tr)
    y_train = np.concatenate(ys_tr)
    order = rng.permutation(len(x_train))
    return SupervisedSplits(
        x_train=x_train[order],
        y_train=y_train[order],
        x_test=np.concatenate(xs_te),
        y_test=np.concatenate(ys_te),
        vocab=LabelVocab(tuple(n for n, _ in per_class)),
        source="real",
        extras={
            "tokenizer": tok.fingerprint(),
            "max_len": seq_len,
            "corpus": _corpus_provenance(base),
        },
    )
