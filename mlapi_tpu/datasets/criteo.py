"""Criteo-style click-through data — config 4 of the ladder
(``BASELINE.json:10``: Wide&Deep, embedding + linear, sharded).

Real Criteo-1TB is obviously not present in an air-gapped build, so
this is a deterministic synthetic generator with the same *shape* of
problem: 13 dense (integer-ish, heavy-tailed) features + 26
categorical features drawn from large hashed vocabularies, binary
click label. The planted structure gives every categorical id a
stable pseudo-random effect, so a model only beats chance by actually
learning per-id embeddings — which is exactly what the sharded
embedding path must get right.

Feature layout matches production Criteo naming: dense ``I1..I13``,
categorical ``C1..C26``. Rows are a single float32 vector
``[I1..I13, C1..C26]`` (categorical ids carried as floats, cast back
to ints inside the model) so the whole tabular train/serve stack
works unchanged.
"""

from __future__ import annotations

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits, register_dataset
from mlapi_tpu.utils.vocab import LabelVocab

LABELS = ("no-click", "click")  # id 1 == click

_MIX1 = np.uint64(0x9E3779B97F4A7C15)
_MIX2 = np.uint64(0xBF58476D1CE4E5B9)


def _hash_effect(ids: np.ndarray, feature: int) -> np.ndarray:
    """Stable pseudo-random effect in [-0.5, 0.5) for each (feature, id)."""
    with np.errstate(over="ignore"):  # uint64 wraparound is the mixer
        h = (ids.astype(np.uint64) + np.uint64(feature + 1) * _MIX1) * _MIX2
    h ^= h >> np.uint64(31)
    return (h % np.uint64(10_000)).astype(np.float32) / 10_000.0 - 0.5


def load_criteo(
    *,
    num_dense: int = 13,
    num_categorical: int = 26,
    vocab_size: int = 100_000,
    n_train: int = 32768,
    n_test: int = 4096,
    seed: int = 7,
) -> SupervisedSplits:
    rng = np.random.default_rng(seed)
    w_dense = rng.normal(0.0, 0.6, size=num_dense).astype(np.float32)
    beta = rng.normal(0.0, 1.2, size=num_categorical).astype(np.float32)

    def make(n: int, rng):
        dense = rng.lognormal(0.0, 1.0, size=(n, num_dense)).astype(np.float32)
        dense = np.log1p(dense)  # the standard Criteo dense transform
        cat = rng.integers(0, vocab_size, size=(n, num_categorical))
        logit = dense @ w_dense
        for f in range(num_categorical):
            logit += beta[f] * _hash_effect(cat[:, f], f)
        logit += rng.normal(0.0, 0.25, size=n).astype(np.float32)
        y = (logit > np.median(logit)).astype(np.int32)  # balanced classes
        x = np.concatenate([dense, cat.astype(np.float32)], axis=1)
        return x, y

    x_train, y_train = make(n_train, np.random.default_rng((seed, 1)))
    x_test, y_test = make(n_test, np.random.default_rng((seed, 2)))
    vocab = LabelVocab(labels=LABELS)
    return SupervisedSplits(
        x_train=x_train,
        y_train=y_train,
        x_test=x_test,
        y_test=y_test,
        vocab=vocab,
        feature_names=tuple(
            [f"I{i+1}" for i in range(num_dense)]
            + [f"C{i+1}" for i in range(num_categorical)]
        ),
        source="synthetic",
    )


register_dataset("criteo")(load_criteo)
