"""The documentation corpus shared by the doc-driven datasets.

``docs_clf`` (the config-5 classification proxy) and ``docs_text``
(the LM / speculation anchors) read the SAME four prose files, and
both must default to the commit-pinned snapshot in ``docs_corpus/``
so their published numbers reproduce from a clean checkout — the live
repo docs grow every round, which silently sank the r04 docsclf
headline's held-out margin from ~0.19 to ~0.07 (VERDICT r04 weak #2).
This module is the ONE place that knows the file list, the snapshot
location, the flat-vs-repo layout fallback, and the provenance
string, so the two datasets cannot drift apart.
"""

from __future__ import annotations

from pathlib import Path

# The corpus files, in repo layout. The frozen snapshot stores each at
# the top level (flat); resolve_doc() tries both.
DOC_SOURCES = (
    "README.md",
    "SURVEY.md",
    "BASELINE.md",
    "docs/DESIGN.md",
)


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def frozen_corpus() -> Path:
    """The commit-pinned snapshot directory (provenance and sha256s in
    its ``MANIFEST.json``)."""
    return Path(__file__).resolve().parent / "docs_corpus"


def resolve_root(root: str | None) -> Path:
    """``None`` → the frozen snapshot; ``"live"`` → the repo's current
    (growing) docs; anything else → a user directory holding the
    corpus files (flat or repo-layout)."""
    if root is None:
        return frozen_corpus()
    if root == "live":
        return repo_root()
    return Path(root)


def resolve_doc(base: Path, rel: str) -> Path | None:
    """Find one corpus file under ``base``: repo layout first, then
    the flat layout the snapshot (and any user-supplied flat dir)
    uses. ``None`` when absent — callers decide whether a missing
    class/file is fatal."""
    p = base / rel
    if p.exists():
        return p
    flat = base / Path(rel).name
    if flat.exists():
        return flat
    return None


def live_markdown_docs(base: Path) -> list[Path]:
    """Every ``docs/*.md`` under ``base`` beyond ``DOC_SOURCES``,
    sorted by name.

    ``docs_text``'s live mode follows the repo's documentation as it
    GROWS: the pre-unification loader globbed ``docs/*.md``, and the
    shared ``DOC_SOURCES`` list (frozen-snapshot compatible) names
    only ``docs/DESIGN.md`` — without this, new design docs would
    silently drop out of live LM corpora (ADVICE r05 #2).
    ``docs_clf`` must NOT use this: its classes are the fixed
    ``DOC_SOURCES`` files, one label per file."""
    known = {Path(rel).name for rel in DOC_SOURCES}
    return sorted(
        p for p in (base / "docs").glob("*.md") if p.name not in known
    )


def corpus_provenance(base: Path) -> str:
    """The provenance string measurements carry in
    ``extras["corpus"]``: the frozen snapshot reports its pinned
    commit, anything else reports the path it read.

    A frozen claim is VERIFIED, not trusted, twice over (ADVICE r05
    #1):

    - the manifest must cover EXACTLY the ``DOC_SOURCES`` basenames —
      a foreign or empty ``MANIFEST.json`` (no ``files``, extra
      files, missing files) previously passed its per-file loop
      vacuously and labeled arbitrary user content ``frozen@?``; such
      a directory is just a user corpus and reports ``live:<path>``;
    - every covered file must hash to its recorded sha256, otherwise
      the published accuracies would silently stop reproducing while
      still reporting ``frozen@...`` — the exact failure mode the
      snapshot exists to eliminate. Corruption raises; it must not
      degrade to a quiet "live" label.
    """
    mf = base / "MANIFEST.json"
    if not mf.exists():
        return f"live:{base}"
    import hashlib
    import json

    manifest = json.loads(mf.read_text())
    files = manifest.get("files", {})
    if set(files) != {Path(rel).name for rel in DOC_SOURCES}:
        # Not OUR snapshot manifest — whatever wrote it, this dir's
        # contents are unpinned as far as the framework is concerned.
        return f"live:{base}"
    for name, meta in files.items():
        p = base / name
        digest = (
            hashlib.sha256(p.read_bytes()).hexdigest()
            if p.exists() else "<missing>"
        )
        if digest != meta.get("sha256"):
            raise ValueError(
                f"frozen corpus snapshot is corrupted: {name} hashes "
                f"to {digest[:12]}…, MANIFEST.json records "
                f"{str(meta.get('sha256'))[:12]}… — restore "
                f"datasets/docs_corpus/ from git before trusting any "
                f"measurement"
            )
    commit = manifest.get("source_commit", "?")
    return f"frozen@{commit}"
