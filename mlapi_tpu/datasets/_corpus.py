"""The documentation corpus shared by the doc-driven datasets.

``docs_clf`` (the config-5 classification proxy) and ``docs_text``
(the LM / speculation anchors) read the SAME four prose files, and
both must default to the commit-pinned snapshot in ``docs_corpus/``
so their published numbers reproduce from a clean checkout — the live
repo docs grow every round, which silently sank the r04 docsclf
headline's held-out margin from ~0.19 to ~0.07 (VERDICT r04 weak #2).
This module is the ONE place that knows the file list, the snapshot
location, the flat-vs-repo layout fallback, and the provenance
string, so the two datasets cannot drift apart.
"""

from __future__ import annotations

from pathlib import Path

# The corpus files, in repo layout. The frozen snapshot stores each at
# the top level (flat); resolve_doc() tries both.
DOC_SOURCES = (
    "README.md",
    "SURVEY.md",
    "BASELINE.md",
    "docs/DESIGN.md",
)


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def frozen_corpus() -> Path:
    """The commit-pinned snapshot directory (provenance and sha256s in
    its ``MANIFEST.json``)."""
    return Path(__file__).resolve().parent / "docs_corpus"


def resolve_root(root: str | None) -> Path:
    """``None`` → the frozen snapshot; ``"live"`` → the repo's current
    (growing) docs; anything else → a user directory holding the
    corpus files (flat or repo-layout)."""
    if root is None:
        return frozen_corpus()
    if root == "live":
        return repo_root()
    return Path(root)


def resolve_doc(base: Path, rel: str) -> Path | None:
    """Find one corpus file under ``base``: repo layout first, then
    the flat layout the snapshot (and any user-supplied flat dir)
    uses. ``None`` when absent — callers decide whether a missing
    class/file is fatal."""
    p = base / rel
    if p.exists():
        return p
    flat = base / Path(rel).name
    if flat.exists():
        return flat
    return None


def corpus_provenance(base: Path) -> str:
    """The provenance string measurements carry in
    ``extras["corpus"]``: the frozen snapshot reports its pinned
    commit, anything else reports the path it read.

    A frozen claim is VERIFIED, not trusted: every file listed in
    MANIFEST.json must hash to its recorded sha256, otherwise the
    published accuracies would silently stop reproducing while still
    reporting ``frozen@...`` — the exact failure mode the snapshot
    exists to eliminate. Corruption raises; it must not degrade to a
    quiet "live" label."""
    mf = base / "MANIFEST.json"
    if not mf.exists():
        return f"live:{base}"
    import hashlib
    import json

    manifest = json.loads(mf.read_text())
    for name, meta in manifest.get("files", {}).items():
        p = base / name
        digest = (
            hashlib.sha256(p.read_bytes()).hexdigest()
            if p.exists() else "<missing>"
        )
        if digest != meta.get("sha256"):
            raise ValueError(
                f"frozen corpus snapshot is corrupted: {name} hashes "
                f"to {digest[:12]}…, MANIFEST.json records "
                f"{str(meta.get('sha256'))[:12]}… — restore "
                f"datasets/docs_corpus/ from git before trusting any "
                f"measurement"
            )
    commit = manifest.get("source_commit", "?")
    return f"frozen@{commit}"
