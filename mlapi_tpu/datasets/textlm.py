"""Language-modelling corpus from the repo's own documentation.

Zero-egress REAL text: this container cannot download a corpus, but it
ships ~40 KB of genuine English prose — README, design docs, survey —
written for humans. ``docs_text`` byte-tokenizes those files into
fixed-length windows for next-token training, which makes the decoder
families (``gpt_lm``, ``llama_lm``) trainable end to end through the
standard ``fit``/CLI pipeline and then servable via ``/generate``
(the checkpoint carries the tokenizer fingerprint like every text
model). Provenance is ``"real"`` — the bytes exist on disk and are
not generated from a statistical model — but the corpus is tiny;
perplexity here demonstrates the PIPELINE, not language quality.
"""

from __future__ import annotations

import numpy as np

from mlapi_tpu.datasets import SupervisedSplits, register_dataset
from mlapi_tpu.utils.vocab import LabelVocab

# Corpus files, snapshot location, layout fallback, and provenance
# all live in datasets/_corpus.py — shared with docs_clf so the two
# doc-driven datasets read the same bytes by construction. The LM
# anchors (docs-llama next-token accuracy, the speculation matrix)
# must reproduce from a clean checkout, hence the frozen default.
from mlapi_tpu.datasets._corpus import (
    DOC_SOURCES as _DOC_SOURCES,
    corpus_provenance as _corpus_provenance,
    live_markdown_docs as _live_markdown_docs,
    resolve_doc as _resolve_doc,
    resolve_root as _resolve_root,
)


@register_dataset("docs_text")
def load_docs_text(
    *,
    seq_len: int = 128,
    stride: int | None = None,
    test_fraction: float = 0.1,
    root: str | None = None,
) -> SupervisedSplits:
    """Byte-id windows over the repo docs. ``x == y`` (``[N, L]``
    int32); the LM loss shifts targets itself. Windows are cut with
    ``stride`` (default ``seq_len``, i.e. non-overlapping); the test
    split is the TAIL of the stream, so train/test windows never
    overlap even with stride < seq_len.

    ``root="live"`` reads the repo's CURRENT docs and — unlike the
    frozen default, which is pinned to the four ``DOC_SOURCES`` files
    so published numbers reproduce — also sweeps every other
    ``docs/*.md``, restoring the pre-unification glob (the corpus
    FOLLOWS the documentation as it grows; ADVICE r05 #2). Frozen
    and user-dir modes stay exactly ``DOC_SOURCES``."""
    from mlapi_tpu.text import ByteTokenizer

    tok = ByteTokenizer()
    stride = stride or seq_len
    base = _resolve_root(root)
    paths = [
        p for rel in _DOC_SOURCES
        if (p := _resolve_doc(base, rel)) is not None
    ]
    if root == "live":
        paths += _live_markdown_docs(base)
    texts = [p.read_text(errors="replace") for p in paths]
    if not texts:
        raise FileNotFoundError(f"no corpus files under {base}")
    ids = np.asarray(tok.token_ids("\n\n".join(texts)), np.int32)

    windows = [
        ids[s : s + seq_len]
        for s in range(0, len(ids) - seq_len + 1, stride)
    ]
    x = np.stack(windows)
    n_test = max(1, int(len(x) * test_fraction))
    split = len(x) - n_test
    # Guard the tail-split from stride overlap: drop train windows
    # that reach into the test region.
    if stride < seq_len:
        limit = split * stride
        keep = [i for i in range(split) if i * stride + seq_len <= limit]
        x_train = x[keep]
    else:
        x_train = x[:split]
    x_test = x[split:]
    return SupervisedSplits(
        x_train=x_train,
        y_train=x_train,  # LM: targets are the inputs, shifted in-loss
        x_test=x_test,
        y_test=x_test,
        vocab=LabelVocab(("<lm>",)),  # no class labels; engine ignores it
        source="real",
        extras={
            "tokenizer": tok.fingerprint(),
            "task": "lm",
            "corpus": _corpus_provenance(base),
        },
    )
