"""Canonical PartitionSpec layouts per parameter family.

One place that answers "how is this tensor laid out on the mesh" for
every config in the ladder, so models annotate params by *role* and
the mesh shape can change without touching model code. (Pattern after
public TPU sharding idioms — a frozen dataclass of named-axis specs.)
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from mlapi_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, MODEL_AXIS

# Leaves with fewer elements than this stay replicated over the fsdp
# axis: sharding a layernorm scale or a bias saves bytes nobody is
# short of, while adding an all-gather per use. 2048 elements keeps
# every scale/small-bias replicated and shards everything matrix-like
# (the smallest sharded leaf in the ladder is digits-mlp's [64, 256]).
FSDP_MIN_SIZE = 2048


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for params and activations."""

    data_axis: str = DATA_AXIS
    model_axis: str = MODEL_AXIS
    fsdp_axis: str = FSDP_AXIS

    # --- activations -----------------------------------------------------
    def batch(self) -> P:
        """Activations: batch dim sharded over data, features replicated."""
        return P(self.data_axis)

    # --- dense layers ----------------------------------------------------
    def replicated(self) -> P:
        """Small params (linear classifier W/b, layernorm scales)."""
        return P()

    def dense_col(self) -> P:
        """[in, out] weight, output features sharded over model (TP
        column-parallel: each chip computes a slice of the outputs)."""
        return P(None, self.model_axis)

    def dense_row(self) -> P:
        """[in, out] weight, input features sharded over model (TP
        row-parallel: follows a col-parallel layer; XLA inserts the
        psum on the output)."""
        return P(self.model_axis, None)

    # --- embeddings ------------------------------------------------------
    def embedding_rows(self) -> P:
        """[vocab, dim] table sharded over vocab rows — each chip owns
        a shard of the vocab/hash space and lookups become an XLA
        gather + all-to-all."""
        return P(self.model_axis, None)

    def embedding_tables(self) -> P:
        """[fields, vocab, dim] stacked tables (Criteo Wide&Deep):
        sharded over the per-field vocab dim, fields replicated."""
        return P(None, self.model_axis, None)

    def bias_col(self) -> P:
        """Bias of a column-parallel layer: sharded like its outputs."""
        return P(self.model_axis)

    # --- attention -------------------------------------------------------
    def attn_qkv(self) -> P:
        """[d_model, heads*head_dim]: heads sharded over model."""
        return P(None, self.model_axis)

    def attn_out(self) -> P:
        """[heads*head_dim, d_model]: contraction dim sharded over model."""
        return P(self.model_axis, None)


# --- FSDP (ZeRO-style parameter + optimizer-state sharding) -----------
def add_fsdp_to_spec(
    spec: P | None,
    shape: tuple[int, ...],
    fsdp_size: int,
    *,
    fsdp_axis: str = FSDP_AXIS,
    min_size: int = FSDP_MIN_SIZE,
) -> P:
    """One leaf's FSDP spec: shard the LARGEST still-unsharded,
    divisible dimension over the ``fsdp`` axis, on top of whatever TP
    layout ``spec`` already declares.

    Rules (docs/DESIGN.md §12):
    - leaves with fewer than ``min_size`` elements stay as-is
      (replicated over fsdp) — sharding a layernorm scale buys bytes
      nobody needs at the price of a collective per use;
    - only dimensions the TP spec leaves unsharded are eligible (an
      axis can appear once per spec), and only those divisible by the
      fsdp axis size (``jax.device_put`` needs even shards);
    - among eligible dims, the largest wins (ties → first), which
      maximises the bytes actually partitioned;
    - a leaf with NO eligible dim stays as-is — correct (GSPMD treats
      it as replicated over fsdp) and loud in the bench numbers rather
      than an error, since e.g. a [3, V, D] stacked table with V taken
      by TP and 3 < fsdp_size has nowhere to split.
    """
    full = tuple(spec) if spec is not None else ()
    full = full + (None,) * (len(shape) - len(full))
    size = int(np.prod(shape, dtype=np.int64)) if shape else 0
    if size < min_size:
        return P(*full)
    candidates = [
        d
        for d in range(len(shape))
        if full[d] is None and shape[d] % fsdp_size == 0
    ]
    if not candidates:
        return P(*full)
    best = max(candidates, key=lambda d: shape[d])
    new = list(full)
    new[best] = fsdp_axis
    return P(*new)


def fsdp_spec_tree(
    params,
    spec_tree,
    fsdp_size: int,
    *,
    fsdp_axis: str = FSDP_AXIS,
    min_size: int = FSDP_MIN_SIZE,
):
    """Derive the full FSDP spec pytree for ``params``.

    ``spec_tree`` is the model's TP layout (``param_shardings()``) or
    ``None`` for models without one (linear, MLP — everything starts
    replicated). The result feeds ``place_params`` unchanged;
    optimizer moments then mirror the PLACED params' shardings via
    ``mesh.state_shardings_like`` (jit-initialising from placed
    params does not inherit them — the moments must be placed
    explicitly).
    """
    from mlapi_tpu.ops.quant import _is_quant_leaf

    if spec_tree is None:
        spec_tree = jax.tree.map(
            lambda _: P(), params, is_leaf=_is_quant_leaf
        )

    def one(leaf, spec):
        shape = (
            leaf["q"].shape if _is_quant_leaf(leaf) else np.shape(leaf)
        )
        return add_fsdp_to_spec(
            spec, tuple(shape), fsdp_size,
            fsdp_axis=fsdp_axis, min_size=min_size,
        )

    return jax.tree.map(one, params, spec_tree, is_leaf=_is_quant_leaf)
