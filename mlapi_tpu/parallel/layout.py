"""Canonical PartitionSpec layouts per parameter family.

One place that answers "how is this tensor laid out on the mesh" for
every config in the ladder, so models annotate params by *role* and
the mesh shape can change without touching model code. (Pattern after
public TPU sharding idioms — a frozen dataclass of named-axis specs.)
"""

from __future__ import annotations

from dataclasses import dataclass

from jax.sharding import PartitionSpec as P

from mlapi_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for params and activations."""

    data_axis: str = DATA_AXIS
    model_axis: str = MODEL_AXIS

    # --- activations -----------------------------------------------------
    def batch(self) -> P:
        """Activations: batch dim sharded over data, features replicated."""
        return P(self.data_axis)

    # --- dense layers ----------------------------------------------------
    def replicated(self) -> P:
        """Small params (linear classifier W/b, layernorm scales)."""
        return P()

    def dense_col(self) -> P:
        """[in, out] weight, output features sharded over model (TP
        column-parallel: each chip computes a slice of the outputs)."""
        return P(None, self.model_axis)

    def dense_row(self) -> P:
        """[in, out] weight, input features sharded over model (TP
        row-parallel: follows a col-parallel layer; XLA inserts the
        psum on the output)."""
        return P(self.model_axis, None)

    # --- embeddings ------------------------------------------------------
    def embedding_rows(self) -> P:
        """[vocab, dim] table sharded over vocab rows — each chip owns
        a shard of the vocab/hash space and lookups become an XLA
        gather + all-to-all."""
        return P(self.model_axis, None)

    def embedding_tables(self) -> P:
        """[fields, vocab, dim] stacked tables (Criteo Wide&Deep):
        sharded over the per-field vocab dim, fields replicated."""
        return P(None, self.model_axis, None)

    def bias_col(self) -> P:
        """Bias of a column-parallel layer: sharded like its outputs."""
        return P(self.model_axis)

    # --- attention -------------------------------------------------------
    def attn_qkv(self) -> P:
        """[d_model, heads*head_dim]: heads sharded over model."""
        return P(None, self.model_axis)

    def attn_out(self) -> P:
        """[heads*head_dim, d_model]: contraction dim sharded over model."""
        return P(self.model_axis, None)
