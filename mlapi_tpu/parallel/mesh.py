"""Device mesh construction and basic sharding helpers.

Idiom (modern JAX, GSPMD): build one logical mesh with named axes,
annotate arrays with ``NamedSharding``, and let ``jax.jit`` insert the
collectives. Scales from 1 chip to multi-host pods without changing
application code; multi-host initialisation is
``jax.distributed.initialize`` before mesh creation.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = (DATA_AXIS, MODEL_AXIS),
    *,
    devices=None,
) -> Mesh:
    """Build a named device mesh.

    Defaults to putting every visible device on the ``data`` axis with
    a trivial ``model`` axis — right for pure data-parallel configs.
    Pass an explicit ``shape`` (e.g. ``(2, 4)``) for configs that
    shard params over ``model`` (Criteo embeddings, BERT TP).
    """
    devices = list(jax.devices() if devices is None else devices)
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    mesh_devices = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(mesh_devices, axis_names)


def replicate_for_mesh(pytree, mesh: Mesh):
    """Fully replicate every leaf across the mesh (params, opt state)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(pytree, sharding)


def place_params(params, mesh: Mesh, spec_tree=None):
    """Place a param pytree on the mesh per a PartitionSpec pytree.

    ``spec_tree`` mirrors ``params`` (models provide it via
    ``param_shardings()``); missing/None spec ⇒ replicated. This is
    the moment sharded training/serving actually happens: after
    placement, ``jax.jit`` sees the shardings on its inputs and GSPMD
    partitions the whole step — gathers, all-to-alls, gradient
    reductions — with no further annotation.

    Weight-only-quantized trees compose transparently: where a float
    leaf became ``{"q": int8, "scale": f32}`` (``ops/quant.py``), the
    float leaf's spec applies to ``q`` verbatim, and ``scale`` — whose
    reduced axes have length 1 — keeps only the LAST axis's placement
    (per-channel scales live on the channel axis; a length-1 axis
    cannot shard). The dequantized product then carries exactly the
    float layout, so every downstream program partitions identically.
    """
    if spec_tree is None:
        return replicate_for_mesh(params, mesh)

    from mlapi_tpu.ops.quant import _is_quant_leaf

    def put(leaf, spec):
        if _is_quant_leaf(leaf):
            q, scale = leaf["q"], leaf["scale"]
            full = tuple(spec) if spec is not None else ()
            full = full + (None,) * (q.ndim - len(full))
            sspec = P(
                *((None,) * (scale.ndim - 1) + (full[q.ndim - 1],))
            )
            return {
                "q": jax.device_put(q, NamedSharding(mesh, P(*full))),
                "scale": jax.device_put(scale, NamedSharding(mesh, sspec)),
            }
        return jax.device_put(
            leaf, NamedSharding(mesh, spec if spec is not None else P())
        )

    return jax.tree.map(put, params, spec_tree, is_leaf=_is_quant_leaf)


def params_for_model(model, params, mesh: Mesh, layout=None):
    """Place ``params`` using the model's own layout when it has one
    (``param_shardings``), else fully replicated.

    ``layout`` (a ``SpecLayout``) renames the mesh axes consistently
    across every model — pass it when the mesh doesn't use the default
    ``data``/``model`` axis names."""
    spec_fn = getattr(model, "param_shardings", None)
    return place_params(params, mesh, spec_fn(layout) if spec_fn else None)


def shard_batch_for_mesh(pytree, mesh: Mesh, axis: str = DATA_AXIS):
    """Shard each leaf's leading (batch) dimension over ``axis``.

    Leading dims must be divisible by the axis size — callers pad
    (the serving batcher pads to bucket sizes for exactly this
    reason, and to avoid recompilation).
    """
    axis_size = mesh.shape[axis]

    def put(leaf):
        arr = np.asarray(leaf)
        if arr.shape[0] % axis_size:
            raise ValueError(
                f"batch dim {arr.shape[0]} not divisible by mesh axis "
                f"{axis!r} of size {axis_size}; pad first"
            )
        spec = P(axis, *(None,) * (arr.ndim - 1))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree.map(put, pytree)
