"""Device mesh construction and basic sharding helpers.

Idiom (modern JAX, GSPMD): build one logical mesh with named axes,
annotate arrays with ``NamedSharding``, and let ``jax.jit`` insert the
collectives. Scales from 1 chip to multi-host pods without changing
application code; multi-host initialisation is
``jax.distributed.initialize`` before mesh creation.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"


def create_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] | None = None,
    *,
    devices=None,
) -> Mesh:
    """Build a named device mesh.

    Defaults to putting every visible device on the ``data`` axis with
    a trivial ``model`` axis — right for pure data-parallel configs.
    Pass an explicit ``shape`` (e.g. ``(2, 4)``) for configs that
    shard params over ``model`` (Criteo embeddings, BERT TP).

    A THREE-dimensional ``shape`` names the axes ``(data, fsdp,
    model)``: the middle axis is a second data-parallel axis over
    which parameters and optimizer state are ZeRO-sharded
    (``layout.fsdp_spec_tree``) — GSPMD turns the gradient all-reduce
    over it into reduce-scatter + all-gather, cutting per-device state
    memory by the axis size at equal math.
    """
    devices = list(jax.devices() if devices is None else devices)
    n = len(devices)
    if axis_names is None:
        axis_names = (
            (DATA_AXIS, FSDP_AXIS, MODEL_AXIS)
            if shape is not None and len(shape) == 3
            else (DATA_AXIS, MODEL_AXIS)
        )
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    mesh_devices = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(mesh_devices, axis_names)


def replicate_for_mesh(pytree, mesh: Mesh):
    """Fully replicate every leaf across the mesh (params, opt state)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(pytree, sharding)


def place_params(params, mesh: Mesh, spec_tree=None):
    """Place a param pytree on the mesh per a PartitionSpec pytree.

    ``spec_tree`` mirrors ``params`` (models provide it via
    ``param_shardings()``); missing/None spec ⇒ replicated. This is
    the moment sharded training/serving actually happens: after
    placement, ``jax.jit`` sees the shardings on its inputs and GSPMD
    partitions the whole step — gathers, all-to-alls, gradient
    reductions — with no further annotation.

    Weight-only-quantized trees compose transparently: where a float
    leaf became ``{"q": int8, "scale": f32}`` (``ops/quant.py``), the
    float leaf's spec applies to ``q`` verbatim, and ``scale`` — whose
    reduced axes have length 1 — keeps only the LAST axis's placement
    (per-channel scales live on the channel axis; a length-1 axis
    cannot shard). The dequantized product then carries exactly the
    float layout, so every downstream program partitions identically.
    """
    if spec_tree is None:
        return replicate_for_mesh(params, mesh)

    from mlapi_tpu.ops.quant import _is_quant_leaf

    def put(leaf, spec):
        if _is_quant_leaf(leaf):
            q, scale = leaf["q"], leaf["scale"]
            full = tuple(spec) if spec is not None else ()
            full = full + (None,) * (q.ndim - len(full))
            sspec = P(
                *((None,) * (scale.ndim - 1) + (full[q.ndim - 1],))
            )
            return {
                "q": jax.device_put(q, NamedSharding(mesh, P(*full))),
                "scale": jax.device_put(scale, NamedSharding(mesh, sspec)),
            }
        return jax.device_put(
            leaf, NamedSharding(mesh, spec if spec is not None else P())
        )

    return jax.tree.map(put, params, spec_tree, is_leaf=_is_quant_leaf)


def params_for_model(model, params, mesh: Mesh, layout=None):
    """Place ``params`` using the model's own layout when it has one
    (``param_shardings``), else fully replicated.

    ``layout`` (a ``SpecLayout``) renames the mesh axes consistently
    across every model — pass it when the mesh doesn't use the default
    ``data``/``model`` axis names.

    On a mesh with a non-trivial ``fsdp`` axis the model's TP specs
    (or the replicated default) are augmented leaf-by-leaf with
    ZeRO-style parameter sharding (``layout.fsdp_spec_tree``): every
    large-enough leaf gets its largest still-unsharded dimension
    partitioned over ``fsdp``. Models need no FSDP awareness — the
    derivation composes with whatever TP layout they declare."""
    spec_fn = getattr(model, "param_shardings", None)
    spec_tree = spec_fn(layout) if spec_fn else None
    fsdp_axis = layout.fsdp_axis if layout is not None else FSDP_AXIS
    if mesh.shape.get(fsdp_axis, 1) > 1:
        from mlapi_tpu.parallel.layout import fsdp_spec_tree

        spec_tree = fsdp_spec_tree(
            params, spec_tree, mesh.shape[fsdp_axis], fsdp_axis=fsdp_axis
        )
    return place_params(params, mesh, spec_tree)


def state_shardings_like(opt_abstract, params, mesh: Mesh):
    """Shardings for an optimizer-state pytree, mirrored from placed
    ``params`` — the piece that makes ZeRO sharding cover the moments,
    which for AdamW are 2x the params.

    ``jax.jit(tx.init)(placed_params)`` does NOT inherit the param
    shardings (measured: the zeros have no data dependence on the
    inputs, so GSPMD assigns them the default device) — the moments
    must be placed explicitly. Optax states mirror the param tree's
    dict structure under their namedtuple/tuple wrappers, so each
    state leaf is matched to its param by the trailing run of dict
    keys in its path (``.mu['dense_0']['kernel']`` →
    ``['dense_0']['kernel']``), longest suffix first:

    - exact shape match → the param's own sharding (adam mu/nu);
    - leading-dims match → the param's spec truncated to the leaf's
      rank (rowwise-AdaGrad accumulators: ``[F, V]`` for a
      ``[F, V, D]`` table keeps the table's vocab sharding);
    - no match (step counters, ``optax.MaskedNode``) → replicated.
    """
    from jax.tree_util import DictKey, tree_leaves_with_path

    # Param index: every dict-key path suffix → (shape, sharding);
    # ambiguous suffixes (two params sharing a trailing key) drop out
    # — their leaves fall back through shorter suffixes or replication.
    index: dict = {}
    collisions: set = set()
    for path, leaf in tree_leaves_with_path(params):
        keys = tuple(
            k.key for k in path if isinstance(k, DictKey)
        )
        for i in range(len(keys)):
            suffix = keys[i:]
            if suffix in index:
                collisions.add(suffix)
            else:
                index[suffix] = (tuple(leaf.shape), leaf.sharding)
    replicated = NamedSharding(mesh, P())

    def match(path, leaf):
        if not hasattr(leaf, "shape"):
            return replicated  # defensive: unshaped leaf
        shape = tuple(leaf.shape)
        keys = [k.key for k in path if isinstance(k, DictKey)]
        # The trailing run of dict keys (state wrappers are tuples/
        # namedtuples; dicts inside the run that are NOT param path
        # segments — e.g. a state dict {'acc': ...} — are shed as the
        # suffix shortens).
        for i in range(len(keys)):
            suffix = tuple(keys[i:])
            if suffix in collisions or suffix not in index:
                continue
            p_shape, p_sharding = index[suffix]
            if shape == p_shape:
                return p_sharding
            if shape == p_shape[: len(shape)]:
                spec = tuple(p_sharding.spec)[: len(shape)]
                return NamedSharding(mesh, P(*spec))
        return replicated

    return jax.tree_util.tree_map_with_path(match, opt_abstract)


def place_train_state(model, params, init_opt, mesh: Mesh, layout=None):
    """Place a full train state on ``mesh``: params in the model's
    (FSDP-augmented) layout, optimizer state EXPLICITLY in the
    mirrored layout, and the sharding trees a train step needs to pin
    its outputs.

    Returns ``(params, opt_state, state_shardings)`` with
    ``state_shardings = (param_shardings, opt_shardings)`` — the ONE
    implementation of the "moments must be placed explicitly"
    invariant, shared by ``fit``, the bench, and the multichip dryrun
    so they cannot measure different memory layouts than training
    uses.
    """
    params = params_for_model(model, params, mesh, layout)
    opt_sh = state_shardings_like(
        jax.eval_shape(init_opt, params), params, mesh
    )
    opt_state = jax.jit(init_opt, out_shardings=opt_sh)(params)
    return params, opt_state, (
        jax.tree.map(lambda a: a.sharding, params), opt_sh
    )


def batch_shard_axes(mesh: Mesh) -> tuple[str, ...]:
    """The mesh axes a batch dimension shards over: ``data``, plus
    ``fsdp`` when present — the FSDP axis is a second data-parallel
    axis (each of its shards sees different examples; what it changes
    is where the *state* lives, not the math)."""
    axes = tuple(
        a for a in (DATA_AXIS, FSDP_AXIS) if a in mesh.axis_names
    )
    return axes or (DATA_AXIS,)


def batch_shard_size(mesh: Mesh) -> int:
    """Product of the batch-sharding axis sizes (divisibility unit
    for batch/bucket dimensions on this mesh)."""
    n = 1
    for a in batch_shard_axes(mesh):
        n *= mesh.shape[a]
    return n


def shard_batch_for_mesh(pytree, mesh: Mesh, axis: str | tuple = DATA_AXIS):
    """Shard each leaf's leading (batch) dimension over ``axis``.

    Leading dims must be divisible by the axis size — callers pad
    (the serving batcher pads to bucket sizes for exactly this
    reason, and to avoid recompilation).

    When the mesh carries an ``fsdp`` axis and the default ``data``
    axis is requested, the batch shards over BOTH ``(data, fsdp)`` —
    on an FSDP mesh every device holds distinct examples, and the
    divisibility unit grows to ``data * fsdp``
    (:func:`batch_shard_size`).
    """
    if axis == DATA_AXIS:
        axes = batch_shard_axes(mesh)
    elif isinstance(axis, (tuple, list)):
        axes = tuple(axis)
    else:
        axes = (axis,)
    axis_size = 1
    for a in axes:
        axis_size *= mesh.shape[a]
    dim0 = axes if len(axes) > 1 else axes[0]

    def put(leaf):
        arr = np.asarray(leaf)
        if arr.shape[0] % axis_size:
            raise ValueError(
                f"batch dim {arr.shape[0]} not divisible by mesh axes "
                f"{axes!r} of total size {axis_size}; pad first"
            )
        spec = P(dim0, *(None,) * (arr.ndim - 1))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree.map(put, pytree)
