"""Multi-host bootstrap.

The reference has no distributed anything (SURVEY §2: "Distributed
communication backend: absent"). Here the multi-host story is JAX's
own runtime: every host calls ``jax.distributed.initialize`` before
touching devices; afterwards ``jax.devices()`` spans the whole pod
and the same mesh/sharding code runs unchanged — collectives ride ICI
within a slice and DCN across slices, compiled by XLA, no hand-rolled
transport.

Bootstrap is env-driven so launchers (GKE, mpi-run style wrappers,
bare SSH loops) only need to export three variables::

    MLAPI_TPU_COORDINATOR=host0:8476
    MLAPI_TPU_NUM_PROCESSES=4
    MLAPI_TPU_PROCESS_ID=2   # this host's rank

On Cloud TPU VMs all three are auto-detected by JAX, so
``initialize_from_env`` with no env set still calls
``jax.distributed.initialize()`` bare when
``MLAPI_TPU_MULTIHOST=auto`` is set. With nothing set it is a no-op
(single host).
"""

from __future__ import annotations

import os

from mlapi_tpu.utils.logging import get_logger

_log = get_logger("parallel.distributed")


def initialize_from_env() -> bool:
    """Initialise JAX's distributed runtime from the environment.

    Returns True if the distributed runtime is (now) initialised.
    Safe to call on every entry point: a plain single-host run (no env
    vars) is a no-op, and a second call in an already-initialised
    process (e.g. a sweep script looping over configs) is too.
    """
    import jax

    # jax.distributed.is_initialized() only exists on newer jax; on
    # older releases probe the client handle the same check reads.
    if hasattr(jax.distributed, "is_initialized"):
        initialized = jax.distributed.is_initialized()
    else:
        from jax._src import distributed as _dist

        initialized = _dist.global_state.client is not None
    if initialized:
        return True

    coordinator = os.environ.get("MLAPI_TPU_COORDINATOR")
    if coordinator:
        missing = [
            v
            for v in ("MLAPI_TPU_NUM_PROCESSES", "MLAPI_TPU_PROCESS_ID")
            if v not in os.environ
        ]
        if missing:
            raise ValueError(
                "MLAPI_TPU_COORDINATOR is set but "
                f"{', '.join(missing)} is not — all three multi-host "
                "variables must be exported together"
            )
        try:
            num = int(os.environ["MLAPI_TPU_NUM_PROCESSES"])
            pid = int(os.environ["MLAPI_TPU_PROCESS_ID"])
        except ValueError:
            raise ValueError(
                "MLAPI_TPU_NUM_PROCESSES and MLAPI_TPU_PROCESS_ID must be "
                "integers"
            ) from None
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num,
            process_id=pid,
        )
        _log.info(
            "multi-host: process %d/%d, coordinator %s, %d global devices",
            pid, num, coordinator, jax.device_count(),
        )
        return True
    if os.environ.get("MLAPI_TPU_MULTIHOST") == "auto":
        # Cloud TPU VM: everything auto-detected from the metadata env.
        jax.distributed.initialize()
        _log.info(
            "multi-host (auto): %d global devices across %d processes",
            jax.device_count(), jax.process_count(),
        )
        return True
    return False


REPLICAS_ENV_VAR = "MLAPI_TPU_REPLICAS"


def replica_endpoints_from_env(
    spec: str | None = None,
) -> list[tuple[str, int]]:
    """Serving-replica discovery — the HTTP sibling of the rendezvous
    trio above. The ``--router`` topology supervisor exports::

        MLAPI_TPU_REPLICAS=host0:8001,host0:8002
        MLAPI_TPU_REPLICA_ID=0   # per spawned replica, its slot

    to every process it spawns, exactly the launcher convention the
    multi-host trio uses (env-driven so GKE manifests, SSH loops, and
    tests all speak it); a router pointed at externally-launched
    replicas (other hosts, other supervisors) reads the same variable
    instead of spawning. Returns ``[]`` when unset — single-process
    serving has no replica set. Malformed entries are loud: a typo'd
    fleet definition must not silently route to half the fleet.
    """
    if spec is None:
        spec = os.environ.get(REPLICAS_ENV_VAR, "")
    endpoints: list[tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"bad replica endpoint {part!r} (want host:port) in "
                f"${REPLICAS_ENV_VAR} / --replica-urls"
            )
        endpoints.append((host, int(port)))
    return endpoints
