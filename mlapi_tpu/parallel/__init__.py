"""Parallelism: device mesh, canonical shardings, collectives.

The reference has **no** parallelism of any kind (single-process
FastAPI app, SURVEY §2). This package supplies the TPU-native layer
the north star demands: a named device ``Mesh`` with ``data`` and
``model`` axes, ``NamedSharding`` annotations on params/batches, and
XLA-inserted collectives over ICI (gradient ``psum`` falls out of the
sharded ``jit`` — no hand-written NCCL/MPI-style transport).
"""

from mlapi_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    batch_shard_axes,
    batch_shard_size,
    create_mesh,
    params_for_model,
    place_params,
    place_train_state,
    replicate_for_mesh,
    shard_batch_for_mesh,
    state_shardings_like,
)
from mlapi_tpu.parallel.layout import (  # noqa: F401
    FSDP_MIN_SIZE,
    SpecLayout,
    fsdp_spec_tree,
)
from mlapi_tpu.parallel.distributed import (  # noqa: F401
    initialize_from_env,
    replica_endpoints_from_env,
)
