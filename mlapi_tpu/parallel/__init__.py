"""Parallelism: device mesh, canonical shardings, collectives.

The reference has **no** parallelism of any kind (single-process
FastAPI app, SURVEY §2). This package supplies the TPU-native layer
the north star demands: a named device ``Mesh`` with ``data`` and
``model`` axes, ``NamedSharding`` annotations on params/batches, and
XLA-inserted collectives over ICI (gradient ``psum`` falls out of the
sharded ``jit`` — no hand-written NCCL/MPI-style transport).
"""

from mlapi_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    create_mesh,
    params_for_model,
    place_params,
    replicate_for_mesh,
    shard_batch_for_mesh,
)
from mlapi_tpu.parallel.layout import SpecLayout  # noqa: F401
from mlapi_tpu.parallel.distributed import initialize_from_env  # noqa: F401
