"""Request-side data types for generative serving.

These are the handoff objects between the async front (ASGI handlers,
the collector) and the decode thread: one :class:`GenRequest` per
in-flight generation, :class:`_SyncSink` adapting the synchronous
``generate_text`` path onto the same batch machinery, and
:class:`_PrefixEntry` describing one cached shared-prompt prefix.
Split out of ``engine.py`` (r04) so the batch lifecycle, the prefix
cache, and the speculation phase can live in modules of their own —
they all speak in these types.
"""

from __future__ import annotations

import asyncio
import collections
import threading
import time

from mlapi_tpu.serving import faults


class DeadlineExceeded(Exception):
    """A request's wall-clock deadline passed before its generation
    finished: delivered IN-BAND as the stream's terminal error frame
    (NDJSON ``{"error": ..., "code": "deadline_exceeded"}``) and
    mapped to 504 on unary paths. ``stage`` records which dispatch
    boundary noticed — ``queued`` (never dispatched), ``prefill``
    (mid prompt ingestion), or ``decode`` — the same split the
    ``deadline_expired_{stage}`` counters export."""

    code = "deadline_exceeded"

    def __init__(self, stage: str, budget_ms: float | None = None):
        extra = (
            f" (budget {budget_ms:.0f} ms)" if budget_ms is not None else ""
        )
        super().__init__(f"deadline exceeded while {stage}{extra}")
        self.stage = stage


class DrainCancelled(Exception):
    """The server's drain budget ran out with this stream still in
    flight: a proper terminal frame (503-mapped — the client should
    retry against a live replica), not a dropped connection."""

    code = "draining"

    def __init__(self):
        super().__init__("server draining: generation cancelled")


class LatencyStats:
    """Bounded reservoir of per-request latency samples, recorded at
    token DELIVERY time (the ``push`` seam every serving path funnels
    through — chunked, fused, speculative, interleaved): TTFT is
    submit→first-chunk, inter-token is the per-token share of each
    chunk gap. One instance per engine; ``/metrics`` and the bench
    read :meth:`summary`. Thread-safe (pushes come from the decode
    thread, scrapes from the event loop); bounded so a long-lived
    server's memory stays flat."""

    def __init__(self, cap: int = 2048):
        self._ttft_ms: collections.deque = collections.deque(maxlen=cap)
        self._itl_ms: collections.deque = collections.deque(maxlen=cap)
        self._lock = threading.Lock()

    def record_first(self, ms: float) -> None:
        with self._lock:
            self._ttft_ms.append(ms)

    def record_gap(self, ms_per_token: float) -> None:
        with self._lock:
            self._itl_ms.append(ms_per_token)

    @staticmethod
    def _q(xs: list, q: float) -> float | None:
        """Quantile pick; ``xs`` must already be sorted."""
        if not xs:
            return None
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def summary(self) -> dict:
        """p50/p95 of both series (ms; ``None`` until samples exist).
        Each reservoir is sorted ONCE per call — this sits on the
        admission-estimate path of every deadlined submit, where a
        per-quantile re-sort of 2048 samples would be the dominant
        cost."""
        with self._lock:
            t, i = list(self._ttft_ms), list(self._itl_ms)
        t.sort()
        i.sort()
        r = lambda v: None if v is None else round(v, 2)  # noqa: E731
        return {
            "ttft_p50_ms": r(self._q(t, 0.50)),
            "ttft_p95_ms": r(self._q(t, 0.95)),
            "intertoken_p50_ms": r(self._q(i, 0.50)),
            "intertoken_p95_ms": r(self._q(i, 0.95)),
        }


def _record_push(sink, item) -> None:
    """Shared delivery-time bookkeeping for GenRequest/_SyncSink: fold
    this chunk into the engine's latency reservoirs."""
    if sink.stats is None or not isinstance(item, dict):
        return
    now = time.perf_counter()
    n = len(item.get("token_ids", ())) or 1
    if sink.t_last is None:
        sink.stats.record_first((now - sink.t0) * 1e3)
    else:
        sink.stats.record_gap((now - sink.t_last) * 1e3 / n)
    sink.t_last = now


class GenRequest:
    """One in-flight generation request: its encoded prompt plus an
    asyncio queue the decode loop feeds with token chunks (and a
    ``None`` sentinel when done)."""

    __slots__ = (
        "row", "used", "n_new", "temperature", "seed", "queue", "loop",
        "cancelled", "top_k", "top_p", "stream",
        "prefix_fp", "prefix_kv", "prefix_len", "prefix_lo",
        "prompt_tokens", "stats", "t0", "t_last", "deadline",
        "push_to", "pushed", "staged", "adapter", "tenant",
        "on_done", "_done_fired",
    )

    def __init__(self, row, used, n_new, temperature, seed, loop,
                 top_k=0, top_p=1.0, prefix=None, stream=False,
                 stats: LatencyStats | None = None,
                 deadline_ms: float | None = None,
                 push_to=None, pushed=None, adapter=None,
                 tenant: str = ""):
        self.row = row            # [bucketed] int32 ids, left-padded
        self.used = used          # real prompt tokens in the row
        self.n_new = n_new
        self.temperature = temperature
        self.seed = seed
        self.loop = loop
        self.top_k = top_k        # 0 disables
        self.top_p = top_p        # 1.0 disables
        # Incremental consumer (NDJSON stream or a stop-sequence
        # watcher): the decode loop keeps at most one chunk in
        # flight so tokens land promptly; non-incremental requests
        # let the loop chain every chunk and sync once (the
        # dispatch-bound single-stream win through a high-RTT
        # attach).
        self.stream = stream
        # Shared-prefix KV entry (the engine's prefix cache); only
        # same-prefix requests batch together.
        if prefix is not None:
            self.prefix_fp = prefix.fp
            self.prefix_kv = prefix.kv
            self.prefix_len = prefix.bucket
            self.prefix_lo = prefix.lo
            # Tokens that actually conditioned the output = prefix
            # real tokens + suffix real tokens (`used` stays the
            # suffix-row count — it drives the pad mask).
            self.prompt_tokens = prefix.used + used
        else:
            self.prefix_fp = None
            self.prefix_kv = None
            self.prefix_len = 0
            self.prefix_lo = 0
            self.prompt_tokens = used
        # Prefill/decode disaggregation (r18, serving/kv_peer.py).
        # push_to = (host, port, xfer): this is a PREFILL-ONLY run on
        # a prefill-role replica — the prompt's KV streams to the
        # named decode replica chunk by chunk and the request ends at
        # its first token. pushed = a PushedKV: this request's prompt
        # KV arrived over the wire — formation installs it instead of
        # prefilling. Both None (every non-disaggregated request):
        # bit-identical to the fields never existing.
        self.push_to = push_to
        self.pushed = pushed
        # Per-tenant LoRA adapter id (serving/adapter_store.py), or
        # None for the base model. _encode resolved it into the HOST
        # store before this request was queued; batch formation turns
        # it into a resident device slot. Requests with different
        # adapters still co-batch (the gathered BGMV path).
        self.adapter = adapter
        # Quota/fairness identity (serving/registry.py TenantLedger,
        # r22): the tenant whose page/slot quota this request reserves
        # against and whose weight scales its deadline slack. Empty =
        # the anonymous tenant (unquotaed, weight 1.0).
        self.tenant = tenant
        # Fired EXACTLY ONCE at this request's terminal frame — normal
        # end, error, deadline, drain, or scheduler stop — so the
        # tenant ledger's live-depth accounting balances on every
        # delivery path. Set by engine.submit; None elsewhere.
        self.on_done = None
        self._done_fired = False
        self.queue: asyncio.Queue = asyncio.Queue()
        self.cancelled = False    # set when the consumer disconnects
        # Staged-for-admission ONCE marker (collector dispatch): a
        # candidate a lane deferred re-dispatches as its own group
        # instead of being re-staged forever.
        self.staged = False
        # Engine latency reservoirs (None for warmup requests): TTFT
        # and inter-token samples recorded as chunks are pushed.
        self.stats = stats
        self.t0 = time.perf_counter()
        self.t_last: float | None = None
        # Absolute expiry on the ``t0`` clock (``perf_counter``):
        # every dispatch boundary the scheduler owns checks it via
        # ``engine._expire_if_due`` and cancels the row exactly like a
        # client disconnect, after pushing the terminal
        # :class:`DeadlineExceeded` frame. ``None`` = no deadline —
        # the pre-deadline behavior, bit for bit.
        self.deadline = (
            self.t0 + deadline_ms / 1e3 if deadline_ms else None
        )

    def push(self, item) -> None:
        """Thread-safe enqueue from the decode thread."""
        faults.fire("stream_push")
        _record_push(self, item)
        if item is None or isinstance(item, BaseException):
            self.finish()  # terminal frame: balance the ledger
        self.loop.call_soon_threadsafe(self.queue.put_nowait, item)

    def finish(self) -> None:
        """Terminal-frame hook, idempotent: fires ``on_done`` exactly
        once no matter which delivery path ends the request (normal
        sentinel, error frame, deadline, drain sweep, scheduler stop,
        or a disconnect's :meth:`cancel`). Mutated from the decode
        thread and the event loop, but only ever False→True — a rare
        double-fire race would double-exit the ledger, which ``exit``
        clamps at zero."""
        if self._done_fired:
            return
        self._done_fired = True
        cb = self.on_done
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — bookkeeping must not kill delivery
                pass

    def cancel(self) -> None:
        """Consumer is gone: tell the decode loop to stop spending
        device time on this row (a plain bool — read cross-thread,
        worst case one extra chunk decodes). The tenant ledger exits
        here too — a disconnected row may retire without a terminal
        push."""
        self.cancelled = True
        self.finish()


class _PrefixEntry:
    """One cached shared-prompt prefix: its device-resident KV (a
    ``[1, bucket]``-shaped cache pytree), the bucket it was padded to,
    its own left-pad ``lo``, and the real token count."""

    __slots__ = ("fp", "kv", "bucket", "lo", "used")

    def __init__(self, fp, kv, bucket, lo, used):
        self.fp = fp
        self.kv = kv
        self.bucket = bucket
        self.lo = lo
        self.used = used


class _SyncSink:
    """Adapter so the synchronous ``generate_text`` path reuses
    ``_run_batch`` verbatim: collects token chunks into a list instead
    of an asyncio queue."""

    def __init__(self, req: "GenRequest", out_ids: list):
        self.row, self.used, self.n_new = req.row, req.used, req.n_new
        self.temperature, self.seed = req.temperature, req.seed
        self.top_k, self.top_p = req.top_k, req.top_p
        self.prefix_fp, self.prefix_kv = req.prefix_fp, req.prefix_kv
        self.prefix_len, self.prefix_lo = req.prefix_len, req.prefix_lo
        self.stream = req.stream
        self.stats, self.t0, self.t_last = req.stats, req.t0, None
        self.deadline = req.deadline
        self.push_to, self.pushed = req.push_to, req.pushed
        self.adapter = req.adapter
        self.tenant = req.tenant
        self._out = out_ids
        self.error: Exception | None = None
        self.cancelled = False
        self.staged = False

    def finish(self) -> None:
        """Parity no-op: the sync path never enters the tenant
        ledger (``engine.submit`` owns enter/exit), but shared
        terminal seams call ``finish`` on every sink type."""

    def push(self, item) -> None:
        faults.fire("stream_push")
        _record_push(self, item)
        if isinstance(item, Exception):
            self.error = item
        elif item is not None:
            self._out.extend(item["token_ids"])

    def cancel(self) -> None:
        """Parity with GenRequest: deadline expiry / drain cancel the
        sink the same way (the decode loop stops scheduling it)."""
        self.cancelled = True
