"""Request-side data types for generative serving.

These are the handoff objects between the async front (ASGI handlers,
the collector) and the decode thread: one :class:`GenRequest` per
in-flight generation, :class:`_SyncSink` adapting the synchronous
``generate_text`` path onto the same batch machinery, and
:class:`_PrefixEntry` describing one cached shared-prompt prefix.
Split out of ``engine.py`` (r04) so the batch lifecycle, the prefix
cache, and the speculation phase can live in modules of their own —
they all speak in these types.
"""

from __future__ import annotations

import asyncio


class GenRequest:
    """One in-flight generation request: its encoded prompt plus an
    asyncio queue the decode loop feeds with token chunks (and a
    ``None`` sentinel when done)."""

    __slots__ = (
        "row", "used", "n_new", "temperature", "seed", "queue", "loop",
        "cancelled", "top_k", "top_p", "stream",
        "prefix_fp", "prefix_kv", "prefix_len", "prefix_lo",
        "prompt_tokens",
    )

    def __init__(self, row, used, n_new, temperature, seed, loop,
                 top_k=0, top_p=1.0, prefix=None, stream=False):
        self.row = row            # [bucketed] int32 ids, left-padded
        self.used = used          # real prompt tokens in the row
        self.n_new = n_new
        self.temperature = temperature
        self.seed = seed
        self.loop = loop
        self.top_k = top_k        # 0 disables
        self.top_p = top_p        # 1.0 disables
        # Incremental consumer (NDJSON stream or a stop-sequence
        # watcher): the decode loop keeps at most one chunk in
        # flight so tokens land promptly; non-incremental requests
        # let the loop chain every chunk and sync once (the
        # dispatch-bound single-stream win through a high-RTT
        # attach).
        self.stream = stream
        # Shared-prefix KV entry (the engine's prefix cache); only
        # same-prefix requests batch together.
        if prefix is not None:
            self.prefix_fp = prefix.fp
            self.prefix_kv = prefix.kv
            self.prefix_len = prefix.bucket
            self.prefix_lo = prefix.lo
            # Tokens that actually conditioned the output = prefix
            # real tokens + suffix real tokens (`used` stays the
            # suffix-row count — it drives the pad mask).
            self.prompt_tokens = prefix.used + used
        else:
            self.prefix_fp = None
            self.prefix_kv = None
            self.prefix_len = 0
            self.prefix_lo = 0
            self.prompt_tokens = used
        self.queue: asyncio.Queue = asyncio.Queue()
        self.cancelled = False    # set when the consumer disconnects

    def push(self, item) -> None:
        """Thread-safe enqueue from the decode thread."""
        self.loop.call_soon_threadsafe(self.queue.put_nowait, item)

    def cancel(self) -> None:
        """Consumer is gone: tell the decode loop to stop spending
        device time on this row (a plain bool — read cross-thread,
        worst case one extra chunk decodes)."""
        self.cancelled = True


class _PrefixEntry:
    """One cached shared-prompt prefix: its device-resident KV (a
    ``[1, bucket]``-shaped cache pytree), the bucket it was padded to,
    its own left-pad ``lo``, and the real token count."""

    __slots__ = ("fp", "kv", "bucket", "lo", "used")

    def __init__(self, fp, kv, bucket, lo, used):
        self.fp = fp
        self.kv = kv
        self.bucket = bucket
        self.lo = lo
        self.used = used


class _SyncSink:
    """Adapter so the synchronous ``generate_text`` path reuses
    ``_run_batch`` verbatim: collects token chunks into a list instead
    of an asyncio queue."""

    def __init__(self, req: "GenRequest", out_ids: list):
        self.row, self.used, self.n_new = req.row, req.used, req.n_new
        self.temperature, self.seed = req.temperature, req.seed
        self.top_k, self.top_p = req.top_k, req.top_p
        self.prefix_fp, self.prefix_kv = req.prefix_fp, req.prefix_kv
        self.prefix_len, self.prefix_lo = req.prefix_len, req.prefix_lo
        self.stream = req.stream
        self._out = out_ids
        self.error: Exception | None = None
        self.cancelled = False

    def push(self, item) -> None:
        if isinstance(item, Exception):
            self.error = item
        elif item is not None:
            self._out.extend(item["token_ids"])
