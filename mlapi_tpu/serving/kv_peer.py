"""Peer-to-peer prefix-KV fetch between engine replicas.

The r14 router gives the fleet ONE cold prefill per distinct prefix —
but only while the affinity-preferred replica stays up and under its
depth limit. Any failover, drain, or depth overflow lands the prefix
on a replica whose caches have never seen it, and that replica pays
the full O(P²) prefill again even though a peer still holds the exact
stored-format bytes (device-resident prefix entry, or an r13 host-tier
blob). This module promotes the tier blob into the fleet's
TRANSFERABLE KV unit: a wire hop between replica tiers, so affinity
becomes a soft hint and a replica death no longer costs its whole
prefix working set (ROADMAP item 2, step one; the hierarchical-memory
move Snap ML makes across DRAM/NVMe levels, taken across hosts).

Topology — who knows what:

- **The router knows warmth.** Its HRW affinity map already names the
  replica most likely to hold a prefix; any forward to a
  NON-preferred replica (p2c fallback, failover, depth overflow,
  post-drain remap) carries ``x-mlapi-warm-peer: host:port`` naming
  the HRW head (``Router.forward``). Replica-gated like
  ``x-mlapi-router-depth`` — direct callers cannot aim a replica's
  fetches at an arbitrary host.
- **The serving replica knows bytes.** ``GET
  /kv/prefix?fp=<digest>`` (``serving/app.py``, installed only with
  ``--kv-peer-fetch``) serves the prefix's blob in its STORED format
  — int8-halved payloads cross the wire at half the bytes for free —
  from the host tier when spilled, else gathered from the
  device-resident prefix entry's contiguous KV (safe from any
  thread: entry KV is never donated). A GET works while DRAINING —
  exactly the window where a peer needs the drained replica's slice.
- **The fetching replica stays off the dispatch thread.** The fetch
  runs inside ``PrefixCache._restore`` on the encode executor thread
  (where the cold prefill it replaces would have run); the fetched
  blob rebuilds the ``_PrefixEntry`` and is STAGED into the local
  tier (``KVTier.stage``), so the dispatch-thread paged formation
  restores pool pages through the existing alloc-first
  ``PagePool.restore_entry`` path — a mid-fetch or mid-restore
  failure conserves pages exactly and degrades to the r13 cold path.
  No wire I/O ever touches the dispatch thread.

Wire format (one blob): a single JSON header line —
``{"v": 1, "page", "num_pages", "nbytes", "bucket", "lo", "used",
"leaves": [[layer, name, shape, dtype], ...]}`` — followed by each
leaf's raw C-order bytes in header order. The payload bytes are
EXACTLY the ``num_pages × kv_page_bytes`` closed form (the same
``ops/quant.kv_tree_bytes`` arithmetic the tier's counters use);
``deserialize_blob`` validates every leaf's size and the total
against the header, so a truncated or corrupt body is a counted MISS,
never a wrong cache. Geometry against the LOCAL replica (bucket/page
drift across builds or configs) is validated by the same ``_plan`` /
``restore_entry`` checks every tier blob passes — a peer can never
install bytes the local pool would not have produced itself.

Failure grammar (``serving/faults.py``): ``peer_fetch`` fires before
the wire request, ``peer_serve`` before the serve-side blob resolve —
a raise at either point falls back to the cold prefill with pages
conserved and the stream completing.

Since r18 this module also carries the DISAGGREGATION wire
(:class:`KVPush`): the same blob framing, extended with
``{xfer, chunk, num_chunks, span}``, pushed PROACTIVELY at chunk
granularity from prefill-role replicas to decode-role replicas
(``POST /kv/push``) — where the peer fetch moves warmth reactively
on a miss, the push moves a request's entire prompt KV while the
prefill is still running, so the decode replica activates the
stream with zero local prefill FLOPs. ``kv_push_send`` /
``kv_push_recv`` extend the failure grammar with the same contract:
a raise fails the transfer and the decode replica cold-prefills,
pages conserved on both ends.
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading

import numpy as np

from mlapi_tpu.serving import faults
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.kv_peer")

WIRE_VERSION = 1
# Header line length cap: a dozen layers of leaf manifests fit in a
# few KB; anything larger is a corrupt/hostile response, refused
# before allocation.
_MAX_HEADER_BYTES = 1 << 20


def fp_digest(fp: str) -> str:
    """URL-safe fingerprint of a prefix string: blake2b-128 hex of
    its UTF-8 bytes (prefix text is arbitrary — it cannot ride a URL
    path raw, and the serving replica must not need the full text to
    index its blobs)."""
    return hashlib.blake2b(
        fp.encode("utf-8", "surrogatepass"), digest_size=16
    ).hexdigest()


def serialize_blob(blob) -> bytes:
    """A :class:`~mlapi_tpu.serving.kv_tier.KVTierBlob` → wire bytes:
    JSON header line + concatenated raw leaf payloads in header
    order. Payload bytes total exactly ``blob.nbytes`` (the
    ``num_pages × kv_page_bytes`` closed form)."""
    leaves = []
    chunks = []
    for ln in sorted(blob.payload):
        for name in sorted(blob.payload[ln]):
            a = np.ascontiguousarray(blob.payload[ln][name])
            leaves.append([ln, name, list(a.shape), a.dtype.str])
            chunks.append(a.tobytes())
    header = json.dumps(
        {
            "v": WIRE_VERSION,
            "page": blob.page,
            "num_pages": blob.num_pages,
            "nbytes": blob.nbytes,
            "bucket": blob.bucket,
            "lo": blob.lo,
            "used": blob.used,
            "leaves": leaves,
        }
    ).encode()
    return header + b"\n" + b"".join(chunks)


def deserialize_blob(fp, data: bytes):
    """Wire bytes → a validated ``KVTierBlob`` for ``fp``. Raises
    ``ValueError`` on ANY inconsistency — unparseable header, leaf
    shapes that are not ``[num_pages, page, ...]``, a payload whose
    size does not match the manifest, trailing bytes, or a byte total
    that disagrees with the header's ``nbytes`` — so a corrupt wire
    response is dropped as a counted miss, never installed."""
    from mlapi_tpu.serving.kv_tier import KVTierBlob

    nl = data.find(b"\n", 0, _MAX_HEADER_BYTES)
    if nl < 0:
        raise ValueError("no header line in peer blob")
    try:
        head = json.loads(data[:nl])
    except Exception as e:
        raise ValueError(f"unparseable peer blob header: {e}") from None
    if not isinstance(head, dict) or head.get("v") != WIRE_VERSION:
        raise ValueError(f"unknown peer blob version {head!r:.80}")
    try:
        page = int(head["page"])
        num_pages = int(head["num_pages"])
        nbytes = int(head["nbytes"])
        # A meta-less blob cannot rebuild an entry and the serve side
        # never emits one, so a None here is corruption — and int()
        # turns it (or any non-numeric junk) into the TypeError this
        # clause converts to the one documented exception type.
        bucket = int(head["bucket"])
        lo = int(head["lo"])
        used = int(head["used"])
        leaves = head["leaves"]
        if not isinstance(leaves, list) or not leaves:
            raise ValueError("leaf manifest is not a non-empty list")
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"incomplete peer blob header: {e}") from None
    payload: dict = {}
    off = nl + 1
    total = 0
    for leaf in leaves:
        try:
            ln, name, shape, dtype = leaf
            shape = tuple(int(s) for s in shape)
            dt = np.dtype(dtype)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad leaf manifest {leaf!r:.80}: {e}") from None
        if (
            len(shape) < 2
            or shape[0] != num_pages
            or shape[1] != page
            or any(s <= 0 for s in shape)
        ):
            # Non-positive dims included: a negative dim would make
            # ``size`` negative — defeating the truncation check
            # below and letting ``off`` walk backward into already-
            # consumed bytes (np.frombuffer treats a negative count
            # as "the rest of the buffer", silently).
            raise ValueError(
                f"leaf {ln}/{name} shape {shape} is not "
                f"[{num_pages}, {page}, ...] with positive dims"
            )
        size = int(np.prod(shape)) * dt.itemsize
        if off + size > len(data):
            raise ValueError("truncated peer blob payload")
        payload.setdefault(ln, {})[name] = np.frombuffer(
            data, dtype=dt, count=int(np.prod(shape)), offset=off
        ).reshape(shape)
        off += size
        total += size
    if off != len(data):
        raise ValueError("trailing bytes after peer blob payload")
    if total != nbytes:
        raise ValueError(
            f"peer blob payload is {total} bytes, header says {nbytes}"
        )
    return KVTierBlob(fp, payload, page, nbytes, bucket, lo, used)


def serialize_push_chunk(xfer: str, chunk: int, num_chunks: int,
                         span: tuple[int, int], kv: dict) -> bytes:
    """One prefill chunk's KV slice → wire bytes (r18 disaggregation:
    the r17 blob format extended with ``{xfer, chunk, num_chunks,
    span}``). ``kv`` is ``{layer: {leaf: [1, span, ...]}}`` in the
    STORED format — int8 KV crosses the wire at half the bf/f32
    bytes, exactly like the peer-fetch blob. Payload bytes are the
    closed form ``(hi - lo) × kv_page_bytes(model, 1)``."""
    lo, hi = int(span[0]), int(span[1])
    leaves = []
    chunks = []
    total = 0
    for ln in sorted(kv):
        for name in sorted(kv[ln]):
            a = np.ascontiguousarray(kv[ln][name])
            leaves.append([ln, name, list(a.shape), a.dtype.str])
            chunks.append(a.tobytes())
            total += a.nbytes
    header = json.dumps(
        {
            "v": WIRE_VERSION,
            "kind": "chunk",
            "xfer": xfer,
            "chunk": int(chunk),
            "num_chunks": int(num_chunks),
            "span": [lo, hi],
            "nbytes": total,
            "leaves": leaves,
        }
    ).encode()
    return header + b"\n" + b"".join(chunks)


def serialize_push_fin(xfer: str, num_chunks: int, first_token: int,
                       bucket: int, used: int) -> bytes:
    """The transfer's FINALIZE message: no KV payload — it carries
    the prefill replica's sampled first token plus the geometry the
    decode replica validates against its own ``_encode`` (bucket/used
    drift ⇒ the transfer can never apply ⇒ cold prefill)."""
    return json.dumps(
        {
            "v": WIRE_VERSION,
            "kind": "fin",
            "xfer": xfer,
            "num_chunks": int(num_chunks),
            "first_token": int(first_token),
            "bucket": int(bucket),
            "used": int(used),
        }
    ).encode() + b"\n"


def deserialize_push(data: bytes) -> dict:
    """Wire bytes → a validated push message dict (``kind`` is
    ``"chunk"`` — with ``payload`` — or ``"fin"``). Raises
    ``ValueError`` on ANY inconsistency, same contract as
    :func:`deserialize_blob`: a corrupt push is a counted receive
    failure, never a staged wrong chunk."""
    nl = data.find(b"\n", 0, _MAX_HEADER_BYTES)
    if nl < 0:
        raise ValueError("no header line in pushed chunk")
    try:
        head = json.loads(data[:nl])
    except Exception as e:
        raise ValueError(f"unparseable push header: {e}") from None
    if not isinstance(head, dict) or head.get("v") != WIRE_VERSION:
        raise ValueError(f"unknown push version {head!r:.80}")
    kind = head.get("kind")
    try:
        xfer = head["xfer"]
        if not isinstance(xfer, str) or not xfer:
            raise ValueError("xfer id is not a non-empty string")
        num_chunks = int(head["num_chunks"])
        if num_chunks < 1:
            raise ValueError("num_chunks must be >= 1")
        if kind == "fin":
            if data[nl + 1:]:
                raise ValueError("trailing bytes after fin header")
            return {
                "kind": "fin",
                "xfer": xfer,
                "num_chunks": num_chunks,
                "first_token": int(head["first_token"]),
                "bucket": int(head["bucket"]),
                "used": int(head["used"]),
            }
        if kind != "chunk":
            raise ValueError(f"unknown push kind {kind!r}")
        chunk = int(head["chunk"])
        if not 0 <= chunk < num_chunks:
            raise ValueError(f"chunk {chunk} outside [0, {num_chunks})")
        lo, hi = (int(s) for s in head["span"])
        if not 0 <= lo < hi:
            raise ValueError(f"bad span [{lo}, {hi})")
        nbytes = int(head["nbytes"])
        leaves = head["leaves"]
        if not isinstance(leaves, list) or not leaves:
            raise ValueError("leaf manifest is not a non-empty list")
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"incomplete push header: {e}") from None
    payload: dict = {}
    off = nl + 1
    total = 0
    span = hi - lo
    for leaf in leaves:
        try:
            ln, name, shape, dtype = leaf
            shape = tuple(int(s) for s in shape)
            dt = np.dtype(dtype)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad leaf manifest {leaf!r:.80}: {e}") from None
        if (
            len(shape) < 2
            or shape[0] != 1
            or shape[1] != span
            or any(s <= 0 for s in shape)
        ):
            # Same non-positive-dim refusal as deserialize_blob: a
            # negative dim defeats the truncation check below.
            raise ValueError(
                f"leaf {ln}/{name} shape {shape} is not "
                f"[1, {span}, ...] with positive dims"
            )
        size = int(np.prod(shape)) * dt.itemsize
        if off + size > len(data):
            raise ValueError("truncated push payload")
        payload.setdefault(ln, {})[name] = np.frombuffer(
            data, dtype=dt, count=int(np.prod(shape)), offset=off
        ).reshape(shape)
        off += size
        total += size
    if off != len(data):
        raise ValueError("trailing bytes after push payload")
    if total != nbytes:
        raise ValueError(
            f"push payload is {total} bytes, header says {nbytes}"
        )
    return {
        "kind": "chunk",
        "xfer": xfer,
        "chunk": chunk,
        "num_chunks": num_chunks,
        "span": (lo, hi),
        "nbytes": nbytes,
        "payload": payload,
    }


class PushedKV:
    """One COMPLETE assembled transfer on the decode replica: the
    prompt's contiguous ``[1, bucket]`` stored-format KV (chunks
    concatenated in span order), the prefill replica's sampled first
    token, and the geometry the local ``_encode`` must reproduce for
    the bytes to apply."""

    __slots__ = ("kv", "first_token", "bucket", "used", "nbytes")

    def __init__(self, kv, first_token, bucket, used, nbytes):
        self.kv = kv
        self.first_token = int(first_token)
        self.bucket = int(bucket)
        self.used = int(used)
        self.nbytes = int(nbytes)


class _Xfer:
    """Sender-side transfer record (one per in-flight handoff)."""

    __slots__ = ("host", "port", "failed", "done")

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.failed = False
        self.done = threading.Event()


class _Staged:
    """Receiver-side staging record: chunks land out of band (the
    /kv/push handler) and are assembled once the fin arrives with
    every chunk present."""

    __slots__ = ("chunks", "spans", "num_chunks", "fin", "nbytes")

    def __init__(self):
        self.chunks: dict = {}
        self.spans: dict = {}
        self.num_chunks: int | None = None
        self.fin: dict | None = None
        self.nbytes = 0

    @property
    def complete(self) -> bool:
        return (
            self.fin is not None
            and self.num_chunks is not None
            and len(self.chunks) == self.num_chunks
        )


class KVPush:
    """Prefill/decode disaggregation state (r18): the PREFILL side's
    chunk-push client (a background sender thread so the dispatch
    thread never blocks on the wire) and the DECODE side's staging
    store feeding ``BatchRun``'s pushed-KV formation. One instance
    per role-carrying engine; a ``mixed`` replica has none — the
    default topology is bit-identical to r17. Thread-safe: chunks
    enqueue from the dispatch thread, the sender thread posts,
    receives land on the app executor, assembly runs on the encode
    executor, and /metrics scrapes from the event loop."""

    # Receiver caps: a staged transfer is host RAM a remote peer
    # controls — bound both the count and the bytes.
    _STAGE_CAP = 32
    _STAGE_BYTES_CAP = 1 << 30

    def __init__(self, engine, *, timeout_s: float = 10.0):
        self.eng = engine
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        # Sender side.
        self._xfers: dict[str, _Xfer] = {}
        self._sendq: "queue.Queue" = None  # created with the worker
        self._worker: threading.Thread | None = None
        # Receiver side (xfer -> _Staged, insertion-ordered for LRU
        # eviction of stale incompletes).
        self._staged: collections.OrderedDict = collections.OrderedDict()
        self._staged_bytes = 0
        # Counters (exported as generate.kv_push_*; byte counters are
        # exact payload arithmetic — every chunk's bytes are the
        # ``span × kv_page_bytes(model, 1)`` closed form — never
        # wall-clock).
        self.push_sent = 0
        self.push_send_failures = 0
        self.push_bytes_sent = 0
        self.push_recv = 0
        self.push_recv_failures = 0
        self.push_bytes_recv = 0
        self.push_applied = 0
        self.push_bytes_applied = 0
        self.push_fallbacks = 0

    # -- sender (prefill replica) ---------------------------------------
    # Patch point for in-process tests: (host, port, path, body,
    # timeout_s) -> (status, body).
    _transport = None  # set below (staticmethod of _http_post)

    def begin(self, xfer: str, host: str, port: int) -> None:
        """Open a transfer toward the decode replica at host:port.
        Chunks enqueued before ``begin`` would have nowhere to go —
        the BatchRun push hook calls this at formation."""
        with self._lock:
            self._xfers[xfer] = _Xfer(host, int(port))

    def send_chunk(self, xfer: str, chunk: int, num_chunks: int,
                   span: tuple[int, int], kv: dict) -> None:
        """Enqueue one finished chunk's KV slice for the sender
        thread. Called from the dispatch thread at the chunk
        boundary — the device→host gather already happened there (the
        chunk's bytes are needed on host either way); serialization
        and the wire POST stay on the sender thread, so the running
        prefill is never stalled by a slow decode replica."""
        self._enqueue(("chunk", xfer, chunk, num_chunks, span, kv))

    def finish(self, xfer: str, num_chunks: int, first_token: int,
               bucket: int, used: int) -> None:
        """Enqueue the transfer's finalize (first token + geometry).
        Processed strictly after every chunk of the transfer — the
        send queue is FIFO — so a decode replica that has the fin has
        everything."""
        self._enqueue(
            ("fin", xfer, num_chunks, first_token, bucket, used)
        )

    def abort(self, xfer: str) -> None:
        """Fail a transfer NOW (formation died before the fin): the
        waiter unblocks immediately and the router's fallback submits
        the request cold instead of blocking out its full timeout."""
        with self._lock:
            x = self._xfers.get(xfer)
        if x is not None:
            x.failed = True
            x.done.set()

    def wait_sent(self, xfer: str, timeout_s: float | None = None) -> bool:
        """Block until the transfer's fin was sent (or it failed);
        returns True only for a fully-delivered transfer. Pops the
        sender record — a transfer is waited on exactly once (the
        prefill replica's handler, off the event loop)."""
        with self._lock:
            x = self._xfers.get(xfer)
        if x is None:
            return False
        ok = x.done.wait(
            self.timeout_s if timeout_s is None else timeout_s
        )
        with self._lock:
            self._xfers.pop(xfer, None)
        return ok and not x.failed

    def _enqueue(self, item) -> None:
        import queue

        with self._lock:
            if self._worker is None:
                self._sendq = queue.Queue()
                self._worker = threading.Thread(
                    target=self._send_loop, name="kv-push-send",
                    daemon=True,
                )
                self._worker.start()
            q = self._sendq
        q.put(item)

    def _send_loop(self) -> None:
        while True:
            item = self._sendq.get()
            kind, xfer = item[0], item[1]
            with self._lock:
                x = self._xfers.get(xfer)
            if x is None:
                continue  # transfer already reaped (timed out waiter)
            if x.failed:
                if kind == "fin":
                    x.done.set()
                continue  # drop the rest of a failed transfer
            try:
                # The kv_push_send seam: BEFORE serialization or any
                # wire byte — an injected raise exercises the exact
                # degradation contract (transfer failed, remaining
                # chunks dropped, decode replica cold-prefills).
                faults.fire("kv_push_send")
                if kind == "chunk":
                    _, _, chunk, num_chunks, span, kv = item
                    body = serialize_push_chunk(
                        xfer, chunk, num_chunks, span, kv
                    )
                    # Exact payload arithmetic (the closed form the
                    # bench asserts) — header bytes excluded.
                    nbytes = sum(
                        np.asarray(a).nbytes
                        for layer in kv.values()
                        for a in layer.values()
                    )
                else:
                    _, _, num_chunks, first_token, bucket, used = item
                    body = serialize_push_fin(
                        xfer, num_chunks, first_token, bucket, used
                    )
                    nbytes = 0
                status, _ = self._transport(
                    x.host, x.port, "/kv/push", body, self.timeout_s
                )
                if status != 200:
                    raise RuntimeError(f"/kv/push answered {status}")
            except Exception as e:
                with self._lock:
                    self.push_send_failures += 1
                x.failed = True
                x.done.set()
                _log.debug(
                    "kv push to %s:%d failed (%s); decode replica "
                    "will cold-prefill", x.host, x.port, e,
                )
                continue
            with self._lock:
                if kind == "chunk":
                    self.push_sent += 1
                    self.push_bytes_sent += nbytes
            if kind == "fin":
                x.done.set()

    # -- receiver (decode replica) --------------------------------------
    def receive(self, data: bytes) -> dict:
        """Stage one pushed message (the /kv/push handler, app
        executor thread). Raises ``ValueError`` on corrupt bodies
        (counted receive failures — the sender sees the non-200 and
        fails the transfer). The ``kv_push_recv`` seam fires before
        any parse or counter mutation."""
        try:
            faults.fire("kv_push_recv")
            msg = deserialize_push(data)
        except Exception:
            with self._lock:
                self.push_recv_failures += 1
            raise
        with self._lock:
            st = self._staged.get(msg["xfer"])
            if st is None:
                st = self._staged[msg["xfer"]] = _Staged()
            self._staged.move_to_end(msg["xfer"])
            if msg["kind"] == "chunk":
                prev = st.chunks.pop(msg["chunk"], None)
                if prev is not None:
                    prev_bytes = sum(
                        a.nbytes for layer in prev.values()
                        for a in layer.values()
                    )
                    self._staged_bytes -= prev_bytes
                    st.nbytes -= prev_bytes
                st.chunks[msg["chunk"]] = msg["payload"]
                st.spans[msg["chunk"]] = msg["span"]
                st.num_chunks = msg["num_chunks"]
                st.nbytes += msg["nbytes"]
                self._staged_bytes += msg["nbytes"]
                self.push_recv += 1
                self.push_bytes_recv += msg["nbytes"]
            else:
                st.fin = msg
                st.num_chunks = msg["num_chunks"]
            # Bound what remote peers can pin in host RAM: evict the
            # LRU staged transfer (complete or not) past either cap.
            while len(self._staged) > self._STAGE_CAP or (
                self._staged_bytes > self._STAGE_BYTES_CAP
                and len(self._staged) > 1
            ):
                _, victim = self._staged.popitem(last=False)
                self._staged_bytes -= victim.nbytes
            return {"ok": True, "complete": st.complete}

    def take(self, xfer: str) -> PushedKV | None:
        """Pop a COMPLETE staged transfer and assemble the contiguous
        ``[1, bucket]`` KV (encode executor thread — host concat off
        the dispatch thread). ``None`` for unknown/incomplete
        transfers or spans that do not tile ``[0, bucket)`` exactly —
        the caller cold-prefills, counted via
        :meth:`count_fallback`."""
        with self._lock:
            st = self._staged.get(xfer)
            if st is None or not st.complete:
                return None
            self._staged.pop(xfer)
            self._staged_bytes -= st.nbytes
        bucket = st.fin["bucket"]
        order = sorted(st.spans, key=lambda i: st.spans[i][0])
        pos = 0
        for i in order:
            lo, hi = st.spans[i]
            if lo != pos:
                _log.debug(
                    "push transfer %s spans do not tile the bucket "
                    "(gap at %d); cold prefill", xfer, pos,
                )
                return None
            pos = hi
        if pos != bucket:
            _log.debug(
                "push transfer %s covers %d of %d slots; cold "
                "prefill", xfer, pos, bucket,
            )
            return None
        first = st.chunks[order[0]]
        kv = {
            ln: {
                name: np.concatenate(
                    [st.chunks[i][ln][name] for i in order], axis=1
                )
                for name in first[ln]
            }
            for ln in first
        }
        return PushedKV(
            kv, st.fin["first_token"], bucket, st.fin["used"], st.nbytes
        )

    def count_applied(self, nbytes: int) -> None:
        """A pushed transfer became a live decode row (BatchRun's
        formation installed it): THE disaggregation counter — it
        moving while ``prefix_builds``/``prefill_chunks`` stay flat
        is the zero-decode-side-prefill claim."""
        with self._lock:
            self.push_applied += 1
            self.push_bytes_applied += int(nbytes)

    def count_fallback(self) -> None:
        """A request that named a transfer cold-prefilled instead
        (incomplete/failed/drifted transfer): the degradation leg,
        counted so the fault matrix asserts it from state."""
        with self._lock:
            self.push_fallbacks += 1

    @property
    def staged_count(self) -> int:
        with self._lock:
            return len(self._staged)


def _http_post(host: str, port: int, path: str, body: bytes,
               timeout_s: float) -> tuple[int, bytes]:
    """One bounded POST against a peer replica (the push transport).
    Blocking by design — it only ever runs on the KVPush sender
    thread, never the event loop or the dispatch thread."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(
            "POST", path, body=body,
            headers={"content-type": "application/octet-stream"},
        )
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


KVPush._transport = staticmethod(_http_post)


def _http_get(host: str, port: int, path: str,
              timeout_s: float) -> tuple[int, bytes]:
    """One bounded GET against a peer replica. Blocking by design —
    every caller runs on an encode executor thread (the same place
    the cold prefill it replaces would block), never the event loop
    or the dispatch thread."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class KVPeer:
    """Per-engine peer-fetch state: the warm-peer hint map the router
    feeds, the fetch client, the serve-side blob resolver, and the
    counters ``/metrics`` exports. Thread-safe: hints arrive from the
    event loop (header scan), fetches run on encode executor threads,
    serves on the app's executor."""

    def __init__(self, engine, *, timeout_s: float = 5.0):
        self.eng = engine
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        # fp_digest(fp) -> (host, port) of the replica the router
        # last named warm for that prefix; bounded LRU. Keyed by the
        # 32-char DIGEST, not the prefix text — hints are noted from
        # the request header BEFORE any validation rejects the
        # request, so text keys would let a caller pin up to
        # hint_cap arbitrarily long strings in host RAM. The fetch
        # path only ever needs the digest (it is what rides the
        # wire), so nothing is lost.
        self._hints: collections.OrderedDict = collections.OrderedDict()
        self._hint_cap = 1024
        # Counters (exported as generate.kv_peer_*). Hits/bytes count
        # blobs APPLIED (an entry rebuilt from the fetch); misses
        # count completed fetches that yielded nothing usable (404,
        # corrupt wire body, local geometry drift); failures count
        # transport errors, non-200/404 statuses, and injected
        # ``peer_fetch`` faults — the legs that degrade to the cold
        # prefill without ever having had usable bytes.
        self.fetch_hits = 0
        self.fetch_misses = 0
        self.fetch_bytes = 0
        self.fetch_failures = 0
        self.serve_count = 0
        self.serve_bytes = 0
        # digest -> serialized wire image, small LRU. A prefix's blob
        # bytes are DETERMINISTIC per engine config (same params +
        # tokenizer -> the same stored-format KV, whether prefilled,
        # tier-restored, or re-adopted — the r13 byte-identity pins),
        # so the serialized image can be reused across peers: N-1
        # replicas fetching one hot prefix cost ONE device gather +
        # serialize, not N-1. Bounded tight (a few blobs) — this is a
        # latency cache for the hot serve path, not a store.
        self._serve_cache: collections.OrderedDict = (
            collections.OrderedDict()
        )
        self._serve_cache_cap = 4

    # -- warm-peer hints ------------------------------------------------
    def note_hint(self, fp: str, peer: str) -> None:
        """Record the router's warmth hint for ``fp``. Validated here
        (host:port shape) so a malformed header can never become a
        connect attempt later."""
        host, _, port = peer.rpartition(":")
        if not host or not port.isdigit():
            return
        key = fp_digest(fp)
        with self._lock:
            self._hints[key] = (host, int(port))
            self._hints.move_to_end(key)
            while len(self._hints) > self._hint_cap:
                self._hints.popitem(last=False)

    def hint_for(self, fp: str):
        with self._lock:
            return self._hints.get(fp_digest(fp))

    def drop_hint(self, fp: str) -> None:
        with self._lock:
            self._hints.pop(fp_digest(fp), None)

    # -- fetch (encode executor thread) ---------------------------------
    # Patch point for in-process tests and drills: (host, port, path,
    # timeout_s) -> (status, body).
    _transport = staticmethod(_http_get)

    def fetch(self, fp: str):
        """Fetch ``fp``'s blob from its hinted warm peer, or ``None``
        (no hint / miss / failure — every ``None`` means the caller
        goes cold). The ``peer_fetch`` fault point fires before any
        wire byte moves. Returns an UNVALIDATED-against-local-geometry
        blob — the caller applies the same ``_plan`` check every tier
        blob passes and reports the outcome via
        :meth:`count_applied` / :meth:`count_miss`."""
        digest = fp_digest(fp)
        with self._lock:
            hint = self._hints.get(digest)
        if hint is None:
            return None
        host, port = hint
        try:
            faults.fire("peer_fetch")
            status, body = self._transport(
                host, port, f"/kv/prefix?fp={digest}",
                self.timeout_s,
            )
        except Exception as e:
            with self._lock:
                self.fetch_failures += 1
            _log.debug(
                "peer fetch from %s:%d failed (%s); cold path",
                host, port, e,
            )
            return None
        if status == 404:
            # The peer is not warm after all (evicted, restarted):
            # drop the hint so the next miss does not re-pay the hop.
            with self._lock:
                self.fetch_misses += 1
                self._hints.pop(digest, None)
            return None
        if status != 200:
            with self._lock:
                self.fetch_failures += 1
            _log.debug(
                "peer %s:%d answered %d for a KV fetch; cold path",
                host, port, status,
            )
            return None
        try:
            return deserialize_blob(fp, body)
        except Exception as e:
            # ValueError is the documented corruption signal, but the
            # contract here is the CALLER's: any body that does not
            # parse is a counted miss and a cold prefill — never an
            # exception escaping into the user's request.
            with self._lock:
                self.fetch_misses += 1
            _log.debug("corrupt peer blob dropped as a miss: %s", e)
            return None

    def count_applied(self, nbytes: int) -> None:
        """The fetched blob rebuilt an entry: the fetch is a hit and
        its exact payload bytes count."""
        with self._lock:
            self.fetch_hits += 1
            self.fetch_bytes += int(nbytes)

    def count_miss(self) -> None:
        """The fetched blob can never apply here (geometry drift vs
        what a local build would produce today): a miss, like a
        corrupt body — the bytes were real, just not ours."""
        with self._lock:
            self.fetch_misses += 1

    # -- serve (app executor thread) ------------------------------------
    def serve_wire(self, digest: str) -> bytes | None:
        """Resolve a fingerprint digest against this replica's warm
        state and return the blob's wire bytes, or ``None`` (404).
        Sources, warmest-cheapest first: the host tier's blob (already
        page-shaped host numpy — no device work), else the prefix
        dict's device-resident entry gathered via its contiguous KV
        (never donated, safe from any thread). The ``peer_serve``
        fault point fires before anything is resolved; counters move
        only after serialization succeeds."""
        from mlapi_tpu.serving.kv_tier import (
            payload_bytes,
            payload_from_contiguous,
        )

        faults.fire("peer_serve")
        with self._lock:
            cached = self._serve_cache.get(digest)
            if cached is not None:
                self._serve_cache.move_to_end(digest)
                self.serve_count += 1
                self.serve_bytes += cached[1]
                return cached[0]
        eng = self.eng
        tier = getattr(eng, "kv_tier", None)
        fp = None
        if tier is not None:
            fp = next(
                (
                    f for f in tier.fingerprints()
                    if isinstance(f, str) and fp_digest(f) == digest
                ),
                None,
            )
        blob = None
        if fp is not None:
            blob = tier.lookup(fp, count=False)
            if blob is not None and blob.bucket is None:
                # Spilled before any entry registration recorded its
                # metadata: a peer cannot rebuild an entry from it —
                # fall through to the entry scan below.
                blob = None
        if blob is None:
            # Snapshot under the lock, hash OUTSIDE it: every
            # /generate request's entry() fast path takes this same
            # lock, and hashing N full prefix texts under it would
            # serialize encode threads behind every peer probe.
            with eng.prefix._lock:
                candidates = list(eng.prefix._entries.items())
            entry = next(
                (e for f, e in candidates if fp_digest(f) == digest),
                None,
            )
            if entry is None:
                return None
            from mlapi_tpu.serving.kv_tier import KVTierBlob

            page = eng.pool.page if eng.pool is not None else entry.bucket
            payload = payload_from_contiguous(entry.kv, page)
            blob = KVTierBlob(
                entry.fp, payload, page, payload_bytes(payload),
                entry.bucket, entry.lo, entry.used,
            )
        data = serialize_blob(blob)
        with self._lock:
            self._serve_cache[digest] = (data, blob.nbytes)
            self._serve_cache.move_to_end(digest)
            while len(self._serve_cache) > self._serve_cache_cap:
                self._serve_cache.popitem(last=False)
            self.serve_count += 1
            self.serve_bytes += blob.nbytes
        return data
