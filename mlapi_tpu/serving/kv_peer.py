"""Peer-to-peer prefix-KV fetch between engine replicas.

The r14 router gives the fleet ONE cold prefill per distinct prefix —
but only while the affinity-preferred replica stays up and under its
depth limit. Any failover, drain, or depth overflow lands the prefix
on a replica whose caches have never seen it, and that replica pays
the full O(P²) prefill again even though a peer still holds the exact
stored-format bytes (device-resident prefix entry, or an r13 host-tier
blob). This module promotes the tier blob into the fleet's
TRANSFERABLE KV unit: a wire hop between replica tiers, so affinity
becomes a soft hint and a replica death no longer costs its whole
prefix working set (ROADMAP item 2, step one; the hierarchical-memory
move Snap ML makes across DRAM/NVMe levels, taken across hosts).

Topology — who knows what:

- **The router knows warmth.** Its HRW affinity map already names the
  replica most likely to hold a prefix; any forward to a
  NON-preferred replica (p2c fallback, failover, depth overflow,
  post-drain remap) carries ``x-mlapi-warm-peer: host:port`` naming
  the HRW head (``Router.forward``). Replica-gated like
  ``x-mlapi-router-depth`` — direct callers cannot aim a replica's
  fetches at an arbitrary host.
- **The serving replica knows bytes.** ``GET
  /kv/prefix?fp=<digest>`` (``serving/app.py``, installed only with
  ``--kv-peer-fetch``) serves the prefix's blob in its STORED format
  — int8-halved payloads cross the wire at half the bytes for free —
  from the host tier when spilled, else gathered from the
  device-resident prefix entry's contiguous KV (safe from any
  thread: entry KV is never donated). A GET works while DRAINING —
  exactly the window where a peer needs the drained replica's slice.
- **The fetching replica stays off the dispatch thread.** The fetch
  runs inside ``PrefixCache._restore`` on the encode executor thread
  (where the cold prefill it replaces would have run); the fetched
  blob rebuilds the ``_PrefixEntry`` and is STAGED into the local
  tier (``KVTier.stage``), so the dispatch-thread paged formation
  restores pool pages through the existing alloc-first
  ``PagePool.restore_entry`` path — a mid-fetch or mid-restore
  failure conserves pages exactly and degrades to the r13 cold path.
  No wire I/O ever touches the dispatch thread.

Wire format (one blob): a single JSON header line —
``{"v": 1, "page", "num_pages", "nbytes", "bucket", "lo", "used",
"leaves": [[layer, name, shape, dtype], ...]}`` — followed by each
leaf's raw C-order bytes in header order. The payload bytes are
EXACTLY the ``num_pages × kv_page_bytes`` closed form (the same
``ops/quant.kv_tree_bytes`` arithmetic the tier's counters use);
``deserialize_blob`` validates every leaf's size and the total
against the header, so a truncated or corrupt body is a counted MISS,
never a wrong cache. Geometry against the LOCAL replica (bucket/page
drift across builds or configs) is validated by the same ``_plan`` /
``restore_entry`` checks every tier blob passes — a peer can never
install bytes the local pool would not have produced itself.

Failure grammar (``serving/faults.py``): ``peer_fetch`` fires before
the wire request, ``peer_serve`` before the serve-side blob resolve —
a raise at either point falls back to the cold prefill with pages
conserved and the stream completing.
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading

import numpy as np

from mlapi_tpu.serving import faults
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.kv_peer")

WIRE_VERSION = 1
# Header line length cap: a dozen layers of leaf manifests fit in a
# few KB; anything larger is a corrupt/hostile response, refused
# before allocation.
_MAX_HEADER_BYTES = 1 << 20


def fp_digest(fp: str) -> str:
    """URL-safe fingerprint of a prefix string: blake2b-128 hex of
    its UTF-8 bytes (prefix text is arbitrary — it cannot ride a URL
    path raw, and the serving replica must not need the full text to
    index its blobs)."""
    return hashlib.blake2b(
        fp.encode("utf-8", "surrogatepass"), digest_size=16
    ).hexdigest()


def serialize_blob(blob) -> bytes:
    """A :class:`~mlapi_tpu.serving.kv_tier.KVTierBlob` → wire bytes:
    JSON header line + concatenated raw leaf payloads in header
    order. Payload bytes total exactly ``blob.nbytes`` (the
    ``num_pages × kv_page_bytes`` closed form)."""
    leaves = []
    chunks = []
    for ln in sorted(blob.payload):
        for name in sorted(blob.payload[ln]):
            a = np.ascontiguousarray(blob.payload[ln][name])
            leaves.append([ln, name, list(a.shape), a.dtype.str])
            chunks.append(a.tobytes())
    header = json.dumps(
        {
            "v": WIRE_VERSION,
            "page": blob.page,
            "num_pages": blob.num_pages,
            "nbytes": blob.nbytes,
            "bucket": blob.bucket,
            "lo": blob.lo,
            "used": blob.used,
            "leaves": leaves,
        }
    ).encode()
    return header + b"\n" + b"".join(chunks)


def deserialize_blob(fp, data: bytes):
    """Wire bytes → a validated ``KVTierBlob`` for ``fp``. Raises
    ``ValueError`` on ANY inconsistency — unparseable header, leaf
    shapes that are not ``[num_pages, page, ...]``, a payload whose
    size does not match the manifest, trailing bytes, or a byte total
    that disagrees with the header's ``nbytes`` — so a corrupt wire
    response is dropped as a counted miss, never installed."""
    from mlapi_tpu.serving.kv_tier import KVTierBlob

    nl = data.find(b"\n", 0, _MAX_HEADER_BYTES)
    if nl < 0:
        raise ValueError("no header line in peer blob")
    try:
        head = json.loads(data[:nl])
    except Exception as e:
        raise ValueError(f"unparseable peer blob header: {e}") from None
    if not isinstance(head, dict) or head.get("v") != WIRE_VERSION:
        raise ValueError(f"unknown peer blob version {head!r:.80}")
    try:
        page = int(head["page"])
        num_pages = int(head["num_pages"])
        nbytes = int(head["nbytes"])
        # A meta-less blob cannot rebuild an entry and the serve side
        # never emits one, so a None here is corruption — and int()
        # turns it (or any non-numeric junk) into the TypeError this
        # clause converts to the one documented exception type.
        bucket = int(head["bucket"])
        lo = int(head["lo"])
        used = int(head["used"])
        leaves = head["leaves"]
        if not isinstance(leaves, list) or not leaves:
            raise ValueError("leaf manifest is not a non-empty list")
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"incomplete peer blob header: {e}") from None
    payload: dict = {}
    off = nl + 1
    total = 0
    for leaf in leaves:
        try:
            ln, name, shape, dtype = leaf
            shape = tuple(int(s) for s in shape)
            dt = np.dtype(dtype)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad leaf manifest {leaf!r:.80}: {e}") from None
        if (
            len(shape) < 2
            or shape[0] != num_pages
            or shape[1] != page
            or any(s <= 0 for s in shape)
        ):
            # Non-positive dims included: a negative dim would make
            # ``size`` negative — defeating the truncation check
            # below and letting ``off`` walk backward into already-
            # consumed bytes (np.frombuffer treats a negative count
            # as "the rest of the buffer", silently).
            raise ValueError(
                f"leaf {ln}/{name} shape {shape} is not "
                f"[{num_pages}, {page}, ...] with positive dims"
            )
        size = int(np.prod(shape)) * dt.itemsize
        if off + size > len(data):
            raise ValueError("truncated peer blob payload")
        payload.setdefault(ln, {})[name] = np.frombuffer(
            data, dtype=dt, count=int(np.prod(shape)), offset=off
        ).reshape(shape)
        off += size
        total += size
    if off != len(data):
        raise ValueError("trailing bytes after peer blob payload")
    if total != nbytes:
        raise ValueError(
            f"peer blob payload is {total} bytes, header says {nbytes}"
        )
    return KVTierBlob(fp, payload, page, nbytes, bucket, lo, used)


def _http_get(host: str, port: int, path: str,
              timeout_s: float) -> tuple[int, bytes]:
    """One bounded GET against a peer replica. Blocking by design —
    every caller runs on an encode executor thread (the same place
    the cold prefill it replaces would block), never the event loop
    or the dispatch thread."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class KVPeer:
    """Per-engine peer-fetch state: the warm-peer hint map the router
    feeds, the fetch client, the serve-side blob resolver, and the
    counters ``/metrics`` exports. Thread-safe: hints arrive from the
    event loop (header scan), fetches run on encode executor threads,
    serves on the app's executor."""

    def __init__(self, engine, *, timeout_s: float = 5.0):
        self.eng = engine
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        # fp_digest(fp) -> (host, port) of the replica the router
        # last named warm for that prefix; bounded LRU. Keyed by the
        # 32-char DIGEST, not the prefix text — hints are noted from
        # the request header BEFORE any validation rejects the
        # request, so text keys would let a caller pin up to
        # hint_cap arbitrarily long strings in host RAM. The fetch
        # path only ever needs the digest (it is what rides the
        # wire), so nothing is lost.
        self._hints: collections.OrderedDict = collections.OrderedDict()
        self._hint_cap = 1024
        # Counters (exported as generate.kv_peer_*). Hits/bytes count
        # blobs APPLIED (an entry rebuilt from the fetch); misses
        # count completed fetches that yielded nothing usable (404,
        # corrupt wire body, local geometry drift); failures count
        # transport errors, non-200/404 statuses, and injected
        # ``peer_fetch`` faults — the legs that degrade to the cold
        # prefill without ever having had usable bytes.
        self.fetch_hits = 0
        self.fetch_misses = 0
        self.fetch_bytes = 0
        self.fetch_failures = 0
        self.serve_count = 0
        self.serve_bytes = 0
        # digest -> serialized wire image, small LRU. A prefix's blob
        # bytes are DETERMINISTIC per engine config (same params +
        # tokenizer -> the same stored-format KV, whether prefilled,
        # tier-restored, or re-adopted — the r13 byte-identity pins),
        # so the serialized image can be reused across peers: N-1
        # replicas fetching one hot prefix cost ONE device gather +
        # serialize, not N-1. Bounded tight (a few blobs) — this is a
        # latency cache for the hot serve path, not a store.
        self._serve_cache: collections.OrderedDict = (
            collections.OrderedDict()
        )
        self._serve_cache_cap = 4

    # -- warm-peer hints ------------------------------------------------
    def note_hint(self, fp: str, peer: str) -> None:
        """Record the router's warmth hint for ``fp``. Validated here
        (host:port shape) so a malformed header can never become a
        connect attempt later."""
        host, _, port = peer.rpartition(":")
        if not host or not port.isdigit():
            return
        key = fp_digest(fp)
        with self._lock:
            self._hints[key] = (host, int(port))
            self._hints.move_to_end(key)
            while len(self._hints) > self._hint_cap:
                self._hints.popitem(last=False)

    def hint_for(self, fp: str):
        with self._lock:
            return self._hints.get(fp_digest(fp))

    def drop_hint(self, fp: str) -> None:
        with self._lock:
            self._hints.pop(fp_digest(fp), None)

    # -- fetch (encode executor thread) ---------------------------------
    # Patch point for in-process tests and drills: (host, port, path,
    # timeout_s) -> (status, body).
    _transport = staticmethod(_http_get)

    def fetch(self, fp: str):
        """Fetch ``fp``'s blob from its hinted warm peer, or ``None``
        (no hint / miss / failure — every ``None`` means the caller
        goes cold). The ``peer_fetch`` fault point fires before any
        wire byte moves. Returns an UNVALIDATED-against-local-geometry
        blob — the caller applies the same ``_plan`` check every tier
        blob passes and reports the outcome via
        :meth:`count_applied` / :meth:`count_miss`."""
        digest = fp_digest(fp)
        with self._lock:
            hint = self._hints.get(digest)
        if hint is None:
            return None
        host, port = hint
        try:
            faults.fire("peer_fetch")
            status, body = self._transport(
                host, port, f"/kv/prefix?fp={digest}",
                self.timeout_s,
            )
        except Exception as e:
            with self._lock:
                self.fetch_failures += 1
            _log.debug(
                "peer fetch from %s:%d failed (%s); cold path",
                host, port, e,
            )
            return None
        if status == 404:
            # The peer is not warm after all (evicted, restarted):
            # drop the hint so the next miss does not re-pay the hop.
            with self._lock:
                self.fetch_misses += 1
                self._hints.pop(digest, None)
            return None
        if status != 200:
            with self._lock:
                self.fetch_failures += 1
            _log.debug(
                "peer %s:%d answered %d for a KV fetch; cold path",
                host, port, status,
            )
            return None
        try:
            return deserialize_blob(fp, body)
        except Exception as e:
            # ValueError is the documented corruption signal, but the
            # contract here is the CALLER's: any body that does not
            # parse is a counted miss and a cold prefill — never an
            # exception escaping into the user's request.
            with self._lock:
                self.fetch_misses += 1
            _log.debug("corrupt peer blob dropped as a miss: %s", e)
            return None

    def count_applied(self, nbytes: int) -> None:
        """The fetched blob rebuilt an entry: the fetch is a hit and
        its exact payload bytes count."""
        with self._lock:
            self.fetch_hits += 1
            self.fetch_bytes += int(nbytes)

    def count_miss(self) -> None:
        """The fetched blob can never apply here (geometry drift vs
        what a local build would produce today): a miss, like a
        corrupt body — the bytes were real, just not ours."""
        with self._lock:
            self.fetch_misses += 1

    # -- serve (app executor thread) ------------------------------------
    def serve_wire(self, digest: str) -> bytes | None:
        """Resolve a fingerprint digest against this replica's warm
        state and return the blob's wire bytes, or ``None`` (404).
        Sources, warmest-cheapest first: the host tier's blob (already
        page-shaped host numpy — no device work), else the prefix
        dict's device-resident entry gathered via its contiguous KV
        (never donated, safe from any thread). The ``peer_serve``
        fault point fires before anything is resolved; counters move
        only after serialization succeeds."""
        from mlapi_tpu.serving.kv_tier import (
            payload_bytes,
            payload_from_contiguous,
        )

        faults.fire("peer_serve")
        with self._lock:
            cached = self._serve_cache.get(digest)
            if cached is not None:
                self._serve_cache.move_to_end(digest)
                self.serve_count += 1
                self.serve_bytes += cached[1]
                return cached[0]
        eng = self.eng
        tier = getattr(eng, "kv_tier", None)
        fp = None
        if tier is not None:
            fp = next(
                (
                    f for f in tier.fingerprints()
                    if isinstance(f, str) and fp_digest(f) == digest
                ),
                None,
            )
        blob = None
        if fp is not None:
            blob = tier.lookup(fp, count=False)
            if blob is not None and blob.bucket is None:
                # Spilled before any entry registration recorded its
                # metadata: a peer cannot rebuild an entry from it —
                # fall through to the entry scan below.
                blob = None
        if blob is None:
            # Snapshot under the lock, hash OUTSIDE it: every
            # /generate request's entry() fast path takes this same
            # lock, and hashing N full prefix texts under it would
            # serialize encode threads behind every peer probe.
            with eng.prefix._lock:
                candidates = list(eng.prefix._entries.items())
            entry = next(
                (e for f, e in candidates if fp_digest(f) == digest),
                None,
            )
            if entry is None:
                return None
            from mlapi_tpu.serving.kv_tier import KVTierBlob

            page = eng.pool.page if eng.pool is not None else entry.bucket
            payload = payload_from_contiguous(entry.kv, page)
            blob = KVTierBlob(
                entry.fp, payload, page, payload_bytes(payload),
                entry.bucket, entry.lo, entry.used,
            )
        data = serialize_blob(blob)
        with self._lock:
            self._serve_cache[digest] = (data, blob.nbytes)
            self._serve_cache.move_to_end(digest)
            while len(self._serve_cache) > self._serve_cache_cap:
                self._serve_cache.popitem(last=False)
            self.serve_count += 1
            self.serve_bytes += blob.nbytes
        return data
