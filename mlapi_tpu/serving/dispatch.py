"""Chained device dispatch with lazy token drains.

``decode_chunk_fn`` RETURNS the feedback token as a device array, so
consecutive chunks need no host round trip between them: the decode
loop dispatches ahead and drains token readbacks lazily. Through a
high-RTT attach (the tunneled chip: ~68 ms per synced readback, while
argument uploads pipeline for free) this turns a request's serial cost
from one RTT PER CHUNK into one readback at the end.

:class:`DispatchChain` owns the in-flight chunk queue and the
device-resident feedback token (``tok_dev``); the per-request delivery
bookkeeping stays with the caller as the ``deliver`` callback, because
it mutates the batch's host mirrors. Anything that mutates batch state
— admission, compaction, the spec phase — must :meth:`invalidate`
first (drain fully and drop the device chain: the host mirrors are the
source of truth again). Split out of ``engine._run_batch`` (r04
VERDICT "Next" #7).
"""

from __future__ import annotations

import numpy as np


class DispatchChain:
    def __init__(self, deliver):
        # deliver(toks_host [B, size], size, live_indices): push the
        # drained chunk to its requests and update the host mirrors.
        self._deliver = deliver
        self._inflight: list = []  # (toks_dev [B, size], size, live)
        self.tok_dev = None        # device-resident feedback token

    def __len__(self) -> int:
        return len(self._inflight)

    def push(self, toks_dev, size: int, live: list) -> None:
        """Queue one dispatched chunk's device output for a later
        drain. ``live`` are the request indices it covers."""
        self._inflight.append((toks_dev, size, live))

    def pending_live(self):
        """Request indices covered by any in-flight chunk."""
        for _, _, plive in self._inflight:
            yield from plive

    def drain(self, count: int | None = None) -> None:
        """Read back the oldest ``count`` chunks (all by default) and
        deliver them in dispatch order."""
        take = self._inflight[:] if count is None else self._inflight[:count]
        if not take:
            return
        del self._inflight[: len(take)]
        for toks_dev, _, _ in take:
            # Start every host copy before blocking on the first: one
            # overlapped transfer window instead of a serial RTT per
            # chunk. (A device-side concat + single readback was
            # measured too: it lands in the same noise band on the
            # tunneled attach, so the simpler form stays.)
            try:
                toks_dev.copy_to_host_async()
            except AttributeError:
                pass
        for toks_dev, got, plive in take:
            self._deliver(np.asarray(toks_dev), got, plive)

    def invalidate(self) -> None:
        """Batch state is about to change under the chain: deliver
        everything in flight and drop the device-resident feedback
        token — the next dispatch re-uploads from the host mirrors."""
        self.drain()
        self.tok_dev = None
