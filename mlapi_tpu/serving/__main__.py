"""CLI: serve a checkpoint over HTTP.

Replaces the reference's ``uvicorn main:app --reload`` (``README.md:16``)
with a first-class entry point::

    python -m mlapi_tpu.serving --checkpoint /path/to/ckpt --port 8000

For a quick demo without a pre-trained checkpoint (trains Iris on the
attached backend in ~a second, the whole reference pipeline end to
end)::

    python -m mlapi_tpu.serving --demo-iris --port 8000
"""

from __future__ import annotations

import argparse
import asyncio
import tempfile

from mlapi_tpu.serving import InferenceEngine, Server, build_app
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.main")


def _demo_iris_checkpoint() -> str:
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.datasets import load_iris
    from mlapi_tpu.models import get_model
    from mlapi_tpu.train import fit

    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features, num_classes=iris.num_classes
    )
    result = fit(model, iris, steps=500, learning_rate=0.1, weight_decay=1e-3)
    _log.info("demo Iris trained: test_accuracy=%.4f", result.test_accuracy)
    path = tempfile.mkdtemp(prefix="mlapi_tpu_iris_")
    save_checkpoint(
        path,
        result.params,
        step=result.steps,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
            "feature_names": list(iris.feature_names),
        },
        vocab=iris.vocab,
    )
    return path


def main(argv=None) -> None:
    from mlapi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    parser = argparse.ArgumentParser("mlapi_tpu.serving")
    parser.add_argument("--checkpoint", help="committed checkpoint dir")
    parser.add_argument(
        "--demo-iris", action="store_true", help="train Iris now and serve it"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument(
        "--max-wait-ms", type=float, default=0.2, help="micro-batch window"
    )
    parser.add_argument(
        "--profiler-port", type=int, default=0,
        help="start a jax.profiler server on this port (XProf/TensorBoard "
             "can attach live)",
    )
    args = parser.parse_args(argv)

    if args.profiler_port:
        import jax.profiler

        jax.profiler.start_server(args.profiler_port)
        _log.info("jax profiler server on port %d", args.profiler_port)

    if not args.checkpoint and not args.demo_iris:
        parser.error("need --checkpoint or --demo-iris")
    ckpt = args.checkpoint or _demo_iris_checkpoint()

    engine = InferenceEngine.from_checkpoint(ckpt)
    app = build_app(engine, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms)
    server = Server(app, host=args.host, port=args.port)
    try:
        asyncio.run(server.serve_forever())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
