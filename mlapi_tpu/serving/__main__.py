"""CLI: serve a checkpoint over HTTP.

Replaces the reference's ``uvicorn main:app --reload`` (``README.md:16``)
with a first-class entry point::

    python -m mlapi_tpu.serving --checkpoint /path/to/ckpt --port 8000

For a quick demo without a pre-trained checkpoint (trains Iris on the
attached backend in ~a second, the whole reference pipeline end to
end)::

    python -m mlapi_tpu.serving --demo-iris --port 8000
"""

from __future__ import annotations

import argparse
import asyncio
import tempfile

from mlapi_tpu.serving import InferenceEngine, Server, build_app
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.main")


def _demo_iris_checkpoint() -> str:
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.datasets import load_iris
    from mlapi_tpu.models import get_model
    from mlapi_tpu.train import fit

    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features, num_classes=iris.num_classes
    )
    result = fit(model, iris, steps=500, learning_rate=0.1, weight_decay=1e-3)
    _log.info("demo Iris trained: test_accuracy=%.4f", result.test_accuracy)
    path = tempfile.mkdtemp(prefix="mlapi_tpu_iris_")
    save_checkpoint(
        path,
        result.params,
        step=result.steps,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
            "feature_names": list(iris.feature_names),
        },
        vocab=iris.vocab,
    )
    return path


def _watch_and_reexec(argv) -> int:
    """Dev loop (the reference's ``uvicorn --reload``,
    ``README.md:16``): run the server as a child process, poll the
    package's ``.py`` mtimes, and restart the child on any change.
    The child carries a marker env var so it serves instead of
    watching."""
    import os
    import signal
    import subprocess
    import sys
    import time

    import mlapi_tpu

    root = os.path.dirname(os.path.abspath(mlapi_tpu.__file__))

    def snapshot() -> dict:
        mt = {}
        for dirpath, _, files in os.walk(root):
            for f in files:
                if f.endswith(".py"):
                    p = os.path.join(dirpath, f)
                    try:
                        mt[p] = os.stat(p).st_mtime
                    except OSError:
                        pass
        return mt

    env = dict(os.environ, MLAPI_TPU_RELOAD_CHILD="1")
    cmd = [sys.executable, "-m", "mlapi_tpu.serving", *argv]
    while True:
        snap = snapshot()
        child = subprocess.Popen(cmd, env=env)
        restart = False
        try:
            while True:
                time.sleep(0.5)
                if child.poll() is not None:
                    # A crashed child (e.g. a transient syntax error
                    # mid-edit) must NOT end the watch — that's the
                    # state a dev-reload loop exists to recover from.
                    # Keep watching; the next change respawns it.
                    _log.warning(
                        "server exited with code %d; waiting for a "
                        "source change to restart", child.returncode,
                    )
                    while snapshot() == snap:
                        time.sleep(0.5)
                    restart = True
                    break
                if snapshot() != snap:
                    _log.info("source change detected; restarting server")
                    restart = True
                    break
        except KeyboardInterrupt:
            restart = False
        if child.poll() is None:
            child.send_signal(signal.SIGTERM)
            try:
                child.wait(10)
            except subprocess.TimeoutExpired:
                child.kill()
        if not restart:
            return 0


def main(argv=None) -> None:
    from mlapi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    parser = argparse.ArgumentParser("mlapi_tpu.serving")
    parser.add_argument("--checkpoint", help="committed checkpoint dir")
    parser.add_argument(
        "--demo-iris", action="store_true", help="train Iris now and serve it"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument(
        "--max-wait-ms", type=float, default=0.2, help="micro-batch window"
    )
    parser.add_argument(
        "--profiler-port", type=int, default=0,
        help="start a jax.profiler server on this port (XProf/TensorBoard "
             "can attach live)",
    )
    parser.add_argument(
        "--reload", action="store_true",
        help="dev loop: restart the server when package sources change",
    )
    args = parser.parse_args(argv)

    if args.reload:
        import os
        import sys

        if os.environ.get("MLAPI_TPU_RELOAD_CHILD") != "1":
            sys.exit(
                _watch_and_reexec(argv if argv is not None else sys.argv[1:])
            )

    if args.profiler_port:
        import jax.profiler

        jax.profiler.start_server(args.profiler_port)
        _log.info("jax profiler server on port %d", args.profiler_port)

    if not args.checkpoint and not args.demo_iris:
        parser.error("need --checkpoint or --demo-iris")
    ckpt = args.checkpoint or _demo_iris_checkpoint()

    engine = InferenceEngine.from_checkpoint(ckpt)
    app = build_app(engine, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms)
    server = Server(app, host=args.host, port=args.port)
    try:
        asyncio.run(server.serve_forever())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
