"""CLI: serve a checkpoint over HTTP.

Replaces the reference's ``uvicorn main:app --reload`` (``README.md:16``)
with a first-class entry point::

    python -m mlapi_tpu.serving --checkpoint /path/to/ckpt --port 8000

For a quick demo without a pre-trained checkpoint (trains Iris on the
attached backend in ~a second, the whole reference pipeline end to
end)::

    python -m mlapi_tpu.serving --demo-iris --port 8000
"""

from __future__ import annotations

import argparse
import asyncio
import tempfile

from mlapi_tpu.serving import InferenceEngine, Server, build_app
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.main")


def _demo_iris_checkpoint() -> str:
    from mlapi_tpu.checkpoint import save_checkpoint
    from mlapi_tpu.datasets import load_iris
    from mlapi_tpu.models import get_model
    from mlapi_tpu.train import fit

    iris = load_iris()
    model = get_model(
        "linear", num_features=iris.num_features, num_classes=iris.num_classes
    )
    result = fit(model, iris, steps=500, learning_rate=0.1, weight_decay=1e-3)
    _log.info("demo Iris trained: test_accuracy=%.4f", result.test_accuracy)
    path = tempfile.mkdtemp(prefix="mlapi_tpu_iris_")
    save_checkpoint(
        path,
        result.params,
        step=result.steps,
        config={
            "model": "linear",
            "model_kwargs": {
                "num_features": iris.num_features,
                "num_classes": iris.num_classes,
            },
            "feature_names": list(iris.feature_names),
        },
        vocab=iris.vocab,
    )
    return path


def _watch_and_reexec(argv) -> int:
    """Dev loop (the reference's ``uvicorn --reload``,
    ``README.md:16``): run the server as a child process, poll the
    package's ``.py`` mtimes, and restart the child on any change.
    The child carries a marker env var so it serves instead of
    watching."""
    import os
    import signal
    import subprocess
    import sys
    import time

    import mlapi_tpu

    root = os.path.dirname(os.path.abspath(mlapi_tpu.__file__))

    def snapshot() -> dict:
        mt = {}
        for dirpath, _, files in os.walk(root):
            for f in files:
                if f.endswith(".py"):
                    p = os.path.join(dirpath, f)
                    try:
                        mt[p] = os.stat(p).st_mtime
                    except OSError:
                        pass
        return mt

    env = dict(os.environ, MLAPI_TPU_RELOAD_CHILD="1")
    cmd = [sys.executable, "-m", "mlapi_tpu.serving", *argv]
    while True:
        snap = snapshot()
        child = subprocess.Popen(cmd, env=env)
        restart = False
        try:
            while True:
                time.sleep(0.5)
                if child.poll() is not None:
                    # A crashed child (e.g. a transient syntax error
                    # mid-edit) must NOT end the watch — that's the
                    # state a dev-reload loop exists to recover from.
                    # Keep watching; the next change respawns it.
                    _log.warning(
                        "server exited with code %d; waiting for a "
                        "source change to restart", child.returncode,
                    )
                    while snapshot() == snap:
                        time.sleep(0.5)
                    restart = True
                    break
                if snapshot() != snap:
                    _log.info("source change detected; restarting server")
                    restart = True
                    break
        except KeyboardInterrupt:
            restart = False
        if child.poll() is None:
            child.send_signal(signal.SIGTERM)
            try:
                child.wait(10)
            except subprocess.TimeoutExpired:
                child.kill()
        if not restart:
            return 0


def _forwarded_engine_flags(args) -> list:
    """The engine/app flags a topology supervisor (``--workers``,
    ``--router``) forwards verbatim to every child server process —
    one list so the two supervisors cannot drift (a flag added to one
    but not the other would silently serve a different engine config
    per topology)."""
    cmd: list = []
    if args.max_batch is not None:
        cmd += ["--max-batch", str(args.max_batch)]
    if getattr(args, "quantize", None):
        cmd += ["--quantize", args.quantize]
    if getattr(args, "kv_quant", None):
        cmd += ["--kv-quant", args.kv_quant]
    if getattr(args, "decode_attn_impl", None):
        cmd += ["--decode-attn-impl", args.decode_attn_impl]
    if getattr(args, "kv_page_size", None):
        cmd += ["--kv-page-size", str(args.kv_page_size)]
    if getattr(args, "kv_pages", None):
        cmd += ["--kv-pages", str(args.kv_pages)]
    if getattr(args, "kv_tier_bytes", 0):
        cmd += ["--kv-tier-bytes", str(args.kv_tier_bytes)]
    if getattr(args, "kv_tier_disk_dir", None):
        # Children may share one dir: blob filenames are pid-scoped,
        # each process indexes only its own files (the bytes budget is
        # per-process), and the startup sweep only unlinks files
        # whose owner pid is dead. Forwarded independently of the
        # bytes flag so a mis-paired config fails in the child
        # exactly as it would single-process (main() also rejects it
        # before supervising).
        cmd += ["--kv-tier-disk-dir", args.kv_tier_disk_dir]
    if getattr(args, "kv_peer_fetch", False):
        cmd += ["--kv-peer-fetch"]
    if getattr(args, "adapter_slots", 0):
        cmd += ["--adapter-slots", str(args.adapter_slots)]
        if getattr(args, "adapter_store_bytes", 0):
            cmd += ["--adapter-store-bytes", str(args.adapter_store_bytes)]
        if getattr(args, "adapter_disk_dir", None):
            # Same shared-dir discipline as --kv-tier-disk-dir: blob
            # filenames are pid-scoped, so children can share one dir.
            cmd += ["--adapter-disk-dir", args.adapter_disk_dir]
        for spec in getattr(args, "adapter", None) or ():
            cmd += ["--adapter", spec]
    if getattr(args, "replica_role", "mixed") != "mixed":
        # A uniform role for every child (the role-split supervisor
        # appends its own per-child --replica-role AFTER these, and
        # argparse's last occurrence wins).
        cmd += ["--replica-role", args.replica_role]
    if not getattr(args, "prefill_page_native", True):
        cmd += ["--no-prefill-page-native"]
    if not getattr(args, "prefill_interleave", True):
        cmd += ["--no-prefill-interleave"]
    cmd += ["--sched-max-batches",
            str(getattr(args, "sched_max_batches", 2))]
    # Multi-model + multi-tenant config replicates to every child:
    # the whole fleet serves the same registry under the same quota
    # table (per-model replica groups come from children launched
    # with DIFFERENT --model sets via --replica-urls).
    for spec in getattr(args, "model", None) or ():
        cmd += ["--model", spec]
    for flag, key in (
        ("--tenant-pages", "tenant_pages"),
        ("--tenant-slots", "tenant_slots"),
        ("--tenant-weight", "tenant_weight"),
    ):
        for spec in getattr(args, key, None) or ():
            cmd += [flag, spec]
    if getattr(args, "mesh_shape", None):
        cmd += ["--mesh-shape", args.mesh_shape]
    if getattr(args, "draft_checkpoint", None):
        cmd += ["--draft-checkpoint", args.draft_checkpoint]
    if getattr(args, "spec_sample", False):
        cmd += ["--spec-sample"]
    if getattr(args, "default_deadline_ms", None) is not None:
        cmd += ["--default-deadline-ms", str(args.default_deadline_ms)]
    if not getattr(args, "admission_control", True):
        cmd += ["--no-admission-control"]
    cmd += ["--drain-timeout-s", str(getattr(args, "drain_timeout_s", 10.0))]
    return cmd


def _supervise_workers(n: int, ckpt: str, args) -> int:
    """SO_REUSEPORT worker pool: spawn ``n`` fresh server processes
    all bound to the same (host, port), restart any that die, fan out
    SIGTERM on shutdown. This is the CPU-attach scale-out (one asyncio
    loop saturates one core at ~6-8k req/s); the TPU is
    single-process-exclusive, so TPU scale-out is more chips on a DP
    mesh, not more processes — workers are pinned to CPU unless the
    operator overrides ``MLAPI_TPU_PLATFORM`` themselves."""
    import os
    import signal
    import subprocess
    import sys
    import time

    env = dict(os.environ, MLAPI_TPU_WORKER="1")
    if not env.get("MLAPI_TPU_PLATFORM"):
        env["MLAPI_TPU_PLATFORM"] = "cpu"
        _log.info(
            "--workers: pinning workers to CPU (MLAPI_TPU_PLATFORM=cpu); "
            "the TPU is single-process-exclusive — scale TPU serving "
            "with more chips, not more processes"
        )
    cmd = [
        sys.executable, "-m", "mlapi_tpu.serving",
        "--checkpoint", ckpt, "--host", args.host, "--port", str(args.port),
        "--max-wait-ms", str(args.max_wait_ms),
        *_forwarded_engine_flags(args),
    ]
    # systemd/docker stop the supervisor with SIGTERM; without a
    # handler the finally below never runs and the workers are
    # orphaned still bound to the port (SO_REUSEPORT would then let a
    # restarted service share it with the stale set, silently).
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    children = [subprocess.Popen(cmd, env=env) for _ in range(n)]
    spawned_at = [time.time()] * n
    restart_at = [0.0] * n   # earliest next respawn (backoff)
    backoff = [0.5] * n      # doubles on fast deaths, resets on survival
    fast_deaths = 0          # consecutive across ALL workers
    _log.info("spawned %d workers on %s:%d", n, args.host, args.port)
    try:
        while True:
            time.sleep(0.5)
            for i, c in enumerate(children):
                if c is None:
                    continue
                rc = c.poll()
                if rc is None:
                    continue
                lived = time.time() - spawned_at[i]
                if lived < 5.0:
                    # Died during/just after startup: back off — a
                    # persistent boot failure (bad checkpoint, bind
                    # error) must not crash-loop at full import cost.
                    fast_deaths += 1
                    backoff[i] = min(30.0, backoff[i] * 2)
                    if fast_deaths >= 3 * n:
                        _log.error(
                            "workers keep dying at startup (rc=%d); "
                            "giving up", rc,
                        )
                        return 1
                else:
                    fast_deaths = 0
                    backoff[i] = 0.5
                _log.warning(
                    "worker %d (pid %d) exited rc=%d after %.1fs; "
                    "restarting in %.1fs", i, c.pid, rc, lived, backoff[i],
                )
                restart_at[i] = time.time() + backoff[i]
                spawned_at[i] = time.time() + backoff[i]
                children[i] = None  # placeholder until respawn

            for i, c in enumerate(children):
                if c is None and time.time() >= restart_at[i]:
                    children[i] = subprocess.Popen(cmd, env=env)
                    spawned_at[i] = time.time()
    except KeyboardInterrupt:
        pass
    finally:
        # SIGTERM fan-out, then wait out the workers' DRAIN budget
        # (plus startup/teardown slack) before escalating to SIGKILL —
        # the supervisor must never cut a drain short that it also
        # configured.
        for c in children:
            if c is not None and c.poll() is None:
                c.send_signal(signal.SIGTERM)
        deadline = (
            time.time() + getattr(args, "drain_timeout_s", 10.0) + 5.0
        )
        for c in children:
            if c is None:
                continue
            try:
                c.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                c.kill()
    return 0


def _supervise_router(ckpt: str | None, args) -> int:
    """``--router`` topology: N full engine replicas (separate
    processes, each the whole r13 stack on its own port — ports
    ``--port``+1..N) under one prefix-affinity router serving the
    front ``--port`` in THIS process. Replica discovery speaks the
    same env convention as the multi-host rendezvous trio
    (``parallel/distributed.py``): the supervisor exports
    ``MLAPI_TPU_REPLICAS=host:p1,host:p2`` (+ per-child
    ``MLAPI_TPU_REPLICA_ID``) to everything it spawns, and a router
    over externally-launched replicas (other hosts, k8s pods) reads
    the same variable — or ``--replica-urls`` — instead of spawning.

    Replicas are pinned to CPU unless the operator overrides
    ``MLAPI_TPU_PLATFORM`` (same rule as ``--workers``: the TPU is
    single-process-exclusive — a TPU fleet is one replica per host
    with ``--replica-urls`` across hosts, not N processes on one
    chip). Dead replicas respawn with backoff; while one is down the
    router routes around it (HRW moves only ITS affinity slice) and
    the health poll folds it back in when it returns."""
    import os
    import signal as _signal
    import subprocess
    import sys
    import time

    from mlapi_tpu.parallel.distributed import (
        REPLICAS_ENV_VAR,
        replica_endpoints_from_env,
    )
    from mlapi_tpu.serving.router import Router, build_router_app

    # Role-split topology (r18): --prefill-replicas P --decode-replicas D
    # spawns P prefill-role + D decode-role replicas instead of
    # --replicas mixed ones. Pools size independently per role group
    # (--prefill-kv-pages / --decode-kv-pages override --kv-pages for
    # their group: prompt-only working sets vs prompt+generation).
    n_pre = getattr(args, "prefill_replicas", 0)
    n_dec = getattr(args, "decode_replicas", 0)
    roles: list | None = None
    if args.replica_urls:
        endpoints = replica_endpoints_from_env(args.replica_urls)
        spawn = False
    else:
        endpoints = replica_endpoints_from_env()  # $MLAPI_TPU_REPLICAS
        spawn = not endpoints
        if spawn:
            n = n_pre + n_dec if (n_pre or n_dec) else args.replicas
            endpoints = [
                (args.host, args.port + 1 + i) for i in range(n)
            ]
            if n_pre or n_dec:
                roles = ["prefill"] * n_pre + ["decode"] * n_dec
    if not endpoints:
        raise SystemExit("--router: no replica endpoints")
    env_spec = ",".join(f"{h}:{p}" for h, p in endpoints)

    cmds: list = []
    if spawn:
        base_env = dict(
            os.environ, MLAPI_TPU_REPLICA="1", **{REPLICAS_ENV_VAR: env_spec}
        )
        if not base_env.get("MLAPI_TPU_PLATFORM"):
            base_env["MLAPI_TPU_PLATFORM"] = "cpu"
            _log.info(
                "--router: pinning replicas to CPU (MLAPI_TPU_PLATFORM="
                "cpu); TPU fleets run one replica per host via "
                "--replica-urls"
            )
        for i, (h, p) in enumerate(endpoints):
            role_flags: list = []
            if roles is not None:
                role_flags += ["--replica-role", roles[i]]
                # Per-role pool sizing, appended AFTER the shared
                # flags so argparse's last-occurrence rule makes it
                # the group's --kv-pages override.
                per_role = (
                    getattr(args, "prefill_kv_pages", None)
                    if roles[i] == "prefill"
                    else getattr(args, "decode_kv_pages", None)
                )
                if per_role is not None:
                    role_flags += ["--kv-pages", str(per_role)]
            cmds.append(
                (
                    [
                        sys.executable, "-m", "mlapi_tpu.serving",
                        "--checkpoint", ckpt, "--host", h, "--port", str(p),
                        "--max-wait-ms", str(args.max_wait_ms),
                        *_forwarded_engine_flags(args),
                        *role_flags,
                    ],
                    dict(base_env, MLAPI_TPU_REPLICA_ID=str(i)),
                )
            )

    async def _run() -> int:
        router = Router(
            endpoints,
            policy=args.route_policy,
            affinity_prefix_bytes=args.affinity_prefix_bytes,
            health_poll_s=args.health_poll_s,
            queue_depth_limit=args.queue_depth_limit,
            # Gate routing on a passed health poll: a replica still
            # compiling its warmup grids must not eat traffic.
            assume_live=False,
            roles=roles,
        )
        # Per-model front routes mirror the replicas' own surface:
        # every --model id plus the implicit default entry (replicas
        # in multi-model mode serve /models/default/* too).
        mids = [
            spec.partition("=")[0].strip()
            for spec in (getattr(args, "model", None) or ())
        ]
        server = Server(
            build_router_app(
                router, model_ids=(["default"] + mids) if mids else None
            ),
            host=args.host, port=args.port,
        )
        stop_ev = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_ev.set)
            except (NotImplementedError, RuntimeError):
                pass

        # fork+exec through the executor: this loop IS the router's
        # serving loop, and Popen blocks the calling thread for the
        # whole spawn (mlapi-lint MLA008, caught r19). Startup has no
        # traffic yet, but the respawn loop below shares the shape —
        # one helper, both sites off the loop.
        def _spawn(i: int):
            return subprocess.Popen(cmds[i][0], env=cmds[i][1])

        children: list = [
            await loop.run_in_executor(None, _spawn, i)
            for i in range(len(cmds))
        ]
        spawned_at = [time.time()] * len(children)
        restart_at = [0.0] * len(children)
        backoff = [0.5] * len(children)

        async def _respawn_loop():
            # Same backoff discipline as the --workers supervisor, but
            # no global give-up: the router's whole job is serving on
            # the replicas that ARE up while a bad one crash-loops at
            # bounded cost.
            while True:
                await asyncio.sleep(0.5)
                for i, c in enumerate(children):
                    if c is not None and c.poll() is not None:
                        lived = time.time() - spawned_at[i]
                        backoff[i] = (
                            0.5 if lived >= 5.0
                            else min(30.0, backoff[i] * 2)
                        )
                        _log.warning(
                            "replica %d (pid %d) exited rc=%d after "
                            "%.1fs; respawning in %.1fs",
                            i, c.pid, c.returncode, lived, backoff[i],
                        )
                        restart_at[i] = time.time() + backoff[i]
                        children[i] = None
                    elif c is None and time.time() >= restart_at[i]:
                        # Respawn happens MID-TRAFFIC: the fork+exec
                        # must not stall in-flight relays (MLA008).
                        children[i] = await loop.run_in_executor(
                            None, _spawn, i
                        )
                        spawned_at[i] = time.time()

        respawn = None
        try:
            # Inside the try: a front server that fails to bind (port
            # taken) must still run the finally's SIGTERM fan-out —
            # never orphan N engine replicas behind a dead router.
            await server.start()
            _log.info(
                "router (%s) on %s:%d over replicas %s",
                args.route_policy, args.host, server.port, env_spec,
            )
            if spawn:
                respawn = asyncio.create_task(_respawn_loop())
            await stop_ev.wait()
        finally:
            if respawn is not None:
                respawn.cancel()
            # Drain the FLEET: fan SIGTERM to the replicas (each sheds
            # new work and drains under its own --drain-timeout-s)
            # while the router keeps relaying in-flight streams and
            # answering /healthz "degraded" — the layer above sees a
            # draining fleet, never connection-refused mid-stream.
            for c in children:
                if c is not None and c.poll() is None:
                    c.send_signal(_signal.SIGTERM)
            deadline = time.time() + args.drain_timeout_s + 5.0
            while time.time() < deadline and any(
                c is not None and c.poll() is None for c in children
            ):
                await asyncio.sleep(0.2)
            for c in children:
                if c is not None and c.poll() is None:
                    c.kill()
            await server.stop()
        return 0

    try:
        return asyncio.run(_run())
    except KeyboardInterrupt:
        return 0


def main(argv=None) -> None:
    from mlapi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    parser = argparse.ArgumentParser("mlapi_tpu.serving")
    parser.add_argument("--checkpoint", help="committed checkpoint dir")
    parser.add_argument(
        "--demo-iris", action="store_true", help="train Iris now and serve it"
    )
    parser.add_argument(
        "--model", action="append", metavar="ID=CHECKPOINT",
        help="multi-model serving (repeatable): ADD model ID from "
             "CHECKPOINT to this process's registry, served at "
             "/models/ID/{generate|predict}. --checkpoint stays the "
             "DEFAULT model (id 'default', owns the legacy /generate "
             "and /predict routes). Generative entries get their own "
             "BatchRun lanes; classification/recsys entries get the "
             "scoring fast path — formed micro-batches ride the "
             "first generative entry's unit scheduler as typed "
             "'score' units between decode chunks (one HBM, one "
             "dispatch thread, one policy). Watch model.<id>.* on "
             "/metrics",
    )
    parser.add_argument(
        "--tenant-pages", action="append", metavar="TENANT=N",
        help="per-tenant KV page quota (repeatable; paged engines): "
             "a tenant holding reservations may not grow past N "
             "pages — further group starts defer (counted in "
             "generate.sched_tenant_pages_deferred and "
             "tenant.<t>.deferrals) until its own pages free. "
             "Unlisted tenants are unquotaed",
    )
    parser.add_argument(
        "--tenant-slots", action="append", metavar="TENANT=N",
        help="per-tenant adapter-slot quota (repeatable; with "
             "--adapter-slots): same deferral discipline as "
             "--tenant-pages, over device adapter slots",
    )
    parser.add_argument(
        "--tenant-weight", action="append", metavar="TENANT=W",
        help="per-tenant scheduling weight (repeatable; default "
             "1.0): deadline slack divides by W in the unit "
             "scheduler's pick policy, so a weight-2 tenant's "
             "requests look twice as urgent at equal slack. "
             "Starvation-safe: alternation floors still apply",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument(
        "--max-wait-ms", type=float, default=0.2, help="micro-batch window"
    )
    parser.add_argument(
        "--workers", type=int, default=1,
        help="number of SO_REUSEPORT server processes (CPU-attach "
             "scale-out; needs an explicit --port)",
    )
    parser.add_argument(
        "--router", action="store_true",
        help="scale-out topology: spawn --replicas full engine "
             "replicas (separate processes on ports --port+1..N) and "
             "serve a prefix-affinity front-end router on --port — "
             "repeated prompt prefixes land on the replica whose "
             "pool pages / kv-tier blobs are already warm "
             "(rendezvous hashing; power-of-two-choices fallback when "
             "the preferred replica sheds/drains/overloads). With "
             "--replica-urls (or $MLAPI_TPU_REPLICAS) the router "
             "mounts over externally-launched replicas instead of "
             "spawning",
    )
    parser.add_argument(
        "--replicas", type=int, default=2,
        help="with --router: how many engine replica processes to "
             "spawn (default 2)",
    )
    parser.add_argument(
        "--replica-role", choices=["prefill", "decode", "mixed"],
        default="mixed",
        help="prefill/decode disaggregation role (r18, generative "
             "checkpoints): 'prefill' replicas take the first hop of "
             "role-split generative traffic — they run the prompt's "
             "chunked prefill and PUSH each finished chunk's KV to "
             "the decode replica the router named (POST /kv/push, "
             "the r17 blob wire format at chunk granularity); "
             "'decode' replicas stage pushed chunks and activate the "
             "stream with ZERO local prefill FLOPs the moment the "
             "last chunk lands (generate.kv_push_applied moves while "
             "prefix_builds and prefill_chunks stay flat). 'mixed' "
             "(default) serves both phases — an all-mixed fleet is "
             "bit-identical to the flag never existing. Roles "
             "specialize routing and pool sizing, not capability: "
             "either role still serves a plain /generate end to end "
             "(the router's role-starved fallback)",
    )
    parser.add_argument(
        "--prefill-replicas", type=int, default=0,
        help="with --router: spawn this many PREFILL-role replicas "
             "(combined with --decode-replicas, replaces --replicas; "
             "ports still derive from --port). The router sends new "
             "generative requests to the prefill pool (p2c by load) "
             "and the stream to the HRW-chosen decode replica; a "
             "role-starved fleet degrades to mixed routing, counted "
             "in router.role_fallback_mixed",
    )
    parser.add_argument(
        "--decode-replicas", type=int, default=0,
        help="with --router: spawn this many DECODE-role replicas "
             "(see --prefill-replicas)",
    )
    parser.add_argument(
        "--prefill-kv-pages", type=int, default=None,
        help="with --prefill-replicas and --kv-page-size: the "
             "prefill pool's --kv-pages override — prefill replicas "
             "hold prompt-only working sets (no generation tail), so "
             "their pool sizes independently of the decode pool's",
    )
    parser.add_argument(
        "--decode-kv-pages", type=int, default=None,
        help="with --decode-replicas and --kv-page-size: the decode "
             "pool's --kv-pages override (prompt + generation "
             "working sets)",
    )
    parser.add_argument(
        "--replica-urls", default=None,
        help="with --router: comma-separated host:port replica "
             "endpoints to route over instead of spawning (multi-host "
             "fleets; same format as $MLAPI_TPU_REPLICAS)",
    )
    parser.add_argument(
        "--affinity-prefix-bytes", type=int, default=64,
        help="with --router: how many leading BYTES of the request's "
             "prompt prefix (the 'prefix' field when present, else "
             "'text') feed the rendezvous hash — the affinity key. "
             "The router never tokenizes",
    )
    parser.add_argument(
        "--route-policy", choices=["affinity", "round_robin"],
        default="affinity",
        help="with --router: 'affinity' (prefix-hash rendezvous "
             "routing, the default) or 'round_robin' (the A/B "
             "baseline the bench compares against — every replica "
             "rebuilds every prefix)",
    )
    parser.add_argument(
        "--health-poll-s", type=float, default=0.5,
        help="with --router: per-replica /healthz + /metrics poll "
             "cadence (liveness, draining, queue depth)",
    )
    parser.add_argument(
        "--queue-depth-limit", type=int, default=None,
        help="with --router: a replica whose scraped queue depth plus "
             "router-side in-flight exceeds this is skipped by "
             "routing until it recedes (default: no limit — replica "
             "admission control sheds instead)",
    )
    parser.add_argument(
        "--quantize", choices=["int8"], default=None,
        help="weight-only quantization at load: half the parameter "
             "HBM, dequantization fused into each matmul "
             "(single-chip serving only)",
    )
    parser.add_argument(
        "--kv-quant", choices=["int8"], default=None,
        help="store decode KV caches as int8 payload + per-token-"
             "per-head f32 scales: ~2x less decode HBM per cached "
             "token, ~2x the cache/prefix/slot budget; quantize "
             "fused into the append, dequantize into the attention "
             "read. Generative checkpoints only; composes with "
             "--quantize and --mesh-shape (the draft's cache rides "
             "the same format)",
    )
    parser.add_argument(
        "--decode-attn-impl", choices=["einsum", "flash"], default=None,
        help="decode-step attention: 'einsum' (reference oracle; "
             "dequantizes an int8 cache at the read seam) or 'flash' "
             "(Pallas split-K flash-decode kernel that reads int8 "
             "cache tiles IN-kernel — the --kv-quant byte saving "
             "reaches the decode read, not just storage). Generative "
             "checkpoints only; the draft, if any, rides the same "
             "impl",
    )
    parser.add_argument(
        "--kv-page-size", type=int, default=None,
        help="paged KV cache: allocate decode caches as fixed-size "
             "pages of this many tokens from a device-resident pool "
             "(page tables per sequence) instead of contiguous "
             "per-slot tier buffers — near-zero padding waste, "
             "ref-counted shared prefix pages with copy-on-write, "
             "O(table) batch growth/compaction. Token streams are "
             "pinned identical to contiguous allocation; composes "
             "with --kv-quant and --decode-attn-impl flash (the "
             "kernel reads pages via a page-table index map). "
             "Generative checkpoints only",
    )
    parser.add_argument(
        "--kv-pages", type=int, default=None,
        help="with --kv-page-size: total pool pages (default: the "
             "contiguous-equivalent budget — max_batch slots at the "
             "default cache tier). A full pool rejects loudly; watch "
             "generate.kv_page_utilization on /metrics",
    )
    parser.add_argument(
        "--kv-tier-bytes", type=int, default=0,
        help="hierarchical KV tier: keep up to this many bytes of "
             "EVICTED prefix KV page sets in host RAM (LRU), in their "
             "stored format (--kv-quant int8 halves the spill "
             "bandwidth) — a re-arrival restores by device_put with "
             "zero prefill FLOPs instead of paying a cold prefill; "
             "streams are pinned token-identical across evict+restore "
             "vs never-evicted. Multiplies the effective prefix "
             "budget by the host-RAM/HBM ratio. 0 (default) disables "
             "the tier: evictions discard as before. Watch "
             "generate.kv_prefix_restore_hits / kv_tier_bytes_in_use "
             "on /metrics. Generative checkpoints only",
    )
    parser.add_argument(
        "--kv-tier-disk-dir", default=None,
        help="with --kv-tier-bytes: back the tier's blob payloads "
             "with .npz files under this directory (only the index "
             "stays in RAM; the bytes budget then bounds disk use). "
             "Files are per-process and inert across restarts — a "
             "stale blob that no longer matches the live pool "
             "geometry is dropped, never restored wrong",
    )
    parser.add_argument(
        "--kv-peer-fetch", action="store_true", default=False,
        help="peer-to-peer prefix-KV fetch between router replicas: "
             "serve this replica's warm prefix blobs on GET "
             "/kv/prefix (stored format — int8 KV crosses the wire "
             "at half the bytes) and, on a local miss, fetch the "
             "blob from the replica the router's x-mlapi-warm-peer "
             "hint names instead of cold-prefilling — a failover, "
             "drain, or depth overflow costs one host-to-host copy, "
             "not an O(P^2) re-prefill. Off (default): bit-identical "
             "to r16. Watch generate.kv_peer_fetch_hits / "
             "kv_peer_serve_bytes on /metrics. Generative "
             "checkpoints only",
    )
    parser.add_argument(
        "--prefill-page-native", action=argparse.BooleanOptionalAction,
        default=True,
        help="with --kv-page-size: prefill writes K/V straight into "
             "pool pages through the page table (default) — the "
             "contiguous-then-adopt copy drops to exactly zero bytes "
             "(generate.prefill_adopt_bytes reads 0). "
             "--no-prefill-page-native keeps the r09 adopt path for "
             "comparison; token streams are pinned identical either "
             "way",
    )
    parser.add_argument(
        "--prefill-interleave", action=argparse.BooleanOptionalAction,
        default=True,
        help="with --kv-page-size: a long prompt admitted into a "
             "running batch prefills as chunked dispatches "
             "interleaved one-for-one with decode chunks (default) — "
             "in-flight streams stall by at most ONE prefill-chunk "
             "dispatch instead of the whole prompt "
             "(generate.interleave_max_stall pins the bound). "
             "--no-prefill-interleave defers long joiners to their "
             "own batch",
    )
    # r22: `--no-scheduler` retired on schedule (deprecated r20,
    # kept one release r21). The scheduler IS the execution model;
    # the one thing the flag still did — pin a single lane — is
    # `--sched-max-batches 1`, same machinery, same token streams.
    # Passing the dead flag now errors at parse, which is the
    # scheduled removal behaving exactly like the r21 retirements.
    parser.add_argument(
        "--sched-max-batches", type=int, default=2,
        help="how many batches may be live at once (lanes); 1 pins "
             "the legacy serial semantics on the same machinery "
             "(what --no-scheduler, retired r22, used to do). Paged "
             "engines additionally gate new lanes on the pool's "
             "free-page budget (generate.sched_pages_deferred counts "
             "waits)",
    )
    parser.add_argument(
        "--draft-checkpoint", default=None,
        help="speculative decoding: a smaller same-tokenizer "
             "checkpoint whose proposals the target verifies in one "
             "block — speeds up single-stream greedy generation",
    )
    parser.add_argument(
        "--spec-sample", action="store_true",
        help="with --draft-checkpoint: also speculate SAMPLED "
             "(temperature > 0) single-stream requests via "
             "acceptance-rejection — exact target distribution, but "
             "streams under concurrent admission churn are not "
             "byte-reproducible per seed (solo runs are)",
    )
    parser.add_argument(
        "--adapter-slots", type=int, default=0,
        help="many-adapter LoRA serving: device-resident (A, B) slot "
             "pool size — up to this many tenants' adapters resident "
             "in HBM at once over the ONE shared base model "
             "(HBM cost: base + N x generate.adapter_slot_bytes). "
             "Requests name tenants via the 'adapter' field; mixed-"
             "tenant batches apply per-row deltas via gathered BGMV. "
             "0 (default) disables the subsystem entirely. "
             "Generative checkpoints only",
    )
    parser.add_argument(
        "--adapter-store-bytes", type=int, default=0,
        help="with --adapter-slots: host-side adapter store LRU "
             "byte budget (default 256 MiB when unset) — evicted "
             "device slots refill from here without a peer fetch",
    )
    parser.add_argument(
        "--adapter-disk-dir", default=None,
        help="with --adapter-slots: spill directory for the host "
             "adapter store (same pid-scoped blob discipline as "
             "--kv-tier-disk-dir)",
    )
    parser.add_argument(
        "--adapter", action="append", default=None, metavar="ID=PATH",
        help="preload an adapter into the host store at startup "
             "(repeatable): PATH is an exported adapter file "
             "(models/lora.py export_adapter wire format) registered "
             "under ID — the file's embedded id must match",
    )
    parser.add_argument(
        "--default-deadline-ms", type=float, default=None,
        help="end-to-end wall-clock budget applied to requests that "
             "name no deadline_ms of their own: expiry at any "
             "dispatch boundary (queue wait, prefill chunk, decode "
             "chunk, spec round) ends the request with a "
             "deadline_exceeded terminal frame (504 unary). Default: "
             "no deadline",
    )
    parser.add_argument(
        "--admission-control", action=argparse.BooleanOptionalAction,
        default=True,
        help="SLO-aware admission: estimate queue-wait + TTFT from "
             "the live p95 reservoirs and shed deadlined requests "
             "that cannot finish in time at the door (503 + computed "
             "retry-after); sustained queue pressure engages the "
             "brownout ladder (clamp max_new_tokens, suppress "
             "speculation, evict idle prefix pages) before shedding. "
             "--no-admission-control disables the estimate and the "
             "ladder (deadlines still enforce)",
    )
    parser.add_argument(
        "--drain-timeout-s", type=float, default=10.0,
        help="graceful-drain budget on shutdown (SIGTERM/SIGINT): new "
             "admissions shed 503 and /healthz reports \"draining\" "
             "while in-flight streams run to completion; streams "
             "still live after the budget are cancelled with proper "
             "terminal frames. The --workers supervisor waits this "
             "long after SIGTERM before SIGKILL",
    )
    parser.add_argument(
        "--mesh-shape", default=None,
        help="serve sharded over a (data, model) device mesh, e.g. "
             "'1,4' or '2,4' — params follow the model's declared TP "
             "layout (classification AND generative engines; the "
             "draft, if any, rides the same mesh). Shape must cover "
             "the visible devices",
    )
    parser.add_argument(
        "--profiler-port", type=int, default=0,
        help="start a jax.profiler server on this port (XProf/TensorBoard "
             "can attach live)",
    )
    parser.add_argument(
        "--reload", action="store_true",
        help="dev loop: restart the server when package sources change",
    )
    args = parser.parse_args(argv)

    if args.reload:
        import os
        import sys

        if os.environ.get("MLAPI_TPU_RELOAD_CHILD") != "1":
            sys.exit(
                _watch_and_reexec(argv if argv is not None else sys.argv[1:])
            )

    if args.profiler_port:
        import jax.profiler

        jax.profiler.start_server(args.profiler_port)
        _log.info("jax profiler server on port %d", args.profiler_port)

    import os
    import sys

    # A router over external replicas spawns no engine of its own —
    # the only mode that needs no checkpoint.
    router_external = args.router and bool(
        args.replica_urls or os.environ.get("MLAPI_TPU_REPLICAS")
    )
    if not args.checkpoint and not args.demo_iris and not router_external:
        parser.error("need --checkpoint or --demo-iris")
    if args.kv_tier_disk_dir and not args.kv_tier_bytes:
        # Validate BEFORE a topology supervisor forks: the same
        # mis-pair must be equally loud in every mode (the engine
        # would reject it anyway, but only inside each child).
        parser.error("--kv-tier-disk-dir requires --kv-tier-bytes > 0")
    if (
        args.adapter_store_bytes or args.adapter_disk_dir or args.adapter
    ) and not args.adapter_slots:
        # Same before-the-fork loudness as the kv-tier mis-pair: the
        # engine rejects it anyway, but only inside each child.
        parser.error(
            "--adapter-store-bytes/--adapter-disk-dir/--adapter "
            "require --adapter-slots > 0"
        )
    if args.router and args.workers > 1:
        parser.error(
            "--router and --workers are different topologies (distinct "
            "ports with affinity vs one shared port); pick one"
        )
    if (args.prefill_replicas or args.decode_replicas) and not args.router:
        parser.error(
            "--prefill-replicas/--decode-replicas describe a --router "
            "topology; without the router nothing routes by role"
        )
    if (args.prefill_replicas or args.decode_replicas) and (
        args.replica_urls or os.environ.get("MLAPI_TPU_REPLICAS")
    ):
        parser.error(
            "--prefill-replicas/--decode-replicas spawn the role "
            "topology; over an external fleet (--replica-urls / "
            "$MLAPI_TPU_REPLICAS) launch the replicas with "
            "--replica-role yourself"
        )
    if (
        args.prefill_kv_pages or args.decode_kv_pages
    ) and not args.kv_page_size:
        # Same before-the-fork loudness as the tier mis-pair below.
        parser.error(
            "--prefill-kv-pages/--decode-kv-pages require "
            "--kv-page-size (they size the paged pool per role group)"
        )
    if router_external:
        ckpt = args.checkpoint
    else:
        ckpt = args.checkpoint or _demo_iris_checkpoint()

    is_worker = os.environ.get("MLAPI_TPU_WORKER") == "1"
    is_replica = os.environ.get("MLAPI_TPU_REPLICA") == "1"
    if args.router and not is_replica:
        if args.port == 0 and not router_external:
            parser.error("--router needs an explicit --port (replica "
                         "ports derive from it: --port+1..N)")
        sys.exit(_supervise_router(ckpt, args))
    if args.workers > 1 and not is_worker:
        if args.port == 0:
            parser.error("--workers needs an explicit --port "
                         "(every worker binds the same one)")
        sys.exit(_supervise_workers(args.workers, ckpt, args))

    # Multi-host bootstrap, parity with train/__main__:47 (a no-op on
    # a plain single host): a multi-host serving deployment exports
    # the same MLAPI_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID trio and
    # every process joins the rendezvous BEFORE touching devices —
    # jax.devices() below then spans the pod, so --mesh-shape can name
    # a global mesh. NOT in --workers children: the SO_REUSEPORT pool
    # is single-host CPU scale-out and every child inherits the SAME
    # PROCESS_ID — N workers claiming one rendezvous slot would wedge
    # the pool (a worker is a replica, not a pod rank). Same for
    # --router replica children: the HTTP replica set is its OWN
    # discovery plane ($MLAPI_TPU_REPLICAS), not pod ranks.
    if not is_worker and not is_replica:
        from mlapi_tpu.parallel import initialize_from_env

        initialize_from_env()

    mesh = None
    if args.mesh_shape:
        import math

        import jax

        from mlapi_tpu.parallel import create_mesh

        try:
            shape = tuple(int(d) for d in args.mesh_shape.split(","))
        except ValueError:
            parser.error(
                f"--mesh-shape {args.mesh_shape!r} is not a "
                "comma-separated list of integers (e.g. '1,4')"
            )
        if not shape or any(d < 1 for d in shape):
            parser.error(
                f"--mesh-shape {args.mesh_shape!r}: every dimension "
                "must be a positive integer"
            )
        need = math.prod(shape)
        devices = jax.devices()
        if need > len(devices):
            parser.error(
                f"--mesh-shape {args.mesh_shape} needs {need} devices; "
                f"{len(devices)} visible"
            )
        # A shape smaller than the host's device count serves on the
        # first `need` devices (e.g. a (1,4) TP mesh on an 8-device
        # host) — the deployment decides the slice, not the host size.
        mesh = create_mesh(shape, devices=devices[:need])
    engine = InferenceEngine.from_checkpoint(
        ckpt, quantize=args.quantize,
        kv_quant=args.kv_quant,
        decode_attn_impl=args.decode_attn_impl,
        kv_page_size=args.kv_page_size,
        kv_pages=args.kv_pages,
        prefill_page_native=args.prefill_page_native,
        prefill_interleave=args.prefill_interleave,
        kv_tier_bytes=args.kv_tier_bytes,
        kv_tier_disk_dir=args.kv_tier_disk_dir,
        kv_peer_fetch=args.kv_peer_fetch,
        replica_role=args.replica_role,
        draft_checkpoint=args.draft_checkpoint,
        spec_sample=args.spec_sample,
        sched_max_batches=args.sched_max_batches,
        adapter_slots=args.adapter_slots,
        adapter_store_bytes=args.adapter_store_bytes,
        adapter_disk_dir=args.adapter_disk_dir,
        mesh=mesh,
    )
    for spec in args.adapter or ():
        # Startup preload: ID=PATH into the host store (device slots
        # install lazily, at the first request naming the tenant).
        from mlapi_tpu.serving.adapter_store import load_adapter

        aid, _, path = spec.partition("=")
        if not aid or not path:
            parser.error(f"--adapter {spec!r}: expected ID=PATH")
        try:
            file_aid, payload, rank, nbytes = load_adapter(path)
        except (OSError, ValueError) as e:
            parser.error(f"--adapter {spec!r}: {e}")
        if file_aid != aid:
            parser.error(
                f"--adapter {spec!r}: file embeds adapter id "
                f"{file_aid!r} — ids must match (rename the export, "
                "not the flag)"
            )
        engine.register_adapter(aid, payload)
        _log.info(
            "preloaded adapter %r (rank %d, %d bytes)", aid, rank, nbytes
        )
    models = None
    if args.model:
        # Multi-model registry: --checkpoint is the default entry;
        # each --model ID=CHECKPOINT adds one. Extra entries load
        # with stock engine knobs — the tuned flags (--kv-page-size,
        # --quantize, ...) configure the DEFAULT model; per-entry
        # tuning is a config file's job, not a flag matrix's.
        import re as _re

        from mlapi_tpu.serving.registry import ModelRegistry

        engines = {"default": engine}
        for spec in args.model:
            mid, _, mpath = spec.partition("=")
            mid = mid.strip()
            if not mid or not mpath:
                parser.error(f"--model {spec!r}: expected ID=CHECKPOINT")
            if not _re.fullmatch(r"[A-Za-z0-9._-]+", mid):
                parser.error(
                    f"--model {spec!r}: id must be URL-path-safe "
                    "([A-Za-z0-9._-]+)"
                )
            if mid in engines:
                parser.error(f"--model {spec!r}: duplicate model id")
            try:
                engines[mid] = InferenceEngine.from_checkpoint(mpath)
            except (OSError, ValueError) as e:
                parser.error(f"--model {spec!r}: {e}")
        models = ModelRegistry(engines)
    tenants = None
    if args.tenant_pages or args.tenant_slots or args.tenant_weight:
        from mlapi_tpu.serving.registry import TenantLedger, parse_tenant_kv

        try:
            tenants = TenantLedger(
                quota_pages=parse_tenant_kv(
                    args.tenant_pages, "--tenant-pages"
                ),
                quota_slots=parse_tenant_kv(
                    args.tenant_slots, "--tenant-slots"
                ),
                weights=parse_tenant_kv(
                    args.tenant_weight, "--tenant-weight", cast=float
                ),
            )
        except ValueError as e:
            parser.error(str(e))
    app = build_app(
        engine, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        default_deadline_ms=args.default_deadline_ms,
        drain_timeout_s=args.drain_timeout_s,
        admission_control=args.admission_control,
        models=models, tenants=tenants,
    )
    server = Server(app, host=args.host, port=args.port,
                    reuse_port=is_worker)

    async def _serve_until_signalled():
        # SIGTERM (systemd/docker stop, the --workers supervisor) and
        # SIGINT take the GRACEFUL path: stop accepting, run the
        # app's shutdown hooks — which drain in-flight streams under
        # --drain-timeout-s before the hard stop — then exit. Without
        # this, SIGTERM killed the process mid-decode and every live
        # stream ended as a dropped connection.
        import signal as _signal

        loop = asyncio.get_running_loop()
        stop_ev = asyncio.Event()
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_ev.set)
            except (NotImplementedError, RuntimeError):
                pass  # non-main thread / platforms without support
        await server.start()
        await stop_ev.wait()
        _log.info(
            "shutdown signal: draining (budget %.1fs)",
            args.drain_timeout_s,
        )
        # Drain with the LISTENER STILL OPEN: for the whole budget the
        # load balancer's /healthz polls see "draining" and late
        # arrivals shed 503 + retry-after — not connection-refused.
        # Closing first would make both unreachable and (on runtimes
        # whose wait_closed waits out open handlers) let a long stream
        # outlive the budget into the supervisor's SIGKILL.
        target = app.state.get("batcher") or engine
        drain = getattr(target, "drain", None)
        if drain is not None:
            try:
                await drain(args.drain_timeout_s)
            except Exception:
                _log.exception("drain failed; hard stop follows")
        # Already drained, so the shutdown hook's own drain() returns
        # immediately — this closes the listener and stops the engine.
        await server.stop()

    try:
        asyncio.run(_serve_until_signalled())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
