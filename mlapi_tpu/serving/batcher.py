"""Asyncio micro-batcher: coalesce concurrent requests into one
device call.

The throughput half of the north-star metric (requests/sec/chip,
``BASELINE.json:2``) is won here: N concurrent ``/predict`` requests
become ≤ ceil(N / max_batch) TPU dispatches instead of N. Mechanism:

- ``submit(row)`` parks a future on an asyncio queue.
- A collector task takes the first queued item, then drains up to
  ``max_batch`` items, waiting at most ``max_wait_ms`` for stragglers
  (the window trades a bounded p50 hit for batching win; 0 disables
  waiting for the latency-critical case).
- Batches run on a small executor pool with up to ``max_inflight``
  batches in flight at once. Device dispatch never blocks the event
  loop, and — crucially when the chip sits behind a network tunnel
  where one call's latency is dominated by the wire — round trips
  overlap, so throughput is ``max_inflight × max_batch`` per
  round-trip time instead of one batch per round trip.

The reference has no batching — each request does its own
pickle-load + two matmuls inline on the event loop (``main.py:19-22``).
"""

from __future__ import annotations

import asyncio
import queue
import threading

import numpy as np

from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.batcher")


class _WorkerPool:
    """Reusable daemon worker threads that heal around wedged device
    calls: ``submit`` hands work to an idle worker, or spawns a fresh
    one when none is idle. A worker stuck inside a device call (lost
    transport RPC) simply never returns to the idle set — it is out of
    circulation, and the next batch gets a new thread — which keeps
    the original per-batch-thread recovery property without paying a
    thread start per batch (~50 µs each, ~20% of event-loop time at
    full load). Steady-state thread count equals peak concurrent
    batches (≤ the batcher's max_inflight)."""

    def __init__(self, name: str):
        self._name = name
        self._work: queue.SimpleQueue = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._idle = 0
        self._spawned = 0

    def submit(self, fn) -> None:
        with self._lock:
            spawn = self._idle == 0
            if spawn:
                self._spawned += 1
                n = self._spawned
            else:
                self._idle -= 1
            work = self._work
        if spawn:
            threading.Thread(
                target=self._run, args=(work,),
                name=f"{self._name}-{n}", daemon=True,
            ).start()
        work.put(fn)

    def close(self) -> None:
        """Release every live worker. Workers are bound to the queue
        they were spawned with; swapping in a fresh queue makes stale
        sentinels (destined for forever-wedged workers) and any stale
        work die with the old queue instead of poisoning a restarted
        pool."""
        with self._lock:
            n = self._spawned
            self._spawned = 0
            self._idle = 0
            old = self._work
            self._work = queue.SimpleQueue()
        for _ in range(n):
            old.put(None)

    def _run(self, work: queue.SimpleQueue) -> None:
        while True:
            fn = work.get()
            if fn is None:
                return  # pool closed
            try:
                fn()
            except Exception:  # noqa: BLE001 — workers must survive
                _log.exception("dispatch worker error")
            finally:
                with self._lock:
                    if work is self._work:
                        self._idle += 1
                    else:
                        return  # pool closed while we were busy


class OverloadedError(Exception):
    """The serving queue is full: shed the request NOW (503 +
    ``Retry-After``) instead of parking it on an ever-growing queue
    where it would time out after adding to the overload. Raised by
    both engines' ``submit``; the app converts it to HTTP."""

    def __init__(self, what: str, retry_after_s: float = 1.0,
                 detail: str | None = None):
        # ``detail`` overrides the classic queue-full message for the
        # other shed reasons (draining, infeasible deadline) that ride
        # the same 503 + Retry-After path.
        super().__init__(detail or f"{what} queue full")
        self.retry_after_s = retry_after_s


class MicroBatcher:
    """Coalesces single-row predict requests into batched engine calls."""

    def __init__(
        self,
        engine,
        *,
        max_batch: int | None = None,
        max_wait_ms: float = 0.2,
        max_queue: int = 8192,
        max_inflight: int = 16,
        dispatch_timeout_s: float = 30.0,
        default_deadline_ms: float | None = None,
    ):
        self.engine = engine
        self.max_batch = min(max_batch or engine.max_batch, engine.max_batch)
        self.max_wait_s = max_wait_ms / 1e3
        self.max_inflight = max_inflight
        self.dispatch_timeout_s = dispatch_timeout_s
        # Wall-clock budget applied when a request names none (None =
        # no deadline): classification's one dispatch boundary is the
        # queue→batch handoff, where expired entries fail with
        # DeadlineExceeded (504) instead of burning device time.
        self.default_deadline_ms = default_deadline_ms
        # Graceful drain: submit sheds while True; in-flight batches
        # finish (their resolvers set results), the queue empties.
        self.draining = False
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_queue)
        # True while the collect loop holds popped rows it has not
        # yet dispatched (the straggler window): those rows are in
        # neither the queue nor ``inflight``, and drain() must treat
        # the window as live work or it can declare the batcher idle
        # with a batch still forming.
        self._collecting = False
        self._inflight: asyncio.Semaphore | None = None
        self._task: asyncio.Task | None = None
        self._resolvers: set[asyncio.Task] = set()
        self._pool = _WorkerPool("tpu-dispatch")
        # Stats (read by /metrics and the coalescing test).
        self.device_calls = 0
        self.requests = 0
        self.timeouts = 0
        self.rejected = 0
        self.inflight = 0
        self.shed_draining = 0
        self.deadline_expired = 0
        # Fleet backlog a fronting router last stamped on a forwarded
        # request (x-mlapi-router-depth; 0 direct) — classification
        # replicas surface the same backpressure gauge the generative
        # engine feeds into its admission estimate (r15).
        self.router_queue_depth = 0

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    async def start(self) -> None:
        if self._task is None:
            self._inflight = asyncio.Semaphore(self.max_inflight)
            self._task = asyncio.create_task(self._collect_loop(), name="microbatcher")

    async def stop(self) -> None:
        """Graceful shutdown: no awaiting ``submit()`` may hang.

        In-flight batches are allowed to finish (their resolvers set
        results); anything still queued gets a clean exception.
        """
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._resolvers:
            await asyncio.gather(*list(self._resolvers), return_exceptions=True)
        self._pool.close()  # release idle dispatch workers
        while not self._queue.empty():
            _, fut, _ = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("batcher stopped"))

    async def drain(self, timeout_s: float = 10.0) -> None:
        """Graceful drain: shed new submits (503 + retry-after), let
        queued and in-flight batches finish inside the budget; when
        the budget runs out, anything still QUEUED sheds with the
        same documented 503 + retry-after (``stop()`` would fail it
        with an opaque RuntimeError → 500), while dispatched batches
        are left to resolve — late but clean."""
        self.draining = True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(0.0, timeout_s)
        while loop.time() < deadline:
            if (
                self._queue.empty()
                and self.inflight == 0
                and not self._collecting
            ):
                return
            await asyncio.sleep(0.05)
        while not self._queue.empty():
            _, fut, _ = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(OverloadedError(
                    "predict", retry_after_s=5.0,
                    detail="drain budget exhausted: retry against "
                           "another replica",
                ))

    async def submit(
        self, row: np.ndarray, *, deadline_ms: float | None = None
    ) -> tuple[str, float]:
        """Queue one feature row; resolves to (label, probability).

        Raises :class:`OverloadedError` immediately when the queue is
        full — under overload, fast-fail beats queueing: a blocked
        ``put`` here would grow latency without bound while every
        queued request eventually times out anyway."""
        if self._task is None:
            raise RuntimeError("batcher not started")
        loop = asyncio.get_running_loop()
        if self.draining:
            self.shed_draining += 1
            self.rejected += 1
            raise OverloadedError(
                "predict", retry_after_s=5.0,
                detail="server draining: retry against another replica",
            )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (
            loop.time() + deadline_ms / 1e3 if deadline_ms else None
        )
        fut: asyncio.Future = loop.create_future()
        try:
            self._queue.put_nowait(
                (np.asarray(row, np.float32), fut, deadline)
            )
        except asyncio.QueueFull:
            self.rejected += 1
            raise OverloadedError("predict") from None
        self.requests += 1
        return await fut

    async def _collect_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            # Acquire the in-flight slot BEFORE collecting: while every
            # slot is busy, arrivals pile up in the queue, and the slot
            # that frees drains them as ONE large batch. Collecting
            # first (the old order) froze each batch at whatever the
            # 0.2 ms straggler window caught — under closed-loop load
            # that meant many ~32-row batches queueing behind the
            # slots: measured on the real TPU tunnel at concurrency
            # 512, the reorder alone took 1.6k → 4.0k req/s with
            # loaded p50 283 → 111 ms; slot-first + 16 slots measured
            # 5.5k req/s at concurrency 1024 with an out-of-process
            # load generator (4.6k through bench.py, whose generator
            # shares this 1-core box with the server — event-loop
            # bound either way).
            await self._inflight.acquire()
            rows = []
            try:
                rows.append(await self._queue.get())
                # No await between the pop resuming and this flag, so
                # drain() can never observe the popped row in neither
                # the queue nor the collection window.
                self._collecting = True
                if self.max_wait_s > 0:
                    deadline = loop.time() + self.max_wait_s
                    while len(rows) < self.max_batch:
                        timeout = deadline - loop.time()
                        if timeout <= 0:
                            break
                        try:
                            rows.append(
                                await asyncio.wait_for(
                                    self._queue.get(), timeout
                                )
                            )
                        except asyncio.TimeoutError:
                            break
                else:
                    while (
                        len(rows) < self.max_batch
                        and not self._queue.empty()
                    ):
                        rows.append(self._queue.get_nowait())
            except asyncio.CancelledError:
                # stop() cancelled us mid-collection: rows already
                # popped are no longer in the queue, so stop()'s drain
                # can't see them — fail their futures here or their
                # submit() callers hang forever.
                self._collecting = False
                for _, fut, _ in rows:
                    if not fut.done():
                        fut.set_exception(RuntimeError("batcher stopped"))
                raise

            # Deadline check at the ONE dispatch boundary this path
            # owns (queue → device batch): entries whose wall-clock
            # budget passed while queued fail with DeadlineExceeded
            # (504) instead of occupying batch rows.
            now = loop.time()
            expired = [
                f for _, f, d in rows if d is not None and now > d
            ]
            if expired:
                from mlapi_tpu.serving.requests import DeadlineExceeded

                self.deadline_expired += len(expired)
                for f in expired:
                    if not f.done():
                        f.set_exception(DeadlineExceeded("queued"))
                rows = [
                    rf for rf in rows
                    if rf[2] is None or now <= rf[2]
                ]
                if not rows:
                    self._inflight.release()
                    self._collecting = False
                    continue

            batch = np.stack([r for r, _, _ in rows])
            futures = [f for _, f, _ in rows]
            # Fire the batch without awaiting its completion: up to
            # max_inflight device round trips overlap, while this loop
            # goes straight back to collecting the next batch.
            self.inflight += 1
            self._collecting = False  # rows now covered by inflight
            work = self._dispatch_thread(loop, batch)
            resolver = asyncio.create_task(self._resolve(work, futures))
            self._resolvers.add(resolver)
            resolver.add_done_callback(self._resolvers.discard)

    def _dispatch_thread(self, loop, batch: np.ndarray) -> asyncio.Future:
        """Run one device call on a pool worker thread. The pool heals
        around wedged calls (see :class:`_WorkerPool`): a stranded
        worker stays stranded, and fresh batches get fresh threads —
        the batcher recovers instead of exhausting a fixed pool whose
        every worker is stuck."""
        fut: asyncio.Future = loop.create_future()
        self.device_calls += 1

        def runner():
            try:
                out = self.engine.predict_labels(batch)
            except Exception as e:  # noqa: BLE001
                loop.call_soon_threadsafe(self._finish_future, fut, None, e)
            else:
                loop.call_soon_threadsafe(self._finish_future, fut, out, None)

        self._pool.submit(runner)
        return fut

    @staticmethod
    def _finish_future(fut: asyncio.Future, result, exc) -> None:
        # The watchdog may have abandoned this future already; a late
        # arrival is dropped silently (nobody is waiting for it).
        if fut.done():
            return
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)

    async def _resolve(self, work: asyncio.Future, futures) -> None:
        try:
            # The watchdog is a failure detector, not flow control: a
            # wedged device call fails its own requests and frees the
            # in-flight slot instead of deadlocking the whole batcher.
            labels, probs = await asyncio.wait_for(
                asyncio.shield(work), self.dispatch_timeout_s
            )
        except Exception as e:
            if isinstance(e, asyncio.TimeoutError):
                self.timeouts += 1
                work.cancel()  # nobody will consume a late result
                e = RuntimeError(
                    f"device call exceeded {self.dispatch_timeout_s}s "
                    "(wedged accelerator or transport?)"
                )
            _log.error("batch of %d failed: %s", len(futures), e)
            for f in futures:
                if not f.done():
                    f.set_exception(e)
            return
        finally:
            self.inflight -= 1
            self._inflight.release()
        for f, label, prob in zip(futures, labels, probs):
            if not f.done():
                f.set_result((label, float(prob)))
