"""TPU-native serving stack.

End-to-end request path (contrast with reference ``main.py``, which
re-loads a pickle and runs sklearn inline per request):

    client ──HTTP──▶ server.py (asyncio HTTP/1.1, keep-alive)
      └─ asgi.py  App: route match, pydantic 422 validation
         └─ app.py /predict + /models/<id>/* handlers
            └─ scoring.py  ScorePath: coalesce concurrent rows into
               typed score units (or pool-worker dispatches)
               └─ engine.py InferenceEngine: padded bucket batch →
                  ONE jitted device call (argmax + max-softmax) →
                  futures resolved per request
"""

from mlapi_tpu.serving.app import build_app, feature_schema  # noqa: F401
from mlapi_tpu.serving.asgi import App, HTTPError, Request, Response  # noqa: F401
from mlapi_tpu.serving.engine import (  # noqa: F401
    InferenceEngine,
    TextClassificationEngine,
)
from mlapi_tpu.serving.registry import ModelRegistry, TenantLedger  # noqa: F401
from mlapi_tpu.serving.scoring import MicroBatcher, ScorePath  # noqa: F401
from mlapi_tpu.serving.router import Router, build_router_app  # noqa: F401
from mlapi_tpu.serving.server import Server  # noqa: F401
