"""Deterministic fault injection for the serving engine.

The serving lifecycle funnels every kind of device work through a
handful of seams — pool page allocation, table-row install, prefill
chunk dispatch, decode chunk dispatch, speculative verify, the
collector's queue pop, the per-request stream push. This module puts
a NAMED injection point at each of those seams so tests (and chaos
drills) can force the failure modes the engine's invariants must
survive — ``PagePoolExhausted`` mid-admission, a slow dispatch inside
a drain window, a killed collector — without hacking private state,
then assert the conservation invariants: page refcounts return to
baseline, no orphan table rows, every stream ends in a well-formed
terminal frame, and the engine serves fresh work afterward.

Design constraints, in order:

- **Zero overhead when disarmed.** ``fire()`` is one module-global
  bool check; nothing is parsed, counted, or locked until a spec is
  armed. The production hot path pays a predictable ~100 ns per seam.
- **Deterministic.** Triggers are CALL COUNTS (``after=N`` skips the
  first N calls then fires; ``every=N`` fires each Nth call), never
  randomness or wall-clock — the same traffic hits the same fault at
  the same dispatch, every run.
- **Seam-native exceptions.** A point may hand ``fire()`` the
  exception its seam raises for real (``pool_alloc`` raises
  ``PagePoolExhausted``), so armed faults exercise the EXACT handler
  paths production failures take; everywhere else an
  :class:`InjectedFault` makes the provenance unmistakable.

Arming, by env or explicitly::

    MLAPI_FAULTS="pool_alloc:after=3:raise,decode:every=5:delay=0.05"

Grammar: comma-separated clauses, each ``point[:trigger]*[:action]``.
Actions: ``raise`` (default) or ``delay=<seconds>``. Triggers:
``after=N`` (skip N calls, then due) or ``every=N`` (due on each Nth
call) — at most one of the two per clause — plus ``times=M`` (fire at
most M times; defaults to 1 for ``raise`` — one shot — and unlimited
for ``delay``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

POINTS = (
    "pool_alloc",      # PagePool.alloc — before pages leave the free list
    "table_install",   # admission/pf-activation table-row install
    "prefill_chunk",   # each prefill-chunk dispatch (formation + interleaved)
    "decode",          # each decode-chunk dispatch
    "spec_verify",     # each speculative verify block (solo + batched)
    "collector_pop",   # the collector claiming a queued request
    "stream_push",     # a token chunk entering a request's queue
    "tier_spill",      # KV tier: registering an evicted prefix blob
    "tier_restore",    # KV tier: applying a blob back to device
    # The unit-dispatch seam (serving/scheduler.py, r15): fires once
    # before EVERY scheduler unit — lane formation included. A raise
    # kills that one lane (its generator's finally releases its
    # pages; its waiters get the error as their terminal frame) while
    # every other lane streams on; a delay slows one unit, bounding
    # how long any single batch can stall the queue in a drill.
    "sched_unit",
    # The router↔replica hop (serving/router.py): fires once per
    # forward attempt BEFORE the first request byte is written (a
    # raise there triggers the single failover hop with no duplicate
    # generation) and once per relayed stream chunk (a raise there
    # must yield a well-formed error terminal frame, never a
    # truncated stream). Call counts are shared across both seams —
    # ``after=N`` skips the submits to target the relay.
    "router_forward",
    # Peer-to-peer prefix-KV fetch (serving/kv_peer.py, r17). Both
    # points fire BEFORE any wire byte moves or any counter mutates,
    # so an injected raise exercises the exact degradation contract:
    # the fetching replica counts a fetch failure and falls back to
    # the cold prefill with ``kv_pages_in_use`` conserved (the fetch
    # never touched the pool — restore allocates first, later, on
    # the dispatch thread); the serving replica's handler 500s and
    # its tier/entries are untouched.
    "peer_fetch",       # before the GET /kv/prefix wire request
    "peer_serve",       # before a peer blob is resolved/serialized
    # Prefill/decode disaggregation (serving/kv_peer.py KVPush, r18).
    # Both points fire BEFORE any wire byte moves or any counter
    # mutates. ``kv_push_send`` fires on the PREFILL replica's push
    # worker before each chunk's POST — a raise marks the transfer
    # failed (counted), the remaining chunks are dropped, and the
    # router's fallback submits the request to the decode replica
    # WITHOUT the transfer id, which then cold-prefills with
    # ``kv_pages_in_use`` conserved on both ends (the push path
    # allocates no pages; pool pages only move at the decode
    # replica's formation, which the failed transfer never reaches).
    # ``kv_push_recv`` fires in the decode replica's /kv/push handler
    # before the body is parsed or staged — a raise 500s the push,
    # which the sender counts as the same transfer failure. Delays
    # slow the worker thread / the app executor, never the dispatch
    # thread.
    "kv_push_send",     # before a chunk's POST /kv/push leaves the sender
    "kv_push_recv",     # before a pushed chunk is parsed/staged
    # Many-adapter LoRA serving (serving/adapter_store.py).
    # ``adapter_fetch`` fires on the encode executor thread BEFORE
    # the GET /adapter/<id> wire request — a raise is a counted fetch
    # failure and the request resolves against whatever the host
    # store already holds (absent ⇒ AdapterUnavailable ⇒ 404), slots
    # and pages conserved (the fetch never touches the device).
    # ``adapter_install`` fires on the dispatch thread AFTER payload
    # validation but BEFORE the slot allocation and donated scatter —
    # a raise rejects the install on untouched state (no slot popped,
    # no victim evicted, nothing half-installed) and the affected
    # requests get the error as their terminal frame; a delay slows
    # formation, never breaks it.
    "adapter_fetch",    # before the GET /adapter/<id> wire request
    "adapter_install",  # before an adapter's slot alloc + scatter
    # The scoring fast path (serving/scoring.py, r22): fires once
    # BEFORE each scoring device dispatch — on the unit-scheduler
    # dispatch thread when a generative engine is co-resident, on a
    # pool worker otherwise, so the same spec drills both backends. A
    # raise fails that ONE formed batch (its futures get the error as
    # their result; queue, counters and the in-flight slot are
    # conserved) while the next batch dispatches clean; a delay slows
    # one scoring unit, bounding how long microsecond-scale scoring
    # can stall an interleaved decode chunk in a drill.
    "score_dispatch",   # before a scoring batch's device call
)

ENV_VAR = "MLAPI_FAULTS"


class InjectedFault(RuntimeError):
    """The generic armed-point failure (``action=raise`` at a seam
    with no native exception)."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point!r}")
        self.point = point


class _Rule:
    __slots__ = (
        "point", "action", "delay_s", "after", "every", "times",
        "calls", "fired",
    )

    def __init__(self, point: str, action: str, delay_s: float,
                 after: int | None, every: int | None,
                 times: int | None):
        self.point = point
        self.action = action       # "raise" | "delay"
        self.delay_s = delay_s
        self.after = after
        self.every = every
        self.times = times         # None = unlimited
        self.calls = 0
        self.fired = 0

    def due(self) -> bool:
        """Call-count trigger decision (caller holds the lock and has
        already bumped ``calls``)."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.every is not None:
            return self.calls % self.every == 0
        if self.after is not None:
            return self.calls > self.after
        return True


# Module-global armed state: ONE bool gates the hot path; the rule
# table and counters exist only while armed. The lock serializes
# decode-thread fires against event-loop arms/reads.
armed = False
_rules: dict[str, _Rule] = {}
_lock = threading.Lock()
_injected = 0


def parse(spec: str) -> dict[str, _Rule]:
    """Parse an ``MLAPI_FAULTS`` spec string; loud on unknown points
    or malformed clauses (a typo'd chaos drill must not silently test
    nothing)."""
    rules: dict[str, _Rule] = {}
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        fields = clause.split(":")
        point = fields[0].strip()
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {', '.join(POINTS)}"
            )
        action = None
        delay_s = 0.0
        after = every = times = None
        for f in fields[1:]:
            f = f.strip()
            if f == "raise":
                action = "raise"
            elif f.startswith("delay="):
                action = "delay"
                delay_s = float(f[len("delay="):])
                if delay_s < 0:
                    raise ValueError(f"negative delay in {clause!r}")
            elif f.startswith("after="):
                after = int(f[len("after="):])
            elif f.startswith("every="):
                every = int(f[len("every="):])
                if every < 1:
                    raise ValueError(f"every must be >= 1 in {clause!r}")
            elif f.startswith("times="):
                times = int(f[len("times="):])
            else:
                raise ValueError(
                    f"bad fault field {f!r} in {clause!r} (want raise, "
                    f"delay=S, after=N, every=N, or times=N)"
                )
        if after is not None and every is not None:
            raise ValueError(
                f"both after= and every= in {clause!r}: pick one — "
                f"due() honors a single trigger, and a clause that "
                f"silently ignored one would fire on a schedule the "
                f"operator did not write"
            )
        if point in rules:
            raise ValueError(
                f"duplicate fault point {point!r}: one clause per "
                f"point (a silently-dropped clause would test less "
                f"than the operator wrote)"
            )
        if action is None:
            action = "raise"
        if times is None and action == "raise":
            # An unbounded raise would keep killing the recovery path
            # the test is trying to observe; one shot is the useful
            # default (delay stays unlimited — it only slows).
            times = 1
        rules[point] = _Rule(point, action, delay_s, after, every, times)
    return rules


def arm(spec: str | None = None) -> None:
    """Install a fault spec (replaces any armed one). ``None`` reads
    ``$MLAPI_FAULTS``; an empty spec disarms."""
    global armed, _injected
    if spec is None:
        spec = os.environ.get(ENV_VAR, "")
    rules = parse(spec)
    with _lock:
        _rules.clear()
        _rules.update(rules)
        _injected = 0
        armed = bool(rules)


def arm_from_env() -> bool:
    """Arm from ``$MLAPI_FAULTS`` if set (server startup hook); no-op
    — and no disarm — when the variable is absent."""
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return False
    arm(spec)
    return True


def disarm() -> None:
    global armed, _injected
    with _lock:
        _rules.clear()
        _injected = 0
        armed = False


def injected_count() -> int:
    """Faults actually fired under the CURRENT arming (0 when
    disarmed) — the ``/metrics`` counter
    ``generate.faults_injected``."""
    with _lock:
        return _injected


@contextlib.contextmanager
def active(spec: str):
    """Test-scoped arming: ``with faults.active("decode:raise"): ...``
    — always disarms, even when the injected fault propagates."""
    arm(spec)
    try:
        yield
    finally:
        disarm()


def fire(point: str, exc: BaseException | None = None) -> None:
    """The seam call. Disarmed: one bool check, return. Armed: bump
    the point's call count; when its trigger is due, sleep
    (``delay``) or raise (``exc`` if the seam passed its native
    exception, else :class:`InjectedFault`)."""
    if not armed:
        return
    with _lock:
        rule = _rules.get(point)
        if rule is None:
            return
        rule.calls += 1
        if not rule.due():
            return
        rule.fired += 1
        global _injected
        _injected += 1
        action, delay_s = rule.action, rule.delay_s
    if action == "delay":
        time.sleep(delay_s)
        return
    raise exc if exc is not None else InjectedFault(point)
