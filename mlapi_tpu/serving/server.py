"""Asyncio HTTP/1.1 server speaking ASGI — the framework's uvicorn.

The reference runs under uvicorn/h11 (``README.md:16``,
``requirements.txt:3,17``); neither is part of this stack, so the
framework ships its own server: a single-process asyncio server with
persistent connections (keep-alive matters — the p50 budget can't
afford a TCP+TLS handshake per request), Content-Length and chunked
request bodies, and hard limits on header/body sizes.

Single event loop, no worker processes: the CPU work per request is
tiny (parse + validate); the heavy lifting is on the TPU behind the
micro-batcher, and one loop feeds it comfortably.
"""

from __future__ import annotations

import asyncio
from urllib.parse import unquote

from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.server")

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 256 * 1024 * 1024
_STATUS_PHRASES = {
    200: "OK", 204: "No Content", 304: "Not Modified",
    400: "Bad Request", 404: "Not Found", 405: "Method Not Allowed",
    408: "Request Timeout", 411: "Length Required", 413: "Payload Too Large",
    422: "Unprocessable Entity", 429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error", 501: "Not Implemented",
    502: "Bad Gateway",  # the router's upstream-replica failure
    503: "Service Unavailable", 504: "Gateway Timeout",
}


class HttpProtocolError(Exception):
    def __init__(self, status: int, detail: str):
        super().__init__(detail)
        self.status = status
        self.detail = detail


class Server:
    """Serves one ASGI app on (host, port).

    ``reuse_port=True`` binds with ``SO_REUSEPORT`` so N worker
    *processes* can share one listening port, kernel-balanced per
    connection — the CPU-attach scale-out path (the single asyncio
    loop is the throughput ceiling on one core; see
    ``__main__.py --workers``). TPU serving scales with more chips on
    a mesh instead: the chip is single-process-exclusive.
    """

    def __init__(self, app, host: str = "127.0.0.1", port: int = 8000,
                 *, reuse_port: bool = False):
        self.app = app
        self.host = host
        self.port = port
        self.reuse_port = reuse_port
        self._server: asyncio.Server | None = None

    async def start(self) -> None:
        await self.app.startup()
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port,
            reuse_port=self.reuse_port or None,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        _log.info("listening on http://%s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        await self.app.shutdown()

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    # -- connection handling ---------------------------------------------
    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    request = await _read_request(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break  # client closed between requests
                except HttpProtocolError as e:
                    await _write_simple(writer, e.status, e.detail)
                    break
                if request is None:
                    break
                keep_alive = await self._dispatch(request, writer)
                if not keep_alive:
                    break
        except Exception:
            _log.exception("connection error from %s", peer)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _dispatch(self, request: "_ParsedRequest", writer) -> bool:
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": request.version,
            "method": request.method,
            "scheme": "http",
            "path": request.path,
            "raw_path": request.raw_path.encode("latin-1"),
            "query_string": request.query.encode("latin-1"),
            "headers": request.headers,  # bytes pairs, passed through
            # Body already fully read — the framework's own App picks
            # it up here and skips the receive-message round trip.
            "extensions": {"mlapi_tpu.body": request.body},
        }

        body_sent = False

        async def receive():
            nonlocal body_sent
            if body_sent:
                return {"type": "http.disconnect"}
            body_sent = True
            return {"type": "http.request", "body": request.body, "more_body": False}

        keep_alive = _wants_keep_alive(request)
        # state: headers buffered until the first body message decides
        # the framing — a single-shot body gets content-length (the
        # /predict fast path, one write + one drain per response); a
        # streamed body (more_body=True) switches to chunked transfer
        # encoding with a write+drain per chunk so the client sees
        # data as the handler produces it. HTTP/1.0 clients don't
        # de-frame chunked encoding, so a stream to them is
        # close-delimited (raw bytes, connection: close) instead.
        chunked_ok = request.version != "1.0"
        state = {"status": 500, "headers": [], "streaming": False,
                 "started": False}

        def _head(extra: bytes) -> bytes:
            status = state["status"]
            phrase = _STATUS_PHRASES.get(status, "Unknown")
            # Bytes all the way down — response headers arrive as
            # bytes from ASGI and hit the socket as bytes.
            head = bytearray(
                f"HTTP/1.1 {status} {phrase}\r\n".encode("latin-1")
            )
            for k, v in state["headers"]:
                if k.lower() not in (b"content-length", b"transfer-encoding"):
                    head += k + b": " + v + b"\r\n"
            head += extra
            head += (
                b"connection: keep-alive\r\n\r\n"
                if keep_alive
                else b"connection: close\r\n\r\n"
            )
            return bytes(head)

        async def send(message):
            nonlocal keep_alive
            if message["type"] == "http.response.start":
                state["status"] = message["status"]
                state["headers"] = message.get("headers", [])
                return
            if message["type"] != "http.response.body":
                return
            body = message.get("body", b"")
            more = message.get("more_body", False)
            if not state["started"]:
                state["started"] = True
                if not more:
                    if state["status"] in (204, 304):
                        # RFC 9110 §8.6: no Content-Length (and no
                        # body) on 204/304.
                        writer.write(_head(b""))
                    else:
                        writer.write(
                            _head(
                                b"content-length: "
                                + str(len(body)).encode() + b"\r\n"
                            )
                            + body
                        )
                    await writer.drain()
                    return
                state["streaming"] = True
                if chunked_ok:
                    writer.write(_head(b"transfer-encoding: chunked\r\n"))
                else:
                    keep_alive = False  # close delimits the 1.0 body
                    writer.write(_head(b""))
            if not state["streaming"]:
                return  # spurious extra message after a completed body
            if writer.transport.is_closing():
                # Client went away mid-stream. write() on a closing
                # transport is silently dropped and drain() may not
                # raise for buffered writes — fail loudly so the app
                # can cancel the work feeding this stream.
                raise ConnectionResetError("client disconnected mid-stream")
            if not chunked_ok:
                if body:
                    writer.write(body)
                await writer.drain()
                return
            if body:
                writer.write(
                    b"%x\r\n" % len(body) + body + b"\r\n"
                )
            if not more:
                writer.write(b"0\r\n\r\n")
            await writer.drain()

        await self.app(scope, receive, send)
        if not state["started"]:
            # App produced no body message at all; close the exchange.
            writer.write(_head(b"content-length: 0\r\n"))
            await writer.drain()
        return keep_alive


class _ParsedRequest:
    __slots__ = ("method", "raw_path", "path", "query", "version", "headers", "body")

    def __init__(self, method, raw_path, path, query, version, headers, body):
        self.method = method
        self.raw_path = raw_path
        self.path = path
        self.query = query
        self.version = version
        self.headers = headers
        self.body = body


async def _read_request(reader: asyncio.StreamReader) -> _ParsedRequest | None:
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.LimitOverrunError:
        raise HttpProtocolError(431, "headers too large") from None
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None  # clean EOF between keep-alive requests
        raise
    if len(head) > MAX_HEADER_BYTES:
        raise HttpProtocolError(431, "headers too large")

    # Headers stay bytes end to end: parsed as bytes here, passed as
    # bytes in the ASGI scope, decoded lazily only if a handler reads
    # them (the /predict hot path never does).
    lines = head.split(b"\r\n")
    try:
        method_b, target_b, proto = lines[0].split(b" ", 2)
        method = method_b.decode("latin-1")
        target = target_b.decode("latin-1")
    except (ValueError, UnicodeDecodeError):
        raise HttpProtocolError(400, f"malformed request line: {lines[0]!r}") from None
    if not proto.startswith(b"HTTP/1."):
        raise HttpProtocolError(501, f"unsupported protocol {proto!r}")
    version = proto[5:].decode("latin-1")

    headers: list[tuple[bytes, bytes]] = []
    for line in lines[1:]:
        if not line:
            continue
        key, sep, value = line.partition(b":")
        if not sep:
            raise HttpProtocolError(400, f"malformed header line: {line!r}")
        headers.append((key.strip().lower(), value.strip()))

    # Framing headers via one linear scan (no dict build per request).
    te = clen = None
    for k, v in headers:
        if k == b"content-length":
            clen = v
        elif k == b"transfer-encoding":
            te = v
    body = b""
    if te is not None:
        if te.lower() != b"chunked":
            raise HttpProtocolError(501, "unsupported transfer-encoding")
        body = await _read_chunked(reader)
    elif clen is not None:
        try:
            n = int(clen)
        except ValueError:
            raise HttpProtocolError(400, "bad content-length") from None
        if n > MAX_BODY_BYTES:
            raise HttpProtocolError(413, "body too large")
        body = await reader.readexactly(n) if n else b""
    elif method in ("POST", "PUT", "PATCH"):
        # No length and not chunked: only valid if there is no body.
        pass

    raw_path, _, query = target.partition("?")
    return _ParsedRequest(
        method=method,
        raw_path=target,
        path=unquote(raw_path),
        query=query,
        version=version,
        headers=headers,
        body=body,
    )


async def _read_chunked(reader: asyncio.StreamReader) -> bytes:
    out = bytearray()
    while True:
        size_line = (await reader.readuntil(b"\r\n")).strip()
        try:
            size = int(size_line.split(b";")[0], 16)
        except ValueError:
            raise HttpProtocolError(400, f"bad chunk size {size_line!r}") from None
        if size < 0:
            raise HttpProtocolError(400, f"negative chunk size {size_line!r}")
        if size == 0:
            # Trailers until blank line.
            while (await reader.readuntil(b"\r\n")) != b"\r\n":
                pass
            return bytes(out)
        if len(out) + size > MAX_BODY_BYTES:
            raise HttpProtocolError(413, "body too large")
        out.extend(await reader.readexactly(size))
        if await reader.readexactly(2) != b"\r\n":
            raise HttpProtocolError(400, "chunk not CRLF-terminated")


def _wants_keep_alive(request: _ParsedRequest) -> bool:
    # Linear scan, no dict build: this runs per request and a request
    # carries a handful of headers. No early break — duplicates keep
    # the dict's last-wins semantics.
    conn = b""
    for k, v in request.headers:
        if k == b"connection":
            conn = v.lower()
    if request.version == "1.0":
        return conn == b"keep-alive"
    return conn != b"close"


async def _write_simple(writer, status: int, detail: str) -> None:
    body = detail.encode()
    phrase = _STATUS_PHRASES.get(status, "Error")
    writer.write(
        (
            f"HTTP/1.1 {status} {phrase}\r\ncontent-type: text/plain\r\n"
            f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
        ).encode("latin-1")
        + body
    )
    try:
        await writer.drain()
    except (ConnectionResetError, BrokenPipeError):
        pass
