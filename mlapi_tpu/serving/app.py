"""The serving application: the reference's API surface, TPU-backed.

Routes preserve the reference's observable contract:

- ``POST /predict``  (``main.py:16-27``): JSON body validated against
  the model's feature schema (422 on failure, FastAPI-shaped), reply
  ``{"prediction": "<label>", "probability": <max prob>}``.
- ``POST /files/``   (``main.py:29-38``): multipart CSV + ``token``
  form field. The reference echoed a raw DataFrame, which is not
  reliably JSON-encodable (its own author left a commented-out
  ``#return df`` at ``main.py:35``); per SURVEY §3.3 we keep the
  capability and fix the contract: a JSON echo of columns/rows/records
  plus the token.

Plus what the reference lacked (SURVEY §5): ``GET /healthz``,
``GET /metrics``, request counters and latency histograms.

(No ``from __future__ import annotations`` here: the ``/predict``
handler's schema annotation is a dynamically-built pydantic model that
must survive as a real class for routing-time body-model detection.)
"""

import asyncio
import io
import json
import time

import numpy as np
import pydantic

from mlapi_tpu.serving.asgi import (
    App,
    HTTPError,
    Request,
    Response,
    StreamingResponse,
    json_response,
)
from mlapi_tpu.serving import faults
from mlapi_tpu.serving.scoring import OverloadedError, ScorePath
from mlapi_tpu.serving.engine import InferenceEngine
from mlapi_tpu.serving.requests import DeadlineExceeded, DrainCancelled
from mlapi_tpu.utils.logging import get_logger
from mlapi_tpu.utils.metrics import MetricsRegistry

_log = get_logger("serving.app")

MAX_ECHO_RECORDS = 1000


def _validate_deadline_ms(value) -> None:
    """Shared /predict + /generate schema check: 0 would silently
    mean "no deadline" and a negative one would burn a queue slot
    just to 504 on the first batch."""
    if value is not None and value <= 0:
        raise HTTPError(
            422,
            [
                {
                    "type": "value_error",
                    "loc": ["deadline_ms"],
                    "msg": "must be > 0 (omit for no deadline)",
                    "input": value,
                }
            ],
        )


def _is_router_replica() -> bool:
    """Is this server a router replica (the only deployment where a
    trusted party stamps ``x-mlapi-router-depth``)? Spawned replicas
    carry ``MLAPI_TPU_REPLICA=1``; externally-launched fleets export
    ``MLAPI_TPU_REPLICAS`` (the same discovery convention the router
    reads). A non-replica server IGNORES the header outright — an
    arbitrary client must not be able to inject fleet pressure into
    admission control / the brownout ladder. (The router additionally
    strips client-sent copies on its forward path, so within a fleet
    only the router's own value ever arrives.)"""
    import os

    return os.environ.get("MLAPI_TPU_REPLICA") == "1" or bool(
        os.environ.get("MLAPI_TPU_REPLICAS")
    )


def _router_depth(request) -> int:
    """The fleet-backlog gauge a fronting router stamps on forwarded
    requests (``x-mlapi-router-depth``; 0 for direct traffic — a
    stale fleet spike must not keep shedding after the router is
    gone). Scans the raw ASGI header list for the one key instead of
    decoding the full header dict — ``/predict``'s hot path
    deliberately never pays the lazy full-header decode."""
    for k, v in request.scope.get("headers", []):
        if k == b"x-mlapi-router-depth":
            try:
                return max(0, int(v))
            except (TypeError, ValueError):
                return 0
    return 0


def _warm_peer(request) -> str | None:
    """The warm-peer hint a fronting router stamps on any forward
    that misses the request's HRW-preferred replica
    (``x-mlapi-warm-peer: host:port`` — who is likely warm for this
    prefix). Same raw-scope scan and same trust model as
    ``_router_depth``: read only on router replicas, and the router
    strips client-sent copies, so an arbitrary caller can never aim
    this replica's KV fetches at a host of their choosing."""
    return _scan_header(request, b"x-mlapi-warm-peer")


def _scan_header(request, key: bytes) -> str | None:
    """Raw ASGI header-list scan for one router-authored key (same
    no-full-decode discipline as ``_router_depth``)."""
    for k, v in request.scope.get("headers", []):
        if k == key:
            try:
                return v.decode("latin-1").strip() or None
            except Exception:
                return None
    return None


def _decode_peer(request) -> str | None:
    """The decode replica a fronting router named for a disaggregated
    forward (``x-mlapi-decode-peer: host:port``, r18) — stamped only
    on forwards to PREFILL-role replicas. Router-authored and
    replica-gated like ``x-mlapi-warm-peer``: the router strips
    client-sent copies, and a non-replica server never reads it, so
    an arbitrary caller can never aim a replica's KV pushes at a host
    of their choosing."""
    return _scan_header(request, b"x-mlapi-decode-peer")


def _kv_xfer(request) -> str | None:
    """The transfer id of a disaggregated request
    (``x-mlapi-kv-xfer``, r18): on a prefill replica it names the
    push stream to open; on a decode replica it names the staged
    transfer whose KV replaces this request's prefill. Same trust
    model as ``_decode_peer``."""
    return _scan_header(request, b"x-mlapi-kv-xfer")


def _overloaded_http(e: OverloadedError) -> HTTPError:
    """Overload → immediate 503 with a Retry-After hint. Shedding at
    the door keeps latency bounded for the requests that ARE admitted;
    clients with backoff recover on their own."""
    return HTTPError(
        503,
        str(e),
        headers={"retry-after": str(int(max(1, e.retry_after_s)))},
    )


def _terminal_http(e: Exception) -> HTTPError | None:
    """Map an in-band terminal error frame to its HTTP shape on the
    UNARY paths (streams carry the same information as their last
    NDJSON frame): deadline expiry → 504, drain-cancel and pool
    exhaustion → 503 (retry against a live/looser replica). Anything
    else stays a 500 via the generic handler."""
    if isinstance(e, DeadlineExceeded):
        return HTTPError(504, str(e))
    if isinstance(e, DrainCancelled):
        return HTTPError(503, str(e), headers={"retry-after": "5"})
    from mlapi_tpu.serving.paged_pool import PagePoolExhausted

    if isinstance(e, PagePoolExhausted):
        return HTTPError(503, str(e), headers={"retry-after": "1"})
    from mlapi_tpu.serving.adapter_store import (
        AdapterSlotsExhausted, AdapterUnavailable,
    )

    if isinstance(e, AdapterUnavailable):
        # The named adapter does not exist anywhere this replica can
        # reach — the resource is absent, not the server unhealthy.
        return HTTPError(404, str(e))
    if isinstance(e, AdapterSlotsExhausted):
        # Momentary: every slot pinned by live batches. Retryable.
        return HTTPError(503, str(e), headers={"retry-after": "1"})
    return None


def feature_schema(feature_names) -> type[pydantic.BaseModel]:
    """Build the request schema from the model's feature names — for
    Iris this reproduces the reference's ``IrisSpecies``
    (``main.py:10-14``): four required floats, numeric strings
    coerced. Models without named features (e.g. 784-pixel MNIST)
    take ``{"features": [..784 floats..]}`` instead. Every variant
    carries the optional ``deadline_ms`` wall-clock budget (r12)."""
    if feature_names:
        return pydantic.create_model(
            "Features",
            **{name: (float, ...) for name in feature_names},
            deadline_ms=(float | None, None),
        )
    return pydantic.create_model(
        "Features", features=(list[float], ...),
        deadline_ms=(float | None, None),
    )


def build_app(
    engine: InferenceEngine | None = None,
    *,
    max_batch: int | None = None,
    max_wait_ms: float = 0.2,
    max_queue: int | None = None,
    registry: MetricsRegistry | None = None,
    default_deadline_ms: float | None = None,
    drain_timeout_s: float = 10.0,
    admission_control: bool = True,
    models=None,
    tenants=None,
) -> App:
    """One app over one model or a whole registry.

    ``models`` (a :class:`~mlapi_tpu.serving.registry.ModelRegistry`)
    is the r22 multi-model surface: every entry serves at
    ``/models/<id>/{predict|generate}`` and the DEFAULT entry also
    owns the legacy ``/predict`` / ``/generate`` routes — a
    single-model process is just a one-entry registry, bit for bit.
    ``tenants`` (a :class:`~mlapi_tpu.serving.registry.TenantLedger`)
    attaches per-tenant quotas/weights/brownout to every generative
    entry."""
    from mlapi_tpu.serving.registry import ModelRegistry

    if models is None:
        if engine is None:
            raise ValueError("build_app needs an engine or a registry")
        models = ModelRegistry({"default": engine})
    engine = models.default
    app = App(title="mlapi-tpu")
    registry = registry or MetricsRegistry()
    app.state["engine"] = engine
    app.state["models"] = models
    app.state["tenants"] = tenants
    app.state["metrics"] = registry
    app.state["drain_timeout_s"] = float(drain_timeout_s)

    multi = len(models.ids()) > 1
    primary_gen = models.primary_generative()
    score_paths: dict[str, ScorePath] = {}
    batcher = None
    for mid, eng in models.items():
        is_default = mid == models.default_id
        if eng.kind == "generative":
            if tenants is not None:
                eng.tenants = tenants
            if is_default:
                # The generative engine owns its queue/batch limits;
                # the app-level knobs apply to it too (engine
                # defaults when None). Non-default entries keep their
                # construction-time limits.
                if max_queue is not None:
                    eng.max_queue = max_queue
                if max_batch is not None:
                    eng.max_batch = min(max_batch, eng.max_batch)
                eng.default_deadline_ms = default_deadline_ms
                eng.admission_control = bool(admission_control)
                eng.drain_timeout_s = float(drain_timeout_s)
                _install_generate(app, eng)
                if getattr(eng, "kv_peer", None) is not None and (
                    _is_router_replica()
                ):
                    # Replica-gated like the hint header itself:
                    # outside a router fleet there is no trusted
                    # hinter, and the endpoint would only be a
                    # cache-presence oracle handing raw KV bytes to
                    # arbitrary direct callers.
                    _install_kv_peer(app, eng)
                if getattr(eng, "adapter_peer", None) is not None and (
                    _is_router_replica()
                ):
                    # Same trust model as /kv/prefix: adapter weight
                    # blobs serve replica↔replica only, inside a
                    # router fleet.
                    _install_adapter_peer(app, eng)
                if (
                    getattr(eng, "kv_push", None) is not None
                    and getattr(eng, "replica_role", "mixed") == "decode"
                    and _is_router_replica()
                ):
                    # The push intake exists ONLY on decode-role
                    # replicas inside a fleet (r18): a mixed topology
                    # exposes no push endpoint at all — bit-identical
                    # to r17 — and outside a fleet there is no
                    # trusted pusher.
                    _install_kv_push(app, eng)
            if multi:
                _install_generate(
                    app, eng, path=f"/models/{mid}/generate"
                )
        else:
            # The scoring fast path: formed batches ride the primary
            # generative engine's unit queue when one is co-resident
            # (typed score units between decode chunks), the folded
            # worker-pool backend otherwise.
            sp = ScorePath(
                eng, model_id=mid, max_batch=max_batch,
                max_wait_ms=max_wait_ms,
                default_deadline_ms=default_deadline_ms,
                sched_source=(
                    (lambda g=primary_gen: g.sched)
                    if primary_gen is not None else None
                ),
                **({"max_queue": max_queue}
                   if max_queue is not None else {}),
            )
            score_paths[mid] = sp
            if is_default:
                batcher = sp
                app.state["batcher"] = sp
                _install_predict(app, eng, sp)
            if multi:
                _install_predict(
                    app, eng, sp, path=f"/models/{mid}/predict"
                )
    app.state["score_paths"] = score_paths

    @app.on_startup
    async def _start():
        # Fault-injection points arm from $MLAPI_FAULTS (chaos drills
        # against a real server); a no-op — zero per-seam overhead —
        # when unset.
        faults.arm_from_env()
        loop = asyncio.get_running_loop()
        # Warm the compiled shapes off the request path, then start
        # the collectors. No request ever sees an XLA compile.
        # Generative engines start BEFORE the scoring paths so a
        # scoring batch formed at t=0 already finds the unit queue.
        for mid, eng in models.items():
            await loop.run_in_executor(None, eng.warmup)
            if eng.kind == "generative":
                await eng.start()
            models.note_started(mid)
        for sp in score_paths.values():
            await sp.start()
        _log.info(
            "serving %s (%s)",
            ", ".join(
                f"{mid}:{type(e.model).__name__}"
                for mid, e in models.items()
            ),
            "+".join(sorted({e.kind for _, e in models.items()})),
        )

    @app.on_shutdown
    async def _stop():
        # Graceful drain first (new admissions shed 503 + retry-after
        # and /healthz flips to "draining" the moment this hook runs;
        # in-flight streams get the budget to finish, then proper
        # terminal frames), THEN the hard stop. Scoring paths drain
        # and stop BEFORE the generative engines whose unit queue
        # their in-flight batches may ride.
        budget = app.state["drain_timeout_s"]
        for sp in score_paths.values():
            await sp.drain(budget)
            await sp.stop()
        for mid, eng in models.items():
            if eng.kind == "generative" and hasattr(eng, "stop"):
                if hasattr(eng, "drain"):
                    await eng.drain(budget)
                await eng.stop()
            models.note_stopped(mid)

    _install_common(app, engine, registry, batcher)
    app.install_docs()  # /openapi.json + /docs, like FastAPI gave free
    return app


def _install_predict(app: App, engine: InferenceEngine, batcher,
                     path: str = "/predict") -> None:
    """The classification surface: ``POST /predict`` — and, in a
    multi-model process, the same handler at
    ``POST /models/<id>/predict`` (the registry's ids are static at
    build time, so per-model routes register as exact paths)."""
    if engine.kind == "text":
        schema = pydantic.create_model(
            "TextRequest", text=(str, ...),
            deadline_ms=(float | None, None),
        )
    else:
        schema = feature_schema(engine.feature_names)
    order = engine.feature_names
    expected_dim = engine.num_features
    # Pre-escaped JSON bytes per class label (labels are fixed at
    # checkpoint load; escaping them per request would be waste).
    label_json = {
        label: json.dumps(label).encode() for label in engine.vocab.labels
    }

    is_replica = _is_router_replica()

    @app.post(path)
    async def predict(features: schema, request):  # type: ignore[valid-type]
        if is_replica:
            batcher.router_queue_depth = _router_depth(request)
        if engine.kind == "text":
            row = engine.encode(features.text)
        elif order:
            row = np.asarray([getattr(features, f) for f in order], np.float32)
        else:
            row = np.asarray(features.features, np.float32)
        if row.shape != (expected_dim,):
            # Same FastAPI-shaped detail list as pydantic 422s, so
            # clients parse every validation failure one way.
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["features"],
                        "msg": f"expected {expected_dim} features, "
                               f"got {row.shape[0]}",
                        "input": int(row.shape[0]),
                    }
                ],
            )
        _validate_deadline_ms(features.deadline_ms)
        try:
            label, prob = await batcher.submit(
                row, deadline_ms=features.deadline_ms
            )
        except OverloadedError as e:
            raise _overloaded_http(e) from None
        except DeadlineExceeded as e:
            raise HTTPError(504, str(e)) from None
        # Hot path: hand-assembled JSON from the per-label pre-escaped
        # bytes — skips json.dumps (with its default-fn machinery) on
        # every request. %.10g is plenty for a softmax probability.
        body = b'{"prediction":%b,"probability":%.10g}' % (
            label_json.get(label) or json.dumps(label).encode(),
            prob,
        )
        return Response(body, content_type="application/json")


def _install_generate(app: App, engine, path: str = "/generate") -> None:
    """The generative surface: ``POST /generate`` — and, in a
    multi-model process, the same handler at
    ``POST /models/<id>/generate``.

    Concurrent requests coalesce into one batched decode stream
    (``TextGenerationEngine``); ``"stream": true`` returns NDJSON —
    one ``{"token_ids": [...]}`` line per decoded chunk as it lands,
    then a ``{"done": true, "text": ..., ...}`` line."""
    from mlapi_tpu.serving.adapter_store import AdapterUnavailable

    schema = pydantic.create_model(
        "GenerateRequest",
        text=(str, ...),
        max_new_tokens=(int | None, None),
        temperature=(float, 0.0),
        top_k=(int, 0),
        top_p=(float, 1.0),
        seed=(int, 0),
        stream=(bool, False),
        stop=(str | list[str] | None, None),
        # End-to-end wall-clock budget (ms, measured from submit):
        # expiry at any dispatch boundary ends the stream with a
        # deadline_exceeded terminal frame / 504; infeasible budgets
        # shed 503 at the door (server default when omitted).
        deadline_ms=(float | None, None),
        # Shared-prefix KV caching: the effective prompt is
        # prefix + text, but the prefix's forward pass is computed
        # once and its KV reused by every request that names it.
        prefix=(str | None, None),
        # Per-tenant LoRA adapter id (serving/adapter_store.py): the
        # request decodes under base + this adapter's delta, batched
        # with other tenants over the one HBM-resident base.
        adapter=(str | None, None),
        # Quota/fairness identity (serving/registry.py, r22): the
        # tenant whose page/slot quota the request reserves against
        # and whose weight scales its deadline slack. Defaults to the
        # adapter id, then the anonymous tenant.
        tenant=(str | None, None),
    )
    hard_cap = engine.model.max_positions - 1

    def _norm_stops(stop) -> list[str]:
        stops = [stop] if isinstance(stop, str) else list(stop or [])
        if len(stops) > 4 or any(not 0 < len(s) <= 64 for s in stops):
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["stop"],
                        "msg": "up to 4 stop strings of 1-64 chars",
                        "input": stop,
                    }
                ],
            )
        return stops

    def _first_stop(text: str, stops: list[str]):
        """(cut_index, stop) of the earliest stop occurrence, or
        ``None``. Generation halts at the FIRST match; same-index ties
        go to the LONGEST stop (deterministic, not lexicographic)."""
        hits = [(i, s) for s in stops if (i := text.find(s)) != -1]
        return min(hits, key=lambda h: (h[0], -len(h[1])), default=None)

    is_replica = _is_router_replica()

    @app.post(path)
    async def generate(req: schema, request):  # type: ignore[valid-type]
        # Router backpressure (r15): the gauge feeds the admission
        # estimate and brownout ladder — replica deployments only
        # (the header is untrusted from arbitrary direct callers).
        if is_replica:
            engine.router_queue_depth = _router_depth(request)
            # Warm-peer hint (r17): noted BEFORE submit so the encode
            # thread's prefix miss can fetch the blob from the peer
            # the router named instead of cold-prefilling.
            if engine.kv_peer is not None and req.prefix:
                wp = _warm_peer(request)
                if wp:
                    engine.kv_peer.note_hint(req.prefix, wp)
            # Same hint, adapter tier: this forward missed the
            # tenant's HRW-preferred replica, so a cold adapter
            # fetches from the peer the router named (where the
            # tenant's prefixes — and so its adapter — stay warm)
            # instead of 404ing at the local store.
            if engine.adapter_peer is not None and req.adapter:
                wp = _warm_peer(request)
                if wp:
                    engine.adapter_peer.note_hint(req.adapter, wp)
        n_new = (
            req.max_new_tokens
            if req.max_new_tokens is not None
            else engine.default_max_new_tokens
        )
        if not 0 < n_new <= hard_cap:
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["max_new_tokens"],
                        "msg": f"must be in [1, {hard_cap}]",
                        "input": n_new,
                    }
                ],
            )
        if not 0.0 <= req.temperature <= 10.0:
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["temperature"],
                        "msg": "must be in [0, 10]",
                        "input": req.temperature,
                    }
                ],
            )
        if not 0 <= req.top_k <= engine.model.vocab_size:
            # Upper bound matters: an int32-overflowing value would
            # otherwise blow up inside the coalesced batch and fail
            # innocent co-batched requests.
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["top_k"],
                        "msg": f"must be in [0, {engine.model.vocab_size}] "
                               "(0 disables)",
                        "input": req.top_k,
                    }
                ],
            )
        if not 0.0 < req.top_p <= 1.0:
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["top_p"],
                        "msg": "must be in (0, 1] (1.0 disables)",
                        "input": req.top_p,
                    }
                ],
            )
        _validate_deadline_ms(req.deadline_ms)
        stops = _norm_stops(req.stop)
        push_to = None
        kv_xfer = None
        if is_replica and getattr(engine, "kv_push", None) is not None:
            xfer = _kv_xfer(request)
            peer = _decode_peer(request)
            if (
                xfer
                and peer
                and engine.replica_role == "prefill"
                and not req.prefix
            ):
                # Disaggregated PREFILL leg (r18): run the prompt as
                # a prefill-only batch whose chunk KV streams to the
                # named decode replica; answer the router with the
                # handoff verdict — it forwards the client's request
                # to the decode replica next (with the transfer id
                # only if every chunk landed).
                host, _, port = peer.rpartition(":")
                if host and port.isdigit():
                    push_to = (host, int(port), xfer)
            elif xfer and engine.replica_role == "decode":
                # Disaggregated DECODE leg: the staged transfer's KV
                # replaces this request's prefill at formation.
                kv_xfer = xfer
        if push_to is not None:
            try:
                gen = await engine.submit(
                    req.text,
                    max_new_tokens=n_new,
                    temperature=req.temperature,
                    seed=req.seed,
                    top_k=req.top_k,
                    top_p=req.top_p,
                    deadline_ms=req.deadline_ms,
                    push_to=push_to,
                )
            except OverloadedError as e:
                raise _overloaded_http(e) from None
            first_token = None
            while True:
                item = await gen.queue.get()
                if isinstance(item, Exception):
                    http = _terminal_http(item)
                    if http is not None:
                        raise http from None
                    raise item
                if item is None:
                    break
                ids = item.get("token_ids") or []
                if ids and first_token is None:
                    first_token = int(ids[0])
            # The fin rides the FIFO sender queue behind every chunk,
            # so a True here means the decode replica has the whole
            # transfer; waited off the event loop.
            complete = await asyncio.get_running_loop().run_in_executor(
                None, engine.kv_push.wait_sent, push_to[2]
            )
            return {
                "handoff": True,
                "xfer": push_to[2],
                "complete": bool(complete and first_token is not None),
                "first_token": first_token,
                "prompt_tokens": gen.prompt_tokens,
            }
        try:
            gen = await engine.submit(
                req.text,
                max_new_tokens=n_new,
                temperature=req.temperature,
                seed=req.seed,
                top_k=req.top_k,
                top_p=req.top_p,
                prefix=req.prefix,
                # Incremental consumers (NDJSON streams, stop-sequence
                # watchers that cancel early) need tokens per chunk;
                # plain requests let the decode loop chain dispatches
                # and sync once.
                stream=bool(req.stream) or bool(stops),
                deadline_ms=req.deadline_ms,
                kv_xfer=kv_xfer,
                adapter=req.adapter,
                tenant=req.tenant,
            )
        except OverloadedError as e:
            raise _overloaded_http(e) from None
        except AdapterUnavailable as e:
            # Raised on the submit path (the encode thread resolves
            # the id before the request queues): the named adapter is
            # absent everywhere this replica can reach — 404, the
            # resource, not the server.
            raise HTTPError(404, str(e)) from None
        except ValueError as e:
            # An invalid prefix (too long for the model window, empty
            # after tokenization) is the requester's error, not a 500.
            raise HTTPError(
                422,
                [
                    {
                        "type": "value_error",
                        "loc": ["prefix"],
                        "msg": str(e),
                        "input": req.prefix,
                    }
                ],
            ) from None

        if req.stream:
            async def ndjson():
                ids: list[int] = []
                finished = False
                try:
                    while True:
                        item = await gen.queue.get()
                        if isinstance(item, Exception):
                            # The stream's TERMINAL ERROR FRAME:
                            # machine-readable ``code`` for the errors
                            # clients route on (deadline_exceeded,
                            # draining) — the status line is long gone,
                            # so the frame IS the status.
                            finished = True
                            frame = {"error": str(item)}
                            code = getattr(item, "code", None)
                            if code:
                                frame["code"] = code
                            yield json.dumps(frame).encode() + b"\n"
                            return
                        if item is None:
                            finished = True
                            yield json.dumps(
                                {
                                    "done": True,
                                    "text": engine.tokenizer.decode(ids),
                                    "token_ids": ids,
                                    "prompt_tokens": gen.prompt_tokens,
                                }
                            ).encode() + b"\n"
                            return
                        ids.extend(item["token_ids"])
                        if stops:
                            # One decode per chunk, reused for the
                            # match and the done frame (decoding the
                            # full prefix each chunk is already
                            # O(n^2)-ish; don't triple it).
                            text = engine.tokenizer.decode(ids)
                            hit = _first_stop(text, stops)
                            if hit is not None:
                                # Stop matched: end the stream with the
                                # truncated authoritative text and free
                                # the decode row (cancel → the batch
                                # compacts it away). Chunks already
                                # streamed may extend past the stop at
                                # chunk granularity; the done frame is
                                # the source of truth.
                                finished = True
                                gen.cancel()
                                cut, s = hit
                                yield json.dumps(
                                    {
                                        "done": True,
                                        "text": text[:cut],
                                        "token_ids": ids,
                                        "prompt_tokens": gen.prompt_tokens,
                                        "stopped": s,
                                    }
                                ).encode() + b"\n"
                                return
                        yield json.dumps(item).encode() + b"\n"
                finally:
                    # Generator closed early (client disconnect →
                    # server acloses the body iterator): stop the
                    # decode loop spending device time on this row.
                    if not finished:
                        gen.cancel()

            return StreamingResponse(
                ndjson(), content_type="application/x-ndjson"
            )

        ids: list[int] = []
        stopped = None
        text = None
        try:
            while True:
                item = await gen.queue.get()
                if isinstance(item, Exception):
                    http = _terminal_http(item)
                    if http is not None:
                        raise http from None
                    raise item
                if item is None:
                    break
                ids.extend(item["token_ids"])
                if stops:
                    text = engine.tokenizer.decode(ids)
                    hit = _first_stop(text, stops)
                    if hit is not None:
                        gen.cancel()  # free the decode row early
                        stopped = hit
                        break
                    # text stays valid: every path that exits the loop
                    # does so before ids grows past this decode.
        except asyncio.CancelledError:
            gen.cancel()  # non-stream handler torn down mid-decode
            raise
        if text is None:
            text = engine.tokenizer.decode(ids)
        out = {
            "text": text if stopped is None else text[: stopped[0]],
            "token_ids": ids,
            "prompt_tokens": gen.prompt_tokens,
        }
        if stopped is not None:
            out["stopped"] = stopped[1]
        return out


def _install_kv_peer(app: App, engine) -> None:
    """The internal replica↔replica KV endpoint (``--kv-peer-fetch``):
    ``GET /kv/prefix?fp=<digest>`` serves this replica's blob for a
    prefix fingerprint — stored-format bytes straight off the host
    tier (or gathered from the device-resident entry's contiguous
    KV), geometry header included (``serving/kv_peer.py`` wire
    format). Deliberately a GET with no engine-submit gate: it keeps
    answering while DRAINING, which is exactly the window a peer
    needs the drained replica's slice. The resolve + serialize run on
    an executor thread — the entry-KV gather is a device_get and must
    not freeze the event loop."""
    peer = engine.kv_peer

    @app.get("/kv/prefix")
    async def kv_prefix(request: Request):
        from urllib.parse import parse_qs

        qs = parse_qs(
            (request.scope.get("query_string") or b"").decode("latin-1")
        )
        digest = (qs.get("fp") or [""])[0]
        if not digest:
            raise HTTPError(422, "missing fp=<fingerprint digest>")
        data = await asyncio.get_running_loop().run_in_executor(
            None, peer.serve_wire, digest
        )
        if data is None:
            raise HTTPError(404, "no warm KV for that fingerprint")
        return Response(data, content_type="application/octet-stream")


def _install_adapter_peer(app: App, engine) -> None:
    """The internal replica↔replica adapter endpoint:
    ``GET /adapter/<id>`` serves this replica's HOST-STORE copy of a
    tenant's LoRA adapter in the wire format (geometry header +
    raw leaves — ``serving/adapter_store.py``). Same shape as
    ``GET /kv/prefix``: a GET with no engine-submit gate (a draining
    replica keeps answering — exactly the window a peer needs its
    tenants' adapters), resolve + serialize on an executor thread,
    404 when the store has no such id. A middleware, not a route —
    the router's exact (method, path) table has no path params, and
    the id lives in the path (``kv_peer._http_get``-framed peers
    request it that way)."""
    peer = engine.adapter_peer

    @app.middleware
    async def _adapter_blob(request: Request, nxt):
        if request.method == "GET" and request.path.startswith(
            "/adapter/"
        ):
            aid = request.path[len("/adapter/"):]
            data = await asyncio.get_running_loop().run_in_executor(
                None, peer.serve_wire, aid
            )
            if data is None:
                raise HTTPError(404, "no such adapter on this replica")
            return Response(
                data, content_type="application/octet-stream"
            )
        return await nxt(request)


def _install_kv_push(app: App, engine) -> None:
    """The internal prefill→decode push intake (r18 disaggregation,
    decode-role replicas only): ``POST /kv/push`` stages one chunk
    (or the fin) of a transfer. Parse + staging run on an executor
    thread — numpy copies of multi-KB bodies must not block the
    event loop. A corrupt body is a 400 the SENDER counts as its
    transfer failure; the decode replica then simply cold-prefills
    when the router's second hop arrives without a usable
    transfer."""
    push = engine.kv_push

    @app.post("/kv/push")
    async def kv_push(request: Request):
        body = request.body
        if not body:
            raise HTTPError(422, "empty push body")
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, push.receive, body
            )
        except ValueError as e:
            raise HTTPError(400, f"bad push body: {e}") from None
        return out


def _install_common(app: App, engine, registry: MetricsRegistry, batcher) -> None:
    """Routes/middleware every engine kind shares: CSV ingestion
    (``/files/``, the reference's second endpoint), health, metrics."""
    # Counter/histogram objects resolved once per (route, status) and
    # cached — the hot path does two dict hits, not two f-string
    # formats + registry lookups per request. Only registered routes
    # become labels — unmatched paths all collapse to one bucket, so a
    # URL scanner can't grow the registry (or this cache) without bound.
    _counters: dict = {}
    _histograms: dict = {}

    def _record(key, status: int, ms: float) -> None:
        ckey = (key, status)
        counter = _counters.get(ckey)
        if counter is None:
            route = f"{key[0]} {key[1]}" if key else "unmatched"
            counter = _counters[ckey] = registry.counter(
                f"http.requests{{route={route},status={status}}}"
            )
            _histograms.setdefault(
                key, registry.histogram(f"http.latency_ms{{route={route}}}")
            )
        counter.inc()
        _histograms[key].observe(ms)

    @app.middleware
    async def _metrics_mw(request: Request, nxt):
        t0 = time.perf_counter()
        # Errors must be counted too: a handler raising HTTPError (or
        # anything else -> 500) unwinds through this middleware before
        # App.handle converts it to a response.
        status = 500
        recorded = False
        try:
            response = await nxt(request)
            status = response.status
            if isinstance(response, StreamingResponse):
                # The handler returns before a single token decodes;
                # measuring here would log ~0 ms for every stream.
                # Record when the body iterator finishes instead.
                response.body_iter = _record_when_done(
                    response.body_iter, request, status, t0
                )
                recorded = True
            return response
        except HTTPError as e:
            status = e.status
            raise
        finally:
            if not recorded:
                key = (request.method, request.path)
                if key not in app._routes:  # plain dict hit, no frozenset
                    key = None
                _record(key, status, (time.perf_counter() - t0) * 1e3)

    async def _record_when_done(it, request: Request, status: int, t0: float):
        try:
            async for chunk in it:
                yield chunk
        finally:
            # Being closed early (client disconnect) must close the
            # WRAPPED iterator too — `async for` does not aclose its
            # source on abnormal exit (PEP 525), and the inner
            # generator's finally is what cancels the decode work.
            aclose = getattr(it, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
            key = (request.method, request.path)
            if key not in app._routes:
                key = None
            _record(key, status, (time.perf_counter() - t0) * 1e3)

    @app.post("/files/")
    async def create_file(request: Request):
        """Ingest a CSV upload (multipart) with an auth-token form
        field; echoes columns/rows/records as JSON."""
        import pandas as pd

        fields, files = request.form()
        if "token" not in fields:
            raise HTTPError(422, "missing form field 'token'")
        if "file" not in files:
            raise HTTPError(422, "missing file field 'file'")
        raw = files["file"].data
        try:
            text = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise HTTPError(400, f"file is not utf-8 text: {e}") from None
        try:
            df = await asyncio.get_running_loop().run_in_executor(
                None, lambda: pd.read_csv(io.StringIO(text))
            )
        except Exception as e:
            raise HTTPError(400, f"could not parse CSV: {e}") from None
        records = df.head(MAX_ECHO_RECORDS).to_dict(orient="records")
        return {
            "file": {
                "columns": list(map(str, df.columns)),
                "rows": int(len(df)),
                "records": records,
                "truncated": len(df) > MAX_ECHO_RECORDS,
            },
            "token": fields["token"],
        }

    # The multipart route has no pydantic body model for the schema
    # generator to introspect; document its form contract explicitly.
    create_file.__openapi__ = {
        "requestBody": {
            "required": True,
            "content": {
                "multipart/form-data": {
                    "schema": {
                        "type": "object",
                        "required": ["file", "token"],
                        "properties": {
                            "file": {"type": "string", "format": "binary"},
                            "token": {"type": "string"},
                        },
                    }
                }
            },
        }
    }

    @app.get("/healthz")
    async def healthz():
        import os

        import jax

        draining = bool(
            getattr(engine, "draining", False)
            or (batcher is not None and batcher.draining)
        )
        depth = (
            batcher.queue_depth if batcher is not None
            else getattr(engine, "queue_depth", 0)
        )
        role = getattr(engine, "replica_role", "mixed")
        models = app.state.get("models")
        multi = models is not None and len(models.ids()) > 1
        return {
            # "draining" the moment shutdown begins: the load balancer
            # stops routing here while in-flight streams finish.
            "status": "draining" if draining else "ok",
            # Role-split fleets (r18): which disaggregation role this
            # replica plays. Absent on mixed replicas — the default
            # topology's healthz is bit-identical to r17.
            **({"role": role} if role != "mixed" else {}),
            # Multi-model registry (r22): which model ids this process
            # serves (the router's per-model candidate filter reads
            # this). Absent in single-model mode — bit-identical to
            # r21.
            **({"models": models.describe()} if multi else {}),
            # Backpressure in the SAME poll the router/balancer already
            # makes for liveness (its threshold check still scrapes the
            # authoritative /metrics gauges on the poll cadence; this
            # rides along for one-shot dashboards and humans).
            "queue_depth": depth,
            "model": type(engine.model).__name__,
            "classes": list(engine.vocab.labels),
            "checkpoint": engine.meta,
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            # Which worker process answered — observability for
            # SO_REUSEPORT multi-worker serving (and the multiworker
            # test's distribution check).
            "pid": os.getpid(),
        }

    @app.get("/metrics")
    async def metrics():
        snap = registry.snapshot()
        if batcher is not None:
            snap["counters"]["batcher.device_calls"] = batcher.device_calls
            snap["counters"]["batcher.requests"] = batcher.requests
            snap["counters"]["batcher.timeouts"] = batcher.timeouts
            snap["counters"]["batcher.rejected"] = batcher.rejected
            snap["counters"]["batcher.shed_draining"] = (
                batcher.shed_draining
            )
            snap["counters"]["batcher.deadline_expired"] = (
                batcher.deadline_expired
            )
            # Gauges: the overload early-warning signals — queue depth
            # and in-flight batches are the first things to move when
            # offered load exceeds capacity.
            snap.setdefault("gauges", {})
            snap["gauges"]["batcher.queue_depth"] = batcher.queue_depth
            snap["gauges"]["batcher.inflight"] = batcher.inflight
            snap["gauges"]["batcher.draining"] = int(batcher.draining)
            snap["gauges"]["batcher.router_queue_depth"] = (
                batcher.router_queue_depth
            )
        elif engine.kind == "generative":
            snap["counters"]["generate.requests"] = engine.requests
            snap["counters"]["generate.batch_calls"] = engine.batch_calls
            snap["counters"]["generate.chunk_calls"] = engine.chunk_calls
            snap["counters"]["generate.rejected"] = engine.rejected
            snap["counters"]["generate.cancelled_batches"] = (
                engine.cancelled_batches
            )
            snap["counters"]["generate.compactions"] = engine.compactions
            snap["counters"]["generate.admitted"] = engine.admitted
            snap["counters"]["generate.growths"] = engine.growths
            snap["counters"]["generate.prefix_hits"] = engine.prefix_hits
            snap["counters"]["generate.prefix_misses"] = (
                engine.prefix_misses
            )
            snap["counters"]["generate.prefix_fallbacks"] = (
                engine.prefix_fallbacks
            )
            # Cold prefix prefills (distinct from misses, which a tier
            # restore also moves): the counter the router's affinity
            # A/B is pinned against — fleet-summed builds stay at one
            # per distinct prefix under affinity routing.
            snap["counters"]["generate.prefix_builds"] = (
                engine.prefix_builds
            )
            snap["counters"]["generate.prefill_chunks"] = (
                engine.prefill_chunks
            )
            snap["counters"]["generate.spec_rounds"] = engine.spec_rounds
            snap["counters"]["generate.spec_drafted"] = (
                engine.spec_drafted
            )
            snap["counters"]["generate.spec_accepted"] = (
                engine.spec_accepted
            )
            # One fused_calls tick per batch that dispatched at least
            # one fused-width decode chunk (r20: the whole-generation
            # programs are gone — fused traffic rides the unit queue).
            snap["counters"]["generate.fused_calls"] = engine.fused_calls
            # Page-native prefill + interleaving (r10). adopt_bytes is
            # exact dtype/shape arithmetic: 0 on the page-native path,
            # one full prefill copy per formation/admission on the
            # legacy adopt path — the gauge IS the claim.
            snap["counters"]["generate.prefill_adopt_bytes"] = (
                engine.prefill_adopt_bytes
            )
            snap["counters"]["generate.prefix_adopt_bytes"] = (
                engine.prefix_adopt_bytes
            )
            snap["counters"]["generate.kv_prefix_copy_fallback"] = (
                engine.kv_prefix_copy_fallback
            )
            snap["counters"]["generate.interleaved_prefills"] = (
                engine.interleaved_prefills
            )
            snap["counters"]["generate.spec_realign_table_ops"] = (
                engine.spec_realign_table_ops
            )
            snap["counters"]["generate.spec_realign_repacks"] = (
                engine.spec_realign_repacks
            )
            # Robustness layer (r12): what was shed at the door
            # (queue-full / infeasible deadline / draining), what
            # expired at which lifecycle stage, which brownout levers
            # engaged, and how many armed faults fired — the overload
            # POST-MORTEM block: these counters say WHY requests
            # failed, the gauges above say when it started.
            snap["counters"]["generate.shed_queue_full"] = (
                engine.shed_queue_full
            )
            snap["counters"]["generate.shed_deadline_infeasible"] = (
                engine.shed_deadline_infeasible
            )
            snap["counters"]["generate.shed_draining"] = (
                engine.shed_draining
            )
            snap["counters"]["generate.deadline_expired_queued"] = (
                engine.deadline_expired_queued
            )
            snap["counters"]["generate.deadline_expired_prefill"] = (
                engine.deadline_expired_prefill
            )
            snap["counters"]["generate.deadline_expired_decode"] = (
                engine.deadline_expired_decode
            )
            snap["counters"]["generate.brownout_spec_suppressed"] = (
                engine.brownout_spec_suppressed
            )
            snap["counters"]["generate.brownout_tokens_clamped"] = (
                engine.brownout_tokens_clamped
            )
            snap["counters"]["generate.faults_injected"] = (
                engine.faults_injected
            )
            # Continuous-batching scheduler v2 (r15; default-on and
            # the ONE execution model since r20): per-unit-type
            # dispatch counters over the typed-unit queue — the
            # counters the concurrency claims are asserted from
            # (interleaving = two lanes' units both moving in one
            # window, never wall-clock). sched_units_admit ticks as
            # lanes install staged joiners at unit boundaries (the
            # r20 in-lane admission path).
            snap["counters"]["generate.sched_units_prefill"] = (
                engine.sched_units_prefill
            )
            snap["counters"]["generate.sched_units_decode"] = (
                engine.sched_units_decode
            )
            snap["counters"]["generate.sched_units_spec"] = (
                engine.sched_units_spec
            )
            snap["counters"]["generate.sched_units_admit"] = (
                engine.sched_units_admit
            )
            snap["counters"]["generate.sched_units_compact"] = (
                engine.sched_units_compact
            )
            snap["counters"]["generate.sched_deadline_preempts"] = (
                engine.sched_deadline_preempts
            )
            snap["counters"]["generate.sched_pages_deferred"] = (
                engine.sched_pages_deferred
            )
            # Multi-model + multi-tenant (r22): scoring dispatches
            # that rode this engine's unit queue, group starts
            # deferred on a TENANT quota (pages / adapter slots —
            # distinct from the pool-wide deferral above), and
            # tenant-scoped brownout clamps (engages before the
            # fleet-wide rung 1).
            snap["counters"]["generate.sched_units_score"] = (
                engine.sched_units_score
            )
            snap["counters"]["generate.sched_tenant_pages_deferred"] = (
                engine.sched_tenant_pages_deferred
            )
            snap["counters"][
                "generate.sched_tenant_adapters_deferred"
            ] = engine.sched_tenant_adapters_deferred
            snap["counters"]["generate.brownout_tenant_clamped"] = (
                engine.brownout_tenant_clamped
            )
            snap.setdefault("gauges", {})
            snap["gauges"]["generate.sched_queue_depth"] = (
                engine.sched_queue_depth
            )
            snap["gauges"]["generate.sched_batches_live"] = (
                engine.sched_batches_live
            )
            snap["gauges"]["generate.sched_batches_live_max"] = (
                engine.sched_batches_live_max
            )
            # Cross-lane head-of-line bound (r20): the longest run of
            # consecutive units one lane dispatched while another was
            # live — ≤ the alternation floor means fused traffic
            # stalls concurrent lanes by at most ONE fused-chunk
            # dispatch.
            snap["gauges"]["generate.sched_lane_stall_max"] = (
                engine.sched_lane_stall_max
            )
            # Fleet pressure the fronting router last reported
            # (x-mlapi-router-depth; 0 for direct traffic).
            snap["gauges"]["generate.router_queue_depth"] = (
                engine.router_queue_depth
            )
            snap["gauges"]["generate.draining"] = int(engine.draining)
            snap["gauges"]["generate.queue_depth"] = engine.queue_depth
            # Chunked-prefill interleaving: chunks still queued for
            # the in-progress long-prompt joiner (0 when idle), and
            # the worst consecutive prefill-dispatch run live decode
            # rows ever waited behind (the design pins it at 1).
            snap["gauges"]["generate.prefill_chunk_queue_depth"] = (
                engine.prefill_chunk_queue_depth
            )
            snap["gauges"]["generate.interleave_max_stall"] = (
                engine.interleave_max_stall
            )
            # TTFT / inter-token latency summaries from the engine's
            # delivery-time reservoirs (ms; null until traffic).
            for k, v in engine.latency.summary().items():
                snap["gauges"][f"generate.{k}"] = v
            # Deterministic per-slot KV bytes at the default
            # bucket/tier (addressable_shards nbytes) — the committed
            # int8-KV number; kv_quant itself rides /healthz meta.
            # Warmup precomputes it, but a scrape that arrives FIRST
            # would build a largest-bucket cache on-device — that
            # fence goes through the executor, never the event loop
            # (mlapi-lint MLA008, caught r19).
            snap["gauges"]["generate.kv_cache_bytes_per_slot"] = (
                await asyncio.get_running_loop().run_in_executor(
                    None, engine.kv_cache_slot_bytes
                )
            )
            # Modeled HBM read per decode step for the ACTIVE (cache
            # format, decode impl) pair — the production-observable
            # form of the int8 flash-decode read saving (exact host
            # arithmetic, no device work).
            snap["gauges"]["generate.decode_bytes_per_step"] = (
                engine.decode_bytes_per_step()
            )
            # Same accounting for one multi-token extend chunk's read
            # (chunked prefill / admission / speculative verify): the
            # int8 flash saving applies to every token the server
            # processes, amortized per chunk instead of per step.
            snap["gauges"]["generate.extend_bytes_per_chunk"] = (
                engine.extend_bytes_per_chunk()
            )
            if getattr(engine, "pool", None) is not None:
                # Paged KV pool observability: capacity headroom
                # (total vs in_use), how much of the live footprint is
                # prefix sharing (shared), and the utilization ratio —
                # the "do I need more --kv-pages" dashboard block.
                snap["gauges"]["generate.kv_pages_total"] = (
                    engine.kv_pages_total
                )
                snap["gauges"]["generate.kv_pages_in_use"] = (
                    engine.kv_pages_in_use
                )
                snap["gauges"]["generate.kv_pages_shared"] = (
                    engine.kv_pages_shared
                )
                snap["gauges"]["generate.kv_page_utilization"] = (
                    engine.kv_page_utilization
                )
                snap["gauges"]["generate.kv_page_bytes"] = (
                    engine.kv_page_bytes()
                )
                # Prefix-entry page-set evictions under pool pressure
                # (alloc-pressure + brownout evict_idle): with the
                # host tier attached these are routine, recoverable
                # spills, so the per-event log dropped to debug and
                # THIS counter is the observable.
                snap["counters"]["generate.kv_entry_evictions"] = (
                    engine.pool.entry_evictions
                )
            if getattr(engine, "kv_tier", None) is not None:
                # Hierarchical KV tier (r13): spill/restore traffic
                # and the tier's occupancy. All byte counters are the
                # kv_tree_bytes closed form per blob (exact dtype/
                # shape arithmetic), never wall-clock — restore_hits
                # moving while prefix builds stay flat IS the
                # saved-prefill claim.
                snap["counters"]["generate.kv_prefix_restore_hits"] = (
                    engine.kv_prefix_restore_hits
                )
                snap["counters"]["generate.kv_prefix_restore_misses"] = (
                    engine.kv_prefix_restore_misses
                )
                snap["counters"]["generate.kv_prefix_restore_bytes"] = (
                    engine.kv_prefix_restore_bytes
                )
                snap["counters"][
                    "generate.kv_prefix_restore_failures"
                ] = engine.kv_prefix_restore_failures
                snap["counters"]["generate.kv_prefix_spill_count"] = (
                    engine.kv_prefix_spill_count
                )
                snap["counters"]["generate.kv_prefix_spill_bytes"] = (
                    engine.kv_prefix_spill_bytes
                )
                snap["counters"]["generate.kv_prefix_spill_failures"] = (
                    engine.kv_prefix_spill_failures
                )
                snap["counters"]["generate.kv_tier_evictions"] = (
                    engine.kv_tier_evictions
                )
                snap["gauges"]["generate.kv_tier_bytes_in_use"] = (
                    engine.kv_tier_bytes_in_use
                )
                snap["gauges"]["generate.kv_tier_entries"] = (
                    engine.kv_tier_entries
                )
            if getattr(engine, "kv_peer", None) is not None:
                # Peer-to-peer prefix-KV fetch (r17): wire traffic in
                # and out, exact payload-byte arithmetic per blob
                # (never wall-clock). fetch_hits moving while
                # prefix_builds stays flat IS the transferred-warmth
                # claim; the router SUMS these across replicas like
                # every other generate counter, so the fleet dashboard
                # reads total KV moved peer-to-peer directly.
                snap["counters"]["generate.kv_peer_fetch_hits"] = (
                    engine.kv_peer_fetch_hits
                )
                snap["counters"]["generate.kv_peer_fetch_misses"] = (
                    engine.kv_peer_fetch_misses
                )
                snap["counters"]["generate.kv_peer_fetch_bytes"] = (
                    engine.kv_peer_fetch_bytes
                )
                snap["counters"]["generate.kv_peer_fetch_failures"] = (
                    engine.kv_peer_fetch_failures
                )
                snap["counters"]["generate.kv_peer_serve_count"] = (
                    engine.kv_peer_serve_count
                )
                snap["counters"]["generate.kv_peer_serve_bytes"] = (
                    engine.kv_peer_serve_bytes
                )
            if getattr(engine, "kv_push", None) is not None:
                # Prefill/decode disaggregation (r18): chunk-push
                # traffic out (prefill role) and in (decode role),
                # exact payload-byte arithmetic per chunk — never
                # wall-clock. kv_push_applied moving while
                # prefix_builds AND prefill_chunks stay flat IS the
                # zero-decode-side-prefill claim; kv_push_fallbacks
                # counts the degradations (failed/incomplete/drifted
                # transfers served by the cold prefill instead).
                # Absent on mixed replicas — the default topology's
                # /metrics is bit-identical to r17.
                snap["counters"]["generate.kv_push_sent"] = (
                    engine.kv_push_sent
                )
                snap["counters"]["generate.kv_push_send_failures"] = (
                    engine.kv_push_send_failures
                )
                snap["counters"]["generate.kv_push_bytes_sent"] = (
                    engine.kv_push_bytes_sent
                )
                snap["counters"]["generate.kv_push_recv"] = (
                    engine.kv_push_recv
                )
                snap["counters"]["generate.kv_push_recv_failures"] = (
                    engine.kv_push_recv_failures
                )
                snap["counters"]["generate.kv_push_bytes_recv"] = (
                    engine.kv_push_bytes_recv
                )
                snap["counters"]["generate.kv_push_applied"] = (
                    engine.kv_push_applied
                )
                snap["counters"]["generate.kv_push_bytes_applied"] = (
                    engine.kv_push_bytes_applied
                )
                snap["counters"]["generate.kv_push_fallbacks"] = (
                    engine.kv_push_fallbacks
                )
            if getattr(engine, "adapters", None) is not None:
                # Many-adapter LoRA serving: the slot pool, the host
                # store, and the fetch/application traffic. All byte
                # gauges are exact dtype/shape arithmetic, never
                # wall-clock — adapter_resident_bytes growing by
                # EXACTLY adapter_slot_bytes per resident tenant over
                # the base footprint IS the HBM-amortization claim,
                # and adapter_fetch_hits moving while the local
                # store's entries grow (prefix_builds-style) is the
                # transferred-warmth claim, adapter tier.
                snap["counters"]["generate.adapter_fetch_hits"] = (
                    engine.adapter_fetch_hits
                )
                snap["counters"]["generate.adapter_fetch_misses"] = (
                    engine.adapter_fetch_misses
                )
                snap["counters"]["generate.adapter_fetch_bytes"] = (
                    engine.adapter_fetch_bytes
                )
                snap["counters"]["generate.adapter_fetch_failures"] = (
                    engine.adapter_fetch_failures
                )
                snap["counters"]["generate.adapter_serve_count"] = (
                    engine.adapter_serve_count
                )
                snap["counters"]["generate.adapter_serve_bytes"] = (
                    engine.adapter_serve_bytes
                )
                snap["counters"]["generate.adapter_installs"] = (
                    engine.adapter_installs
                )
                snap["counters"]["generate.adapter_evictions"] = (
                    engine.adapter_evictions
                )
                snap["counters"]["generate.adapter_grouped_batches"] = (
                    engine.adapter_grouped_batches
                )
                snap["counters"]["generate.adapter_gathered_batches"] = (
                    engine.adapter_gathered_batches
                )
                snap["counters"]["generate.adapter_store_evictions"] = (
                    engine.adapter_store_evictions
                )
                snap["counters"]["generate.sched_adapters_deferred"] = (
                    engine.sched_adapters_deferred
                )
                snap["gauges"]["generate.adapter_slots_total"] = (
                    engine.adapter_slots_total
                )
                snap["gauges"]["generate.adapter_slots_in_use"] = (
                    engine.adapter_slots_in_use
                )
                snap["gauges"]["generate.adapter_slot_bytes"] = (
                    engine.adapter_slot_bytes
                )
                snap["gauges"]["generate.adapter_resident_bytes"] = (
                    engine.adapter_resident_bytes
                )
                snap["gauges"]["generate.adapter_store_bytes_in_use"] = (
                    engine.adapter_store_bytes_in_use
                )
                snap["gauges"]["generate.adapter_store_entries"] = (
                    engine.adapter_store_entries
                )
        # Per-model counter family (r22): ONLY in multi-model mode —
        # a one-entry registry's /metrics stays bit-identical to r21.
        # Each entry exports the small per-model dashboard row; the
        # default model's full counter block above is unchanged.
        models = app.state.get("models")
        if models is not None and len(models.ids()) > 1:
            snap.setdefault("gauges", {})
            score_paths = app.state.get("score_paths") or {}
            for mid, eng in models.items():
                pfx = f"model.{mid}"
                if eng.kind == "generative":
                    snap["counters"][f"{pfx}.requests"] = eng.requests
                    snap["counters"][f"{pfx}.rejected"] = eng.rejected
                    snap["counters"][f"{pfx}.sched_units_decode"] = (
                        eng.sched_units_decode
                    )
                    snap["counters"][f"{pfx}.sched_units_score"] = (
                        eng.sched_units_score
                    )
                    snap["gauges"][f"{pfx}.queue_depth"] = (
                        eng.queue_depth
                    )
                    for k, v in eng.latency.summary().items():
                        snap["gauges"][f"{pfx}.{k}"] = v
                else:
                    sp = score_paths.get(mid)
                    if sp is None:
                        continue
                    snap["counters"][f"{pfx}.requests"] = sp.requests
                    snap["counters"][f"{pfx}.device_calls"] = (
                        sp.device_calls
                    )
                    # Dispatches that rode a co-resident generative
                    # engine's unit queue as score units (vs the pool
                    # backend): sched_dispatches ≈ device_calls IS
                    # the one-scheduler claim.
                    snap["counters"][f"{pfx}.sched_dispatches"] = (
                        sp.sched_dispatches
                    )
                    snap["counters"][f"{pfx}.rejected"] = sp.rejected
                    snap["counters"][f"{pfx}.deadline_expired"] = (
                        sp.deadline_expired
                    )
                    snap["gauges"][f"{pfx}.queue_depth"] = (
                        sp.queue_depth
                    )
                    for k, v in sp.latency.summary().items():
                        snap["gauges"][f"{pfx}.{k}"] = v
        # Per-tenant pressure block (r22): live depth plus the quota
        # deferral / brownout history — only tenants with any history
        # appear, so an untenanted deployment's scrape is unchanged.
        tenants = app.state.get("tenants")
        if tenants is not None:
            snap.setdefault("gauges", {})
            for t, row in sorted(tenants.snapshot().items()):
                pfx = f"tenant.{t or 'anonymous'}"
                snap["gauges"][f"{pfx}.depth"] = row["depth"]
                snap["counters"][f"{pfx}.deferrals"] = row["deferrals"]
                snap["counters"][f"{pfx}.brownouts"] = row["brownouts"]
        return snap

    return app
