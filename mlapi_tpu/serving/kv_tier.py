"""Host-RAM (optionally disk-backed) spill tier under the KV page pool.

The r09 page pool evicts unreferenced prefix page sets LRU-first and
used to DISCARD them — every re-arrival of a popular prefix then paid
a full prefill. This module is the hierarchical-memory move under that
eviction: the victim's pages are gathered to host as numpy blobs in
their STORED format (int8 payload + scales, or bf16/f32 — whatever
the cache format already is, so int8 KV halves the spill bandwidth
for free) and kept under an LRU bytes budget. A later miss restores
by ``device_put`` into freshly allocated pages — zero prefill FLOPs,
byte-identical to the original adopt.

Wired at exactly two seams, both outside this file:

- **Spill** — ``PagePool._spill_and_release`` gathers the victim
  entry's pool rows via its page set and registers the blob here
  BEFORE freeing the pages (plus the same hook from
  ``PrefixCache.entry``'s own LRU eviction, which spills from the
  entry's contiguous KV — the identical bytes — because registration
  threads must never read pool arrays the decode thread may have
  donated).
- **Restore** — ``PrefixCache.entry`` / ``paged_entry`` consult the
  tier on a device-cache miss; a hit rebuilds the entry / repopulates
  pool pages with ref-count/COW semantics unchanged on-device.

Plus, since r17, the fleet seam (``serving/kv_peer.py``): the blob is
the transferable KV unit between replicas — a peer's fetch serves
these same stored-format bytes over the wire, and a fetched blob is
:meth:`KVTier.stage`-d here so the local restore path applies it
exactly like a local spill.

Everything here is host metadata + numpy under one lock; no jax
arrays are held (a blob pins host RAM or disk, never HBM). Byte
accounting is exact dtype/shape arithmetic (``ops/quant
.kv_tree_bytes`` closed form: a spilled set costs
``num_pages x kv_page_bytes``), never wall-clock.

Disk mode (``disk_dir``): blob payloads live as ``.npz`` files and
only the index stays in RAM; the LRU bytes budget then bounds disk
use. The index is per-process — files from a previous run are inert
(restores validate shapes/page size against the live pool and treat
any mismatch as a miss).
"""

from __future__ import annotations

import collections
import os
import threading

import numpy as np

from mlapi_tpu.serving import faults
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.kv_tier")


class KVTierBlob:
    """One spilled prefix page set, fully host-resident: the per-layer
    ``{leaf: [num_pages, page, ...]}`` numpy payload in the cache's
    stored format, plus the entry metadata needed to rebuild a
    :class:`_PrefixEntry` without a prefill (``bucket``/``lo``/``used``
    may be ``None`` if the entry was never registered — pool-page
    restore still works; entry rebuild treats that as a miss)."""

    __slots__ = (
        "fp", "payload", "page", "num_pages", "nbytes",
        "bucket", "lo", "used",
    )

    def __init__(self, fp, payload, page, nbytes, bucket, lo, used):
        self.fp = fp
        self.payload = payload
        self.page = int(page)
        first = next(iter(next(iter(payload.values())).values()))
        self.num_pages = int(first.shape[0])
        self.nbytes = int(nbytes)
        self.bucket = bucket
        self.lo = lo
        self.used = used


class _Stored:
    """Index record: payload in RAM or a path on disk, plus the
    metadata that survives either way."""

    __slots__ = ("payload", "path", "page", "nbytes",
                 "bucket", "lo", "used")

    def __init__(self, payload, path, page, nbytes, bucket, lo, used):
        self.payload = payload      # None when disk-backed
        self.path = path            # None when RAM-resident
        self.page = page
        self.nbytes = nbytes
        self.bucket = bucket
        self.lo = lo
        self.used = used


def payload_bytes(payload: dict) -> int:
    """Exact blob bytes from dtype/shape arithmetic — the same closed
    form as ``ops/quant.kv_tree_bytes`` applied to the numpy tree (an
    ``n``-page set costs exactly ``n x kv_page_bytes(model, page)``)."""
    return sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for layer in payload.values()
        for a in layer.values()
    )


def payload_from_contiguous(kv, page: int) -> dict:
    """A contiguous ``[1, P]`` cache pytree (a prefix entry's KV, on
    device) → the page-shaped ``[ceil(P/page), page, ...]`` numpy
    payload, zero-padded past ``P``. Byte-identical to gathering the
    entry's adopted pool rows for every slot ``< P`` (the adopt
    scatter wrote exactly these values; slots past ``P`` are never
    read) — and safe from ANY thread, because the entry's contiguous
    KV is never donated."""
    out: dict = {}
    for ln, layer in kv.items():
        out[ln] = {}
        for name, leaf in layer.items():
            a = np.asarray(leaf)            # [1, P, ...] device_get
            p = a.shape[1]
            n = -(-p // page)
            if n * page != p:
                pad = np.zeros(
                    (1, n * page - p) + a.shape[2:], a.dtype
                )
                a = np.concatenate([a, pad], axis=1)
            out[ln][name] = np.ascontiguousarray(
                a.reshape((n, page) + a.shape[2:])
            )
    return out


class KVTier:
    """LRU bytes-budgeted store of spilled prefix page sets, keyed by
    prefix fingerprint. Thread-safe: registration threads (entry
    build/restore, dict-LRU spill) and the decode thread (pool spill,
    page restore) mutate it concurrently."""

    def __init__(self, max_bytes: int, disk_dir: str | None = None):
        if max_bytes <= 0:
            raise ValueError(
                f"kv_tier_bytes must be > 0 to enable the tier, got "
                f"{max_bytes}"
            )
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
            self._sweep_stale(disk_dir)
        self._lock = threading.Lock()
        # fp -> _Stored, LRU-ordered (front = coldest).
        self._blobs: collections.OrderedDict = collections.OrderedDict()
        self._bytes = 0
        self._seq = 0
        # Entry metadata noted by the PrefixCache at build/restore time
        # (the pool knows page ids, not buckets); bounded LRU — metas
        # are a few ints each, the cap only guards unbounded churn.
        self._meta: collections.OrderedDict = collections.OrderedDict()
        self._meta_cap = 4096
        # Counters (exported via the engine's /metrics block; bytes
        # are the exact closed form, never wall-clock).
        self.spill_count = 0
        self.spill_bytes = 0
        self.spill_failures = 0
        self.restore_hits = 0
        self.restore_misses = 0
        self.restore_bytes = 0
        self.restore_failures = 0
        self.evictions = 0

    @staticmethod
    def _sweep_stale(disk_dir: str) -> None:
        """Unlink blob files left by DEAD former owners. Filenames are
        pid-scoped and the index is per-process, so files from a
        previous run are unreachable — without this sweep a restart
        loop would accumulate up to one full bytes budget of dead
        files per run. A file whose owner pid is still alive (a
        sibling ``--workers`` process sharing the dir) is left
        alone; so is anything this process cannot signal (EPERM: not
        ours to judge) or cannot parse (not ours at all)."""
        for name in os.listdir(disk_dir):
            if not (name.startswith("kvtier-") and name.endswith(".npz")):
                continue
            try:
                pid = int(name.split("-")[1])
            except (IndexError, ValueError):
                continue
            if pid == os.getpid():
                continue
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(os.path.join(disk_dir, name))
                    _log.debug("swept stale tier blob %s", name)
                except OSError:
                    pass
            except OSError:
                pass  # EPERM etc.: a live process we can't signal

    # -- accounting ----------------------------------------------------
    @property
    def bytes_in_use(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._blobs)

    # -- entry metadata ------------------------------------------------
    def note_meta(self, fp, *, bucket: int, lo: int, used: int) -> None:
        """Record the entry-rebuild metadata for ``fp`` (called by the
        PrefixCache whenever it creates or restores an entry — the ONE
        place that knows bucket/lo/used). Spills attach it so a later
        ``entry()`` miss can rebuild without a prefill."""
        with self._lock:
            self._meta[fp] = (int(bucket), int(lo), int(used))
            self._meta.move_to_end(fp)
            while len(self._meta) > self._meta_cap:
                self._meta.popitem(last=False)

    # -- spill ---------------------------------------------------------
    def spill(self, fp, payload: dict, page: int) -> int:
        """Register a spilled page set (replacing any prior blob for
        ``fp``), evicting LRU blobs past the bytes budget. Returns the
        blob's exact bytes. The ``tier_spill`` fault point fires FIRST
        — an injected raise leaves the tier untouched and the caller
        falls back to the pre-tier discard. Disk mode registers the
        blob RAM-resident first and moves the payload to its ``.npz``
        AFTER releasing the lock — the (multi-MB, slow-disk) write
        must not block concurrent lookups/spills; the transient RAM
        copy is bounded by one blob and disappears with the swap (a
        blob replaced or evicted mid-write just unlinks the fresh
        file)."""
        faults.fire("tier_spill")
        return self._register(fp, payload, page, count_spill=True)

    def stage(self, fp, payload: dict, page: int, *,
              bucket: int, lo: int, used: int) -> int:
        """Register a PEER-FETCHED blob (``serving/kv_peer.py``) so
        the dispatch-thread paged formation finds it locally and
        restores through the same alloc-first ``restore_entry`` path
        every tier blob takes. Identical LRU/budget/disk mechanics to
        :meth:`spill`, but no ``tier_spill`` fault fire and no
        spill counters — nothing was evicted from THIS replica's
        device; the ``kv_peer_fetch_*`` counters carry the story.
        The peer blob's entry metadata rides in explicitly (the wire
        header is the one place that knows it here)."""
        self.note_meta(fp, bucket=bucket, lo=lo, used=used)
        return self._register(fp, payload, page, count_spill=False)

    def _register(self, fp, payload: dict, page: int,
                  count_spill: bool) -> int:
        nbytes = payload_bytes(payload)
        with self._lock:
            meta = self._meta.get(fp)
            bucket, lo, used = meta if meta else (None, None, None)
            old = self._blobs.pop(fp, None)
            if old is not None:
                self._discard_locked(old)
            if nbytes > self.max_bytes:
                # Can't ever fit: count it as an eviction of itself
                # rather than silently thrashing the whole tier out.
                self.evictions += 1
                _log.debug(
                    "tier blob (%d bytes) exceeds the %d-byte budget; "
                    "not stored", nbytes, self.max_bytes,
                )
                return nbytes
            path = None
            if self.disk_dir:
                path = os.path.join(
                    self.disk_dir, f"kvtier-{os.getpid()}-{self._seq}.npz"
                )
                self._seq += 1
            stored = _Stored(
                payload, None, int(page), nbytes, bucket, lo, used
            )
            self._blobs[fp] = stored
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._blobs) > 1:
                _, victim = self._blobs.popitem(last=False)  # LRU
                self._discard_locked(victim)
                self.evictions += 1
            if count_spill:
                self.spill_count += 1
                self.spill_bytes += nbytes
        if path is not None:
            try:
                np.savez(
                    path,
                    **{
                        f"{ln}|{name}": a
                        for ln, layer in payload.items()
                        for name, a in layer.items()
                    },
                )
            except Exception as e:
                # Disk refused: the blob simply stays RAM-resident —
                # still restorable, budget still enforced.
                _log.debug("tier disk write failed (%s); RAM blob", e)
                return nbytes
            with self._lock:
                live = self._blobs.get(fp)
                if live is stored and live.payload is payload:
                    live.path = path
                    live.payload = None
                else:
                    # Replaced or evicted while writing: the file is
                    # an orphan — drop it, the index never saw it.
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        return nbytes

    def drop(self, fp) -> None:
        """Forget ``fp``'s blob (no-op if absent): a restore proved it
        can never apply to the live pool/model (geometry or metadata
        drift — e.g. a disk blob from a previous run with a different
        page size), so keeping it would repeat the failed validation
        on every miss. Distinct from LRU eviction: not counted there
        (`evictions` measures budget pressure, not invalidation)."""
        with self._lock:
            stored = self._blobs.pop(fp, None)
            if stored is not None:
                self._discard_locked(stored)
                _log.debug("dropped inapplicable tier blob for %r", fp)

    def _discard_locked(self, stored: _Stored) -> None:
        self._bytes -= stored.nbytes
        if stored.path is not None:
            try:
                os.unlink(stored.path)
            except OSError:
                pass

    def fingerprints(self) -> list:
        """A snapshot of the stored fingerprints (for the peer-serve
        digest scan — ``serving/kv_peer.py``; blob counts are bounded
        by the bytes budget, so a linear scan is cheap and runs on an
        executor thread anyway)."""
        with self._lock:
            return list(self._blobs)

    # -- restore -------------------------------------------------------
    def lookup(self, fp, count: bool = True) -> KVTierBlob | None:
        """The blob for ``fp`` (LRU-touched), payload loaded back to
        RAM if disk-backed; ``None`` counts a restore miss (pass
        ``count=False`` for reads that are NOT restore attempts — the
        peer-serve path, which must not pollute the restore counters
        the r13 savings story is asserted from). The blob stays
        resident — a restore is a cache READ, so a re-eviction of the
        restored pages re-spills identical bytes (or cheaply replaces
        them)."""
        with self._lock:
            stored = self._blobs.get(fp)
            if stored is None:
                if count:
                    self.restore_misses += 1
                return None
            self._blobs.move_to_end(fp)
            payload = stored.payload
            path = stored.path
            page = stored.page
            nbytes = stored.nbytes
            bucket, lo, used = stored.bucket, stored.lo, stored.used
        if payload is None:
            try:
                with np.load(path) as z:
                    payload = {}
                    for key in z.files:
                        ln, name = key.split("|", 1)
                        payload.setdefault(ln, {})[name] = z[key]
            except Exception as e:
                # A vanished/corrupt file is a miss, not a crash: drop
                # the index entry and let the caller go cold — but
                # only if it is still the record WE read. A concurrent
                # re-spill of the same fp may have replaced it (and
                # unlinked our file, which is exactly why the load
                # failed); the fresh blob must survive.
                _log.debug("tier disk blob unreadable (%s); dropping", e)
                with self._lock:
                    if self._blobs.get(fp) is stored:
                        self._blobs.pop(fp)
                        self._discard_locked(stored)
                    if count:
                        self.restore_misses += 1
                return None
        return KVTierBlob(fp, payload, page, nbytes, bucket, lo, used)

    def count_restore(self, blob: KVTierBlob) -> None:
        """A blob was successfully applied (pool pages repopulated or
        an entry rebuilt): count the hit and its exact bytes."""
        with self._lock:
            self.restore_hits += 1
            self.restore_bytes += blob.nbytes

    def count_spill_failure(self) -> None:
        """A spill seam degraded to the pre-tier discard — counted
        here, under the lock, because spill failures fire from both
        the decode thread (pool eviction) and registration threads
        (dict-LRU eviction); an unsynchronized ``+=`` could drop the
        very increments the fault-matrix degradation story reads."""
        with self._lock:
            self.spill_failures += 1

    def count_restore_failure(self) -> None:
        """A restore seam fell back to the cold path — same locking
        rationale as :meth:`count_spill_failure`."""
        with self._lock:
            self.restore_failures += 1
