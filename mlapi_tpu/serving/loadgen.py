"""Closed-loop HTTP load generator (raw asyncio sockets).

Drives the north-star measurement (``BASELINE.json:2``:
requests/sec/chip and p50 on ``/predict``). Off-the-shelf Python
HTTP clients cost ~0.6-3 ms of client CPU per request — an order of
magnitude above the server's own 0.08 ms/request — so measuring
through them benchmarks the client, not the server. This generator
writes requests and parses responses directly on persistent
keep-alive connections.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field


@dataclass
class LoadResult:
    requests: int
    errors: int
    wall_seconds: float
    latencies_ms: list[float] = field(repr=False, default_factory=list)
    # Completions per payload template (mixed-workload runs): template
    # index -> count. Closed-loop workers complete cheap requests at a
    # higher rate, so aggregate metrics must weight by ACTUAL
    # completions, not the offered mix.
    per_template: dict = field(default_factory=dict)

    @property
    def throughput(self) -> float:
        return self.requests / self.wall_seconds if self.wall_seconds else 0.0

    def quantile(self, q: float) -> float | None:
        from mlapi_tpu.utils.metrics import nearest_rank

        return nearest_rank(self.latencies_ms, q)

    def summary(self) -> dict:
        return {
            "requests": self.requests,
            "errors": self.errors,
            "wall_seconds": round(self.wall_seconds, 3),
            "throughput_rps": round(self.throughput, 1),
            "p50_ms": self.quantile(0.50),
            "p90_ms": self.quantile(0.90),
            "p99_ms": self.quantile(0.99),
        }


async def _worker(
    host: str,
    port: int,
    request_bytes: bytes,
    stop_at: float,
    result: LoadResult,
    template_idx: int = 0,
) -> None:
    reader, writer = await asyncio.open_connection(host, port)
    try:
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            writer.write(request_bytes)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            # Fast path is hardwired to the in-repo server's output
            # (HTTP/1.1 status line, lowercase headers); anything else
            # gets a tolerant parse instead of a silent misparse/stall.
            if head.startswith(b"HTTP/1."):
                status = int(head[9:12])  # b"HTTP/1.1 200 ..."
            else:
                raise RuntimeError(f"not an HTTP/1.x response: {head[:16]!r}")
            i = head.find(b"content-length:")
            if i < 0:  # mixed-case emitter (not this repo's server)
                i = head.lower().find(b"content-length:")
            if i >= 0:
                j = head.index(b"\r\n", i)
                await reader.readexactly(int(head[i + 15 : j]))
            elif b"transfer-encoding" in head.lower():
                raise RuntimeError(
                    "loadgen does not speak chunked responses; point it at "
                    "a non-streaming route"
                )
            result.latencies_ms.append((time.perf_counter() - t0) * 1e3)
            result.requests += 1
            if status != 200:
                result.errors += 1
            else:
                # Only SUCCESSFUL completions count toward the
                # per-template tally — a shed/errored request must not
                # credit its tokens to throughput.
                result.per_template[template_idx] = (
                    result.per_template.get(template_idx, 0) + 1
                )
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass


def build_request(
    host: str, path: str, payload: dict | None = None, method: str | None = None
) -> bytes:
    body = b"" if payload is None else json.dumps(payload).encode()
    method = method or ("POST" if payload is not None else "GET")
    head = (
        f"{method} {path} HTTP/1.1\r\nhost: {host}\r\n"
        f"content-type: application/json\r\ncontent-length: {len(body)}\r\n\r\n"
    )
    return head.encode() + body


async def run_load(
    host: str,
    port: int,
    path: str,
    *,
    payload: dict | list[dict] | None = None,
    concurrency: int = 64,
    duration_s: float = 5.0,
) -> LoadResult:
    """``concurrency`` persistent connections, each a closed loop, for
    ``duration_s`` seconds. A list ``payload`` is distributed
    round-robin across the workers (mixed-workload benching)."""
    if isinstance(payload, list):
        requests = [build_request(host, path, p) for p in payload]
    else:
        requests = [build_request(host, path, payload)]
    result = LoadResult(requests=0, errors=0, wall_seconds=0.0)
    stop_at = time.perf_counter() + duration_s
    t0 = time.perf_counter()
    outcomes = await asyncio.gather(
        *(
            _worker(
                host, port, requests[i % len(requests)], stop_at, result,
                i % len(requests),
            )
            for i in range(concurrency)
        ),
        return_exceptions=True,
    )
    # A dead connection costs that worker's remaining loop, not the
    # whole run — samples from the other workers still count.
    result.errors += sum(1 for o in outcomes if isinstance(o, BaseException))
    result.wall_seconds = time.perf_counter() - t0
    return result
