"""The batch-1 fused single-stream fast path of generative serving.

One :class:`FusedSinglePath` per :class:`TextGenerationEngine`: it
owns the warmed-shape set and decides, per solo non-streaming request,
whether the WHOLE generation runs as one XLA program
(``models.gpt.generate_tier_fn`` / ``ops.speculative.fused_spec_fn``)
instead of chunked dispatches — the single-stream RTT-floor lever
through a high-RTT attach. Split out of ``engine.py`` (r04 VERDICT
"Next" #7); the eligibility and byte-identity contract is documented
on :meth:`try_run`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class FusedSinglePath:
    def __init__(self, engine):
        self.eng = engine
        # (bucket, tier, "plain"|"spec"|"spec_sampled") fused programs
        # proven compiled — strict mode takes the fast path only for
        # these (an unwarmed fused shape falls back to the chunked
        # programs rather than stalling on a remote compile).
        self.warmed: set = set()

    def tiers(self) -> list:
        """The fused-program output-tier ladder, ascending: powers of
        two (of ``chunk``) from the DEFAULT budget's tier up to the
        ``fused_max_new`` cap's. The floor is the default tier because
        ``n_actual`` is traced — the default-tier program already
        serves every smaller budget, so smaller tiers would only
        multiply compiles. ONE definition shared by the request path
        (``try_run``) and the warm grid (``warm``):
        strict mode silently falls back to chunked on a warm-set miss,
        so the two must be tier-identical by construction."""
        eng = self.eng
        t = eng.default_tier
        tiers = [t]
        while t < eng.fused_max_new:
            t *= 2
            tiers.append(t)
        return tiers

    def _spec_headroom(self, bucket: int, tier: int):
        """Fused speculation's window check, ONE definition for the
        run paths and the warm grids (strict mode rejects any shape
        the warm grid skipped, so eligibility must match exactly):
        returns ``(fits, k)`` where ``k`` is the per-tier draft depth
        and ``fits`` says ``bucket + tier + k + 1`` slots fit BOTH
        model windows."""
        eng = self.eng
        k = max(1, min(eng.spec_k, tier))
        need = bucket + tier + k + 1
        fits = (
            eng.draft_model is not None
            and need <= eng.model.max_positions
            and need <= eng.draft_model.max_positions
        )
        return fits, k

    def try_run(self, r, admit: bool) -> bool:
        """Batch-1 fast path: run ``r``'s WHOLE generation as one XLA
        program (``generate_tier_fn``, or ``fused_spec_fn`` with the
        draft) — one dispatch + one readback, the single-stream RTT
        floor through a tunneled attach. Returns ``False`` to fall
        through to the chunked path: streaming consumers, prefix rows,
        long (chunked-prefill) prompts, budgets past ``fused_max_new``,
        deadlined requests, unwarmed shapes in strict mode, and
        batches with staged joiners all decode chunked exactly as
        before. The emitted
        stream is byte-identical to the chunked path (same pads, same
        per-token PRNG stream indices; greedy speculation is
        argmax-exact), so which path served a request is invisible in
        the response.

        One fused run is one uninterruptible device program — a
        request arriving mid-run waits for it (bounded by
        ``fused_max_new``), the price of removing per-chunk
        dispatches. Mirrors the host spec phase's yield discipline at
        ENTRY instead: staged admission candidates suppress the fast
        path entirely.
        """
        eng = self.eng
        # A deadlined request needs the chunked path's per-boundary
        # expiry checks — one fused run is one uninterruptible device
        # program with no boundary to check at, so a blown budget
        # would still return 200 with the full completion.
        if r.deadline is not None:
            return False
        if admit:
            with eng._alock:
                if eng._admit or eng._deferred:
                    return False
        bucket = len(r.row)
        if bucket > eng.prompt_buckets[-1]:
            return False  # chunked-prefill territory
        n_new = r.n_new
        if n_new > eng.fused_max_new:
            return False
        tier = next(t for t in self.tiers() if t >= n_new)
        greedy = (
            r.temperature <= 0.0 and r.top_k == 0 and r.top_p >= 1.0
        )
        fits, k = self._spec_headroom(bucket, tier)
        spec = fits and (
            greedy or (eng.spec_sample and r.temperature > 0.0)
        )
        if not spec and bucket + tier > eng.model.max_positions:
            return False
        # Greedy and sampled speculation are DIFFERENT compiled
        # programs (``sampled`` is static in ``fused_spec_fn``) —
        # strict warm-gating must distinguish them.
        kind = (
            "plain" if not spec
            else ("spec_sampled" if r.temperature > 0.0 else "spec")
        )
        if (
            eng._strict_admit
            and (bucket, tier, kind) not in self.warmed
        ):
            return False

        from mlapi_tpu.models.gpt import generate_tier_fn

        row = jnp.asarray(np.asarray(r.row)[None])
        kd = jnp.asarray(eng._key_data(r.seed)[None])
        temps = jnp.asarray(np.asarray([r.temperature], np.float32))
        topk = jnp.asarray(np.asarray([r.top_k], np.int32))
        topp = jnp.asarray(np.asarray([r.top_p], np.float32))
        n_pad = jnp.asarray(np.asarray([bucket - r.used], np.int32))
        if spec:
            from mlapi_tpu.ops.speculative import fused_spec_fn

            packed = np.asarray(
                fused_spec_fn(
                    eng.model, eng.draft_model, bucket, tier, k,
                    r.temperature > 0.0,
                )(
                    eng.params, eng.draft_params, row, kd, temps,
                    topk, topp, n_pad, jnp.int32(n_new),
                )
            )
            ids = packed[:n_new]
            eng.spec_rounds += int(packed[tier])
            eng.spec_accepted += int(packed[tier + 1])
            eng.spec_drafted += int(packed[tier + 2])
            eng.fused_spec_calls += 1
        else:
            ids = np.asarray(
                generate_tier_fn(eng.model, tier)(
                    eng.params, row, kd, temps, n_pad, topk, topp,
                    jnp.int32(n_new),
                )
            )[0, :n_new]
            eng.fused_calls += 1
        self.warmed.add((bucket, tier, kind))
        if not r.cancelled:
            r.push({"token_ids": ids.tolist()})
            r.push(None)
        return True

    def try_run_batch(self, reqs, admit: bool) -> bool:
        """A whole FORMED batch as one XLA program: ``generate_tier_fn``
        is batch-polymorphic (per-row traced budgets, per-row PRNG
        streams), so a collector batch of plain non-streaming requests
        costs ONE dispatch + ONE readback — through a high-RTT attach
        that replaces (max_budget / chunk) chunk dispatches with one
        round trip for all rows. With a draft attached, an all-greedy
        (or, under ``--spec-sample``, all-sampled) batch runs the
        whole BATCHED SPECULATION as one program instead
        (``fused_spec_batched_fn`` — vs the host batched phase's two
        dispatches per round). Returns ``False`` to fall through to
        continuous batching: streams, prefix rows, deadlined rows,
        mixed greedy/sampled draft batches, long prompts, over-cap
        budgets, staged joiners, and unwarmed shapes in strict mode. Each
        row's stream stays byte-identical to its solo run (per-row
        fold_in streams), so which path served a batch is invisible.
        """
        eng = self.eng
        # Attach-dependent policy, measured both ways: on a HIGH-RTT
        # attach one dispatch per batch beats per-chunk round trips
        # (the tunnel economics); on a LOW-RTT attach the atomic fused
        # batch blocks continuous admission and LOSES to chunked
        # continuous batching (CPU: 4,347 tok/s fused-batched vs
        # ~5,8-7,2k chunked at c8, and HOLB short-latency 27 ms vs 7).
        # ``fused_batch="auto"`` therefore engages only when the
        # dispatch RTT is tunnel-like; True/False force it for tests
        # and deployments that know better.
        batched_on = eng.fused_batch is True or (
            eng.fused_batch == "auto" and not eng._admit_eager
        )
        if not batched_on:
            return False
        if admit:
            with eng._alock:
                if eng._admit or eng._deferred:
                    return False
        if any(
            r.stream or r.cancelled or r.prefix_len
            or r.deadline is not None
            for r in reqs
        ):
            return False
        bucket = max(len(r.row) for r in reqs)
        if bucket > eng.prompt_buckets[-1]:
            return False
        n_max = max(r.n_new for r in reqs)
        if n_max > eng.fused_max_new:
            return False
        tier = next(t for t in self.tiers() if t >= n_max)
        # With a draft attached, the batch speculates as a whole —
        # fused_spec_batched_fn, the last cell of the fused matrix —
        # when every row is greedy (or, under --spec-sample, every
        # row sampled; ``sampled`` is static in the program). Mixed
        # batches and no-headroom windows fall through to the host
        # phases.
        spec = False
        sampled = False
        fits, k = self._spec_headroom(bucket, tier)
        if eng.draft_model is not None:
            all_greedy = all(
                r.temperature <= 0.0 and r.top_k == 0 and r.top_p >= 1.0
                for r in reqs
            )
            uniform_sampled = all(r.temperature > 0.0 for r in reqs)
            all_sampled = eng.spec_sample and uniform_sampled
            if fits and (all_greedy or all_sampled):
                spec = True
                sampled = all_sampled and not all_greedy
            elif not (all_greedy or uniform_sampled):
                # Genuinely MIXED greedy/sampled: ``sampled`` is
                # static per program — the host batched-spec /
                # chunked paths serve it.
                return False
            # No spec headroom — or a homogeneous sampled batch with
            # spec_sample off (speculation can't serve it, but the
            # plain program can, exactly like the solo path): degrade
            # to the plain fused-batched program — one dispatch still
            # beats the host loop through a tunnel.
        if not spec and bucket + tier > eng.model.max_positions:
            return False
        b = len(reqs)
        b_pad = 1
        while b_pad < b:
            b_pad *= 2
        kind = (
            f"spec_batched{'_s' if sampled else ''}{b_pad}"
            if spec else f"batched{b_pad}"
        )
        if (
            eng._strict_admit
            and (bucket, tier, kind) not in self.warmed
        ):
            return False

        prompt, n_pad, temps, topk, topp, keys = eng._pack_rows(
            reqs, bucket, b_pad
        )
        n_vec = np.ones((b_pad,), np.int32)  # dummy rows: 1 token
        for i, r in enumerate(reqs):
            n_vec[i] = r.n_new
        if spec:
            from mlapi_tpu.ops.speculative import fused_spec_batched_fn

            packed = np.asarray(
                fused_spec_batched_fn(
                    eng.model, eng.draft_model, bucket, tier, k, sampled
                )(
                    eng.params, eng.draft_params, jnp.asarray(prompt),
                    jnp.asarray(keys), jnp.asarray(temps),
                    jnp.asarray(topk), jnp.asarray(topp),
                    jnp.asarray(n_pad), jnp.asarray(n_vec),
                )
            )
            out = packed[:, :tier]
            eng.spec_rounds += int(packed[0, tier])
            eng.spec_accepted += int(packed[:b, tier + 1].sum())
            eng.spec_drafted += int(packed[:b, tier + 2].sum())
        else:
            from mlapi_tpu.models.gpt import generate_tier_fn

            out = np.asarray(
                generate_tier_fn(eng.model, tier)(
                    eng.params, jnp.asarray(prompt), jnp.asarray(keys),
                    jnp.asarray(temps), jnp.asarray(n_pad),
                    jnp.asarray(topk), jnp.asarray(topp),
                    jnp.asarray(n_vec),
                )
            )
        self.warmed.add((bucket, tier, kind))
        eng.fused_batch_calls += 1
        for i, r in enumerate(reqs):
            if not r.cancelled:
                r.push({"token_ids": out[i, : r.n_new].tolist()})
                r.push(None)
        return True

    def warm(self, full: bool) -> int:
        """Compile the batch-1 fused-generation grid off the request
        path: per prompt bucket, the whole-generation program at the
        default-``max_new_tokens`` tier and at the ``fused_max_new``
        tier (one program serves every budget in a tier — ``n_actual``
        is traced), plus the fused speculation program when a draft is
        attached. Executed with ``n_actual=1`` so the warm run costs
        one prefill + one loop iteration, not a full generation.
        Populates ``self.warmed``, which strict mode requires."""
        eng = self.eng
        from mlapi_tpu.models.gpt import generate_tier_fn

        tiers = self.tiers()
        buckets = eng.prompt_buckets if full else eng.prompt_buckets[:1]
        kd = jnp.asarray(eng._key_data(0)[None])
        z1f = jnp.zeros((1,), jnp.float32)
        z1i = jnp.zeros((1,), jnp.int32)
        o1f = jnp.ones((1,), jnp.float32)
        # Batched-fused grid: power-of-two batch sizes at the DEFAULT
        # tier only (whole-generation compiles are the most expensive
        # programs in the warmup; larger tiers stay chunked in strict
        # mode rather than doubling the grid). Only warmed where the
        # batched path can actually engage — ``try_run_batch``'s
        # attach policy — so a local attach doesn't pay the compiles.
        batch_sizes = []
        batched_on = eng.fused_batch is True or (
            eng.fused_batch == "auto" and not eng._admit_eager
        )
        if full and batched_on and eng.max_batch > 1:
            bsz = 2
            while bsz <= 1 << (eng.max_batch - 1).bit_length():
                batch_sizes.append(bsz)
                bsz *= 2
        shapes = 0
        for bucket in buckets:
            row = jnp.asarray(
                np.full((1, bucket), eng.tokenizer.pad_id, np.int32)
            )
            n_pad = jnp.asarray(np.asarray([bucket - 1], np.int32))
            for tier in sorted(tiers):
                if bucket + tier <= eng.model.max_positions:
                    generate_tier_fn(eng.model, tier)(
                        eng.params, row, kd, z1f, n_pad, z1i, o1f,
                        jnp.int32(1),
                    )
                    self.warmed.add((bucket, tier, "plain"))
                    shapes += 1
                    if tier == tiers[0]:
                        for bsz in batch_sizes:
                            rows_b = jnp.asarray(np.broadcast_to(
                                np.asarray(row), (bsz, bucket)
                            ).copy())
                            keys_b = jnp.asarray(np.stack(
                                [eng._key_data(0)] * bsz
                            ))
                            zb_f = jnp.zeros((bsz,), jnp.float32)
                            zb_i = jnp.zeros((bsz,), jnp.int32)
                            ob_f = jnp.ones((bsz,), jnp.float32)
                            npad_b = jnp.asarray(np.full(
                                (bsz,), bucket - 1, np.int32
                            ))
                            ones_b = jnp.asarray(
                                np.ones((bsz,), np.int32)
                            )
                            generate_tier_fn(eng.model, tier)(
                                eng.params, rows_b, keys_b, zb_f,
                                npad_b, zb_i, ob_f, ones_b,
                            )
                            self.warmed.add(
                                (bucket, tier, f"batched{bsz}")
                            )
                            shapes += 1
                            fits_b, k = self._spec_headroom(
                                bucket, tier
                            )
                            if fits_b:
                                from mlapi_tpu.ops.speculative import (
                                    fused_spec_batched_fn,
                                )

                                variants = [(False, "")]
                                if eng.spec_sample:
                                    variants.append((True, "_s"))
                                for smp, tag in variants:
                                    fused_spec_batched_fn(
                                        eng.model, eng.draft_model,
                                        bucket, tier, k, smp,
                                    )(
                                        eng.params, eng.draft_params,
                                        rows_b, keys_b,
                                        ob_f if smp else zb_f,
                                        zb_i, ob_f, npad_b, ones_b,
                                    )
                                    self.warmed.add((
                                        bucket, tier,
                                        f"spec_batched{tag}{bsz}",
                                    ))
                                    shapes += 1
                if eng.draft_model is None:
                    continue
                fits, k = self._spec_headroom(bucket, tier)
                if fits:
                    from mlapi_tpu.ops.speculative import fused_spec_fn

                    # Greedy speculation serves every engine; the
                    # sampled variant is a SECOND program, warmed
                    # only when --spec-sample can route to it.
                    variants = [(False, "spec")]
                    if eng.spec_sample:
                        variants.append((True, "spec_sampled"))
                    for sampled, kind in variants:
                        fused_spec_fn(
                            eng.model, eng.draft_model, bucket,
                            tier, k, sampled,
                        )(
                            eng.params, eng.draft_params, row, kd,
                            z1f, z1i, o1f, n_pad, jnp.int32(1),
                        )
                        self.warmed.add((bucket, tier, kind))
                        shapes += 1
        return shapes

