"""Fused-chunk width policy for generative serving (r20).

One :class:`FusedSinglePath` per :class:`TextGenerationEngine`. Up to
r15 this module dispatched a solo (or whole-batch) generation as ONE
uninterruptible XLA program — the r03 RTT-floor lever — which meant
declining deadlines (r12) and disaggregation (r18) at per-path gates
and blocking every concurrent scheduler lane for a whole generation.
r20 folds that dispatch saving into the typed-unit execution model
instead: a fused-eligible batch decodes through the SAME
``decode_chunk_fn`` the chunked path uses, just at TIER-WIDE chunk
sizes, so each fused chunk is one ``"decode"`` unit yielded at
``BatchRun.units()`` boundaries. Deadlines, speculation, brownout,
faults, roles, and drain all apply to fused traffic through that one
seam, and a concurrent lane's head-of-line stall drops from a whole
generation to one fused-chunk dispatch
(``engine.sched_lane_stall_max`` pins it from counters).

The retired whole-generation serving paths (``try_run`` /
``try_run_batch`` and their warm grids) are measured against this
fold in ``bench.py::_sched_report`` (BENCH_r16.json):
``generate_tier_fn`` / ``fused_spec_fn`` remain available as LIBRARY
entry points (``ops/speculative.py``, ``models/gpt.py``) but the
serving engine no longer routes requests to them.

What remains here is the WIDTH POLICY:

- :meth:`tiers` — the fused width ladder (unchanged from r03/r04).
- :meth:`chunk_width` — formation-time decision: the batch's top
  fused width, 0 to pin the plain ``eng.chunk``.
- :meth:`width_at` — per-boundary width: shrinks to the smallest
  power-of-two-of-chunk covering the live rows' remaining budgets
  (bounded program count), drops to the plain chunk while a
  streaming row is live (incremental delivery) and, in strict mode,
  for any (batch, cache, width) shape the warm grid did not compile.
- :meth:`warm` — drives real solo runs at ladder budgets so the
  fused-width decode-chunk programs compile off the request path;
  the warmed set itself is populated at the dispatch site
  (``BatchRun._decode_chunk``), so it can never disagree with what
  actually compiled.
"""

from __future__ import annotations


class FusedSinglePath:
    def __init__(self, engine):
        self.eng = engine
        # (b_cur, total, width) fused-width decode-chunk programs
        # proven compiled (recorded at the dispatch site) — strict
        # mode takes a fused width only for these; an unwarmed shape
        # falls back to the plain chunk rather than stalling a
        # concurrent lane on a remote compile.
        self.warmed: set = set()

    def tiers(self) -> list:
        """The fused width ladder, ascending: powers of two (of
        ``chunk``) from the DEFAULT budget's tier up to the
        ``fused_max_new`` cap's. The floor is the default tier
        because smaller budgets shrink per boundary via
        :meth:`width_at` — extra rungs below the default would only
        multiply compiles. ONE definition shared by the request path
        (:meth:`chunk_width`) and the warm grid (:meth:`warm`)."""
        eng = self.eng
        t = eng.default_tier
        tiers = [t]
        while t < eng.fused_max_new:
            t *= 2
            tiers.append(t)
        return tiers

    def chunk_width(self, run) -> int:
        """Formation-time fused width for ``run``: the smallest
        ladder tier covering the batch's token budget (the largest
        rung when the budget exceeds ``fused_max_new`` — the cap now
        bounds the DISPATCH width, not eligibility, so oversized
        budgets ride fused chunks instead of declining). 0 pins the
        plain ``eng.chunk``: the path is off, the batch hosts a
        streaming consumer at formation (incremental delivery — a
        joiner arriving later drops the width per boundary instead),
        or the ladder would not beat the plain chunk anyway."""
        eng = self.eng
        if not eng.fused_single:
            return 0
        if any(r.stream for r in run.reqs):
            return 0
        w = eng.chunk
        for t in self.tiers():
            w = t
            if t >= run.n_new_max:
                break
        return w if w > eng.chunk else 0

    def width_at(self, run, live: list) -> int:
        """Per-boundary dispatch width for a fused batch: the
        smallest power of two of ``chunk`` covering the live rows'
        remaining budgets, capped at the formation width — the tail
        of a generation never dispatches (and never page-allocates)
        wider than it can use, and the program count stays
        logarithmic. Falls back to the plain chunk (returns 0) while
        a streaming row is live, and in strict (tunnel) mode for any
        (batch width, cache length, width) shape not proven compiled
        — those widths compile on demand only where a compile is
        cheap."""
        eng = self.eng
        reqs = run.reqs
        if any(reqs[i].stream for i in live):
            return 0
        need = max(reqs[i].n_new - run.sched[i] for i in live)
        w = eng.chunk
        while w < need:
            w *= 2
        w = min(w, run.fused_w)
        if w <= eng.chunk:
            return 0
        if (
            eng._strict_admit
            and (run.b_cur, run.total, w) not in self.warmed
        ):
            return 0
        return w

    def warm(self, full: bool) -> int:
        """Compile the fused-width decode-chunk ladder off the
        request path by running REAL solo batches (``_run_batch``
        with ``fused_ok=True``) at each ladder budget — the exact
        programs fused traffic dispatches, recorded into ``warmed``
        at the dispatch site. Minimal warmup covers the first bucket;
        full covers every bucket at the default tier's ladder plus
        the larger tiers on the first bucket (wider multi-row shapes
        fall back to the plain chunk in strict mode — already warm).
        Returns the shape count for the warmup log."""
        import numpy as np

        from mlapi_tpu.serving.requests import GenRequest, _SyncSink

        eng = self.eng
        buckets = eng.prompt_buckets if full else eng.prompt_buckets[:1]
        # Ladder budgets: every power-of-two width width_at can pick
        # below the default tier, plus each full tier rung.
        widths = []
        w = 2 * eng.chunk
        while w <= eng.default_tier:
            widths.append(w)
            w *= 2
        shapes = 0
        for bi, bucket in enumerate(buckets):
            grid = list(widths)
            if full and bi == 0:
                grid += [t for t in self.tiers() if t > eng.default_tier]
            for n_new in grid:
                if bucket + n_new > eng.model.max_positions:
                    continue
                row = np.full((bucket,), eng.tokenizer.pad_id, np.int32)
                req = GenRequest(row, 1, n_new, 0.0, 0, None)
                sink = _SyncSink(req, [])
                eng._run_batch([sink])
                if sink.error is not None:
                    raise sink.error
                shapes += 1
        return shapes
