"""Block-granular KV page pool for generative serving.

The contiguous engine allocates one ``[B, total]`` cache per batch,
sized to the batch's whole TIER: every sequence pays for its padded
tier length, batch growth/compaction GATHER the full cache bytes, and
a shared prefix is broadcast-copied into every row. This module is the
host half of the paged replacement (the vLLM/PagedAttention move,
landed on this repo's flash-decode layout): device HBM holds one
fixed-size POOL of KV pages per layer plus per-row page TABLES, and
everything that used to move cache payloads — admission rows, batch
growth, compaction, prefix reuse — becomes page-table bookkeeping
here, in plain numpy, under one lock.

Division of labor:

- **Device** (``ops/quant`` seams + ``models/gpt`` paged factories +
  ``ops/pallas`` kernels): pool arrays, scatter/gather/COW-copy
  programs, the page-table flash-decode kernel. The pool's device
  arrays live on this object (``layers``) between batches and are
  DONATED through each batch's programs; only the decode thread may
  touch them.
- **Host** (this class): the free list, per-page reference counts,
  prefix-entry page sets with LRU eviction under pressure, and the
  observability counters ``/metrics`` exports. All guarded by
  ``self.lock`` — prefix registration threads mutate metadata
  concurrently with the decode thread.

Invariants:

- Page id 0 is the NULL page: never allocated, permanently ref-pinned.
  Unallocated table entries point at it; dummy and finished rows write
  their dead tokens into it; it is never read unmasked (a row only
  reads slots it wrote — see DESIGN §15).
- A page with ``ref == 1`` is privately owned and writable. ``ref >
  1`` means shared (prefix pages): writers must COW first
  (``models/gpt.paged_cow_fn`` + a table rewrite).
- Exhaustion first evicts prefix-entry page sets nobody currently
  references (LRU), then raises :class:`PagePoolExhausted` — a LOUD
  reject. With a :class:`~mlapi_tpu.serving.kv_tier.KVTier` attached
  (``self.tier``), eviction SPILLS the victim's pages to host before
  freeing them (gather registered before release, so a fault can
  never lose both copies) and a later miss restores them by
  ``device_put`` into fresh pages — see ``serving/kv_tier.py`` and
  DESIGN §19.
"""

from __future__ import annotations

import collections
import functools
import threading

import numpy as np

from mlapi_tpu.serving import faults
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.paged_pool")

NULL_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """No free KV pages (after prefix eviction): the pool is sized too
    small for the offered concurrency — a capacity-planning signal,
    surfaced loudly to every waiter of the batch that hit it."""


class PagePoolPoisoned(RuntimeError):
    """A donated pool program failed DURING execution: the pool
    arrays were consumed and never rebound, so no fallback path may
    read them. Surfaced loudly (callers must not swallow this into a
    cold-path retry — the retry would die on deleted buffers, the
    r12 formation-poisoning bug class)."""


@functools.cache
def _tier_restore_fn():
    """Jitted tier-restore scatter: write a host blob's
    ``[n, page, ...]`` payload rows into pool pages ``pages`` across
    every layer. The pools are DONATED — the restored arrays replace
    them in place, exactly like the adopt scatter's donation — so a
    restore never doubles the pool's HBM footprint. Shape-keyed by
    jit's own cache (one compile per distinct page count), and safe
    under mesh-sharded pools: the payload uploads replicated and
    GSPMD partitions the scatter like any other pool write."""
    import jax

    def _run(pools, payload, pages):
        return {
            ln: {
                name: leaf.at[pages].set(
                    payload[ln][name].astype(leaf.dtype)
                )
                for name, leaf in layer.items()
            }
            for ln, layer in pools.items()
        }

    return jax.jit(_run, donate_argnums=(0,))


class PagePool:
    def __init__(self, model, *, page_size: int, num_pages: int):
        from mlapi_tpu.ops.quant import kv_page_bytes, make_paged_pools

        if page_size < 1:
            raise ValueError(f"kv_page_size must be >= 1, got {page_size}")
        if num_pages < 2:
            raise ValueError(
                f"kv_pages must be >= 2 (one null + one usable), got "
                f"{num_pages}"
            )
        self.page = int(page_size)
        self.num_pages = int(num_pages)
        # Device pools, one [num_pages, page, H, D(|1)] array per cache
        # leaf per layer. Rebound by the decode thread after every
        # donated program (BatchRun writes the updated arrays back).
        self.layers = make_paged_pools(model, num_pages, page_size)
        self.page_bytes = kv_page_bytes(model, page_size)
        self.lock = threading.Lock()
        # Eviction runs its spill (device gather + optional disk
        # write) OUTSIDE the lock; this condition (sharing the lock)
        # lets a concurrent alloc that finds no free pages AND no
        # victim wait for an in-flight eviction's release instead of
        # raising a spurious PagePoolExhausted for capacity that is
        # moments from free.
        self._evict_cond = threading.Condition(self.lock)
        self._evicting = 0
        self.ref = np.zeros((num_pages,), np.int64)
        self.ref[NULL_PAGE] = 1  # pinned forever
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        # Prefix-entry page sets: fingerprint -> int32[NPe] page ids,
        # LRU-ordered. Each set holds ONE ref per page for the entry
        # itself; rows sharing the prefix retain on top of that.
        self._entries: collections.OrderedDict[object, np.ndarray] = (
            collections.OrderedDict()
        )
        # Donation epoch (r15, unit scheduler): bumped by the
        # scheduler after every unit that may have donated the pool
        # arrays through a dispatch, so CONCURRENT lanes know their
        # cache pytree is stale and re-bind from ``layers`` before
        # their next unit. Only the scheduler's single dispatch
        # thread reads or writes it — no lock.
        self.epoch = 0
        # Counters (exported via the engine's /metrics block).
        self.cow_copies = 0
        self.entry_evictions = 0
        self.exhaustions = 0
        # Host-RAM spill tier (serving/kv_tier.py), attached by the
        # engine when --kv-tier-bytes > 0. None = the pre-tier
        # behavior: eviction discards, restore never happens.
        self.tier = None

    # -- accounting (read by /metrics and bench) -----------------------
    @property
    def pages_total(self) -> int:
        """Allocatable pages (the null page is bookkeeping, not
        capacity)."""
        return self.num_pages - 1

    @property
    def pages_in_use(self) -> int:
        with self.lock:
            return self.pages_total - len(self._free)

    @property
    def pages_shared(self) -> int:
        """Pages referenced more than once (shared prefix blocks).
        The null page is excluded by index — it is pinned at ref 1,
        never above."""
        with self.lock:
            return int(np.sum(self.ref[NULL_PAGE + 1:] > 1))

    @property
    def utilization(self) -> float:
        return self.pages_in_use / max(1, self.pages_total)

    # -- allocation ----------------------------------------------------
    def alloc(self, n: int) -> np.ndarray:
        """Pop ``n`` free pages (ref = 1 each). Under pressure, evict
        prefix-entry page sets with no live-row references, LRU-first;
        still short → :class:`PagePoolExhausted`."""
        if n == 0:
            return np.zeros((0,), np.int32)
        # Injection point: armed tests force exhaustion (or a slow
        # allocator) at exactly this seam, BEFORE any free-list state
        # changes — the pool stays consistent and callers exercise
        # their real PagePoolExhausted handling. The armed guard keeps
        # the exception construction off the disarmed hot path.
        if faults.armed:
            faults.fire(
                "pool_alloc",
                exc=PagePoolExhausted(
                    f"KV page pool exhausted (injected fault): "
                    f"need {n} pages"
                ),
            )
        while True:
            with self.lock:
                if len(self._free) >= n:
                    out = np.asarray(
                        [self._free.pop() for _ in range(n)], np.int32
                    )
                    self.ref[out] = 1
                    return out
                victim = self._pop_victim_locked()
                if victim is None:
                    if self._evicting:
                        # Another thread's eviction is mid-spill: its
                        # pages free the moment it finishes — wait for
                        # the release instead of shedding capacity
                        # that exists.
                        self._evict_cond.wait(timeout=5.0)
                        continue
                    self.exhaustions += 1
                    raise PagePoolExhausted(
                        f"KV page pool exhausted: need {n} pages, "
                        f"{len(self._free)} free of {self.pages_total} "
                        f"(page={self.page} tokens); raise --kv-pages "
                        f"or lower concurrency"
                    )
                self._evicting += 1
            # Outside the lock: the victim's pages still carry their
            # entry references (the set is popped, so no other thread
            # can find or free them), and the spill's device gather +
            # optional disk write must not convoy every concurrent
            # pool operation behind one eviction.
            self._spill_and_release(*victim)

    def _pop_victim_locked(self):
        """Claim (pop) the LRU prefix-entry page set whose pages
        nobody else references (ref == 1 everywhere: only the entry's
        own hold) — or ``None``. The pop IS the claim: the pages keep
        their entry refs until :meth:`_spill_and_release` frees them,
        invisible to every other thread in between."""
        victim = next(
            (
                fp for fp, pages in self._entries.items()
                if np.all(self.ref[pages] == 1)
            ),
            None,
        )
        if victim is None:
            return None
        return victim, self._entries.pop(victim)

    def _spill_and_release(self, fp, pages) -> None:
        """Spill a claimed victim to the host tier (when attached),
        then free its pages. Runs OUTSIDE the pool lock (caller
        bumped ``_evicting`` under it); the spill happens BEFORE the
        release so the bytes exist somewhere at every instant. A
        spill failure at any point (including an injected
        ``tier_spill`` raise, or a gather racing a donated program
        when brownout's ``evict_idle`` fires from the event loop)
        leaves the tier untouched and falls back to the pre-tier
        discard, counted — it can never strand pages or lose the
        only copy. The PrefixCache entry itself survives either way
        — its contiguous KV re-adopts into fresh pages on next use.
        Logged at debug: with the tier this is a routine,
        recoverable path (the ``entry_evictions`` counter is the
        observable, exported as ``generate.kv_entry_evictions``)."""
        try:
            if self.tier is not None:
                try:
                    idx = np.asarray(pages)
                    payload = {
                        ln: {
                            name: np.asarray(leaf[idx])
                            for name, leaf in layer.items()
                        }
                        for ln, layer in self.layers.items()
                    }
                    self.tier.spill(fp, payload, self.page)
                except Exception as e:
                    self.tier.count_spill_failure()
                    _log.debug(
                        "tier spill failed (%s); evicting cold", e
                    )
        finally:
            with self.lock:
                # Decrement BEFORE the release: if the release ever
                # raised (a double-release lifecycle bug), waiters
                # must not spin forever on a phantom in-flight
                # eviction.
                self._evicting -= 1
                self._release_locked(np.asarray(pages))
                # Counted under the lock: evictions run concurrently
                # from the decode thread (alloc pressure) and the
                # event loop (brownout evict_idle) — a bare += here
                # lost updates under exactly the load /metrics is
                # read to diagnose (mlapi-lint MLA002, fixed r16).
                self.entry_evictions += 1
        _log.debug(
            "evicted prefix page set (%d pages) under pool pressure%s",
            len(pages),
            " (spilled to host tier)" if self.tier is not None else "",
        )

    def _blob_geometry_ok(self, blob) -> bool:
        """Does a host blob match this pool's page size and every
        layer's leaf shapes/dtypes? ONE definition shared by the tier
        restore and the r18 push install — the two blob-install paths
        must never diverge on what 'applies here' means."""
        if blob.page != self.page:
            return False
        for ln, layer in self.layers.items():
            pl = blob.payload.get(ln)
            if pl is None:
                return False
            for name, leaf in layer.items():
                a = pl.get(name)
                if (
                    a is None
                    or a.shape[1:] != leaf.shape[1:]
                    or a.dtype != leaf.dtype
                ):
                    return False
        return True

    def _scatter_blob(self, pages, blob, *, fire: str | None,
                      what: str) -> None:
        """The shared alloc-first install core: one donated scatter
        rebinds ``self.layers`` atomically. On ANY failure the pages
        go back (``kv_pages_in_use`` conserved exactly) — UNLESS the
        donated scatter failed DURING execution: then the pool
        buffers are consumed with no result to rebind, and any
        fallback that reads them dies on deleted buffers (the r12
        formation-poisoning bug class) — surfaced loudly as
        :class:`PagePoolPoisoned` instead. The optional fault point
        fires BEFORE the call on purpose, so injected raises always
        take the safe branch. Shared by :meth:`restore_entry` and
        :meth:`install_blob` so a fix to the poisoning detection can
        never reach one install path and not the other."""
        import jax.numpy as jnp

        try:
            if fire is not None:
                faults.fire(fire)
            self.layers = _tier_restore_fn()(
                self.layers, blob.payload, jnp.asarray(pages)
            )
        except BaseException as e:
            self.release(pages)
            leaf = next(
                iter(next(iter(self.layers.values())).values())
            )
            if getattr(leaf, "is_deleted", lambda: False)():
                raise PagePoolPoisoned(
                    f"KV pool consumed by a {what} that failed "
                    "mid-execution; no fallback may read the pool"
                ) from e
            raise

    def restore_entry(self, fp, blob, holds: int = 0):
        """Repopulate fresh pool pages from a spilled tier blob and
        register them as ``fp``'s entry page set (with ``holds`` row
        references, like :meth:`put_entry_pages`). Ordering is the
        whole point: pages are ALLOCATED first (a
        :class:`PagePoolExhausted` here propagates with nothing
        installed and nothing device-written — no half-restored entry
        can exist), the ``tier_restore`` fault point fires before any
        device write, the donated scatter rebinds ``self.layers``
        atomically, and registration is last. Returns the installed
        page ids, or ``None`` when the blob does not match this
        pool's geometry (dropped from the tier — it can never apply).
        Decode-thread only, like every other pool-array touch."""
        if not self._blob_geometry_ok(blob):
            self.tier.drop(blob.fp)
            return None
        pages = self.alloc(blob.num_pages)
        self._scatter_blob(
            pages, blob, fire="tier_restore", what="tier restore"
        )
        self.put_entry_pages(fp, pages, holds=holds)
        self.tier.count_restore(blob)
        return pages

    def install_blob(self, blob) -> np.ndarray | None:
        """Repopulate fresh pool pages from a host blob WITHOUT
        registering an entry set — the r18 disaggregation install: a
        pushed prompt's KV becomes a PRIVATE table row (each page at
        ref 1, writable in place), not a shared prefix entry. Same
        ordering contract as :meth:`restore_entry` (the shared
        :meth:`_scatter_blob` core): pages ALLOCATED first, one
        donated scatter, :class:`PagePoolPoisoned` on mid-execution
        failure. Returns the page ids (caller assigns them into its
        row table and owns the release), or ``None`` when the blob
        does not match this pool's geometry (caller cold-prefills,
        pages conserved). Decode-thread only, like every other
        pool-array touch."""
        if not self._blob_geometry_ok(blob):
            return None
        pages = self.alloc(blob.num_pages)
        self._scatter_blob(pages, blob, fire=None, what="push install")
        return pages

    def evict_idle(self, n: int = 1) -> int:
        """Brownout lever: proactively drop up to ``n`` idle
        (unreferenced, LRU-first) prefix-entry page sets so live
        sequences keep allocating under pressure instead of slamming
        into :class:`PagePoolExhausted`. Same eviction ``alloc`` runs
        reactively (claim under the lock, spill+free outside it);
        returns how many sets were dropped."""
        dropped = 0
        while dropped < n:
            with self.lock:
                victim = self._pop_victim_locked()
                if victim is not None:
                    self._evicting += 1
            if victim is None:
                break
            self._spill_and_release(*victim)
            dropped += 1
        return dropped

    def retain(self, pages) -> None:
        """One more holder of each page (a row sharing prefix
        pages)."""
        pages = np.asarray(pages)
        pages = pages[pages != NULL_PAGE]
        if len(pages):
            with self.lock:
                np.add.at(self.ref, pages, 1)

    def release(self, pages) -> None:
        """Drop one hold per page; pages at ref 0 return to the free
        list. Null entries are ignored, so callers can release whole
        table rows."""
        pages = np.asarray(pages).ravel()
        pages = pages[pages != NULL_PAGE]
        if len(pages):
            with self.lock:
                self._release_locked(pages)

    def _release_locked(self, pages) -> None:
        np.subtract.at(self.ref, pages, 1)
        if np.any(self.ref[pages] < 0):
            # A double release is a lifecycle bug: loud, not silent —
            # the page may already belong to someone else.
            bad = pages[self.ref[pages] < 0]
            self.ref[bad] = 0
            raise AssertionError(
                f"KV page(s) {sorted(set(int(p) for p in bad))} "
                "released below zero references"
            )
        freed = np.unique(pages[self.ref[pages] == 0])
        if len(freed):
            self._free.extend(int(p) for p in freed)
            # Wake any alloc waiting out an in-flight eviction (the
            # condition shares self.lock, already held here).
            self._evict_cond.notify_all()

    def is_shared(self, page: int) -> bool:
        with self.lock:
            return bool(self.ref[page] > 1)

    # -- prefix-entry page sets ----------------------------------------
    def entry_pages(self, fp, holds: int = 0) -> np.ndarray | None:
        """The pool-resident page set of a prefix entry, if paged in
        (marks it most-recently-used). ``holds`` extra references are
        taken ATOMICALLY with the lookup — a concurrent entry
        eviction (``drop_entry`` from a registration thread) between
        a bare lookup and a later ``retain`` could otherwise free the
        pages out from under the forming batch."""
        with self.lock:
            pages = self._entries.get(fp)
            if pages is not None:
                self._entries.move_to_end(fp)
                if holds:
                    np.add.at(self.ref, pages, holds)
            return pages

    def put_entry_pages(self, fp, pages: np.ndarray,
                        holds: int = 0) -> None:
        """Register a freshly-adopted entry page set (pages arrive
        from ``alloc`` holding the entry's own reference); ``holds``
        row references are added under the same lock so the set is
        never observable in its evictable state while a batch is
        about to use it."""
        with self.lock:
            pages = np.asarray(pages, np.int32)
            if holds:
                np.add.at(self.ref, pages, holds)
            self._entries[fp] = pages

    def drop_entry(self, fp) -> None:
        """Release an evicted PrefixCache entry's page set (no-op if
        never paged in or already evicted under pressure)."""
        with self.lock:
            pages = self._entries.pop(fp, None)
            if pages is not None:
                self._release_locked(pages)
