"""Scale-out serving: an asyncio front-end router over N engine replicas.

One engine process is now dense with capability (paged pool r09,
flash extend r11, robustness r12, host-RAM tier r13) but it is still
ONE process; the next axis is *out* (ROADMAP item 3). This module is
the front end for a fleet of full engine replicas — separate
processes, each serving the whole r13 stack on its own port — that
spreads ``/generate``, ``/predict``, and streaming NDJSON traffic
over them while keeping each replica's caches hot.

Why the replica choice is the whole game: at millions-of-users scale
prefix reuse is the dominant cache economics (ROADMAP item 2), and a
prefix's pool pages and kv_tier blobs live in ONE replica's memory.
A load balancer that sprays requests uniformly makes every replica
rebuild every prefix — N replicas, ~N× the cold prefills, and the r13
host tier goes cold. So the routing policy is **prefix-hash affinity
with a power-of-two-choices fallback**:

- The router tokenizes nothing. It takes the request's routing key —
  the ``prefix`` field when present (that is the shared-prompt cache
  unit), else the prompt text — truncated to the first K BYTES
  (``affinity_prefix_bytes``, CLI ``--affinity-prefix-bytes``), and
  ranks replicas by **rendezvous (highest-random-weight) hashing**.
  HRW's property is exactly the scale-out story: adding or removing
  one replica remaps ONLY the keys that preferred it — every other
  replica's affinity slice (and therefore its warm pages, tier blobs,
  and compiled shapes) is untouched.
- When the preferred replica is not routable — shedding (a recent
  503/retry-after), draining (its ``/healthz`` says so — poll-cached
  per replica), down (failed polls / refused connects), or over the
  queue-depth threshold scraped from its ``/metrics`` — the router
  falls back to the **less loaded of two random routable replicas**
  (power of two choices: near-optimal load spread at O(1) state,
  without the herding a deterministic second choice causes).

Failure semantics (the part a proxy one-liner gets wrong):

- **Failover-once, never mid-stream.** A submit that provably never
  reached a replica (connect refused, the ``router_forward`` fault
  seam firing before the first request byte is written) or that the
  replica REFUSED whole (a 503 — sheds happen at the replica's door,
  before any decode work) retries exactly one hop on a
  power-of-two-chosen alternate. Once request bytes are on the wire
  with no response, or once any response byte has been relayed, there
  is no retry — a duplicate generation is worse than an honest 502.
- **Streams end in terminal frames, always.** The NDJSON passthrough
  relays body bytes verbatim (the replica's ``DeadlineExceeded`` /
  ``DrainCancelled`` terminal frames reach the client byte-for-byte);
  if the upstream dies mid-stream the router appends a well-formed
  ``{"error": ..., "code": "upstream_error"}`` frame — never a
  truncated stream.

Warmth hinting (r17, ``serving/kv_peer.py``): affinity is a
PREFERENCE, not a placement constraint — any forward that misses the
key's HRW head (p2c fallback, failover, depth overflow, post-drain
remap) carries ``x-mlapi-warm-peer: host:port`` naming that head, so
a ``--kv-peer-fetch`` replica can pull the prefix KV from where it is
warm instead of re-prefilling. The head is computed once per request
over ALL replicas and threaded through the failover hop too (the
second ``choose()`` excludes the failed replica and would otherwise
forget who was preferred).

Observability: the router's ``/metrics`` sums replica counters (the
fleet-wide totals), labels per-replica gauges
(``replica.<host:port>.<gauge>``), and adds its own
``router.affinity_{hits,fallbacks}``, ``router.failovers``,
``router.replicas_{live,draining,down}`` and per-replica queue-depth
gauges; ``/healthz`` reports replica liveness for the layer above
(routers stack: a pod-level balancer health-checks this endpoint the
way this router health-checks its replicas).

The router deliberately imports no jax and touches no device: it is
pure asyncio and can front replicas on other hosts unchanged
(``--replica-urls`` / ``$MLAPI_TPU_REPLICAS`` — the env-driven
discovery mirror of ``parallel/distributed.py``'s rendezvous trio).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
import time

from mlapi_tpu.serving import faults
from mlapi_tpu.serving.asgi import (
    App,
    Request,
    Response,
    StreamingResponse,
    json_response,
)
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.router")

DEFAULT_AFFINITY_PREFIX_BYTES = 64

# Replica lifecycle states (the health/backpressure state machine).
LIVE = "live"
DRAINING = "draining"
DOWN = "down"

# Hop-by-hop / framing headers never forwarded in either direction
# (RFC 9110 §7.6.1): the router re-frames each hop itself.
_HOP_HEADERS = frozenset(
    (
        b"host",
        b"connection",
        b"keep-alive",
        b"content-length",
        b"transfer-encoding",
        b"te",
        b"upgrade",
        b"expect",
        b"proxy-authorization",
        b"proxy-authenticate",
    )
)


class NoReplicaAvailable(Exception):
    """Every replica is down, draining, shedding, or over the queue
    threshold: the router sheds at ITS door (503 + retry-after), the
    same contract a single overloaded replica gives its clients."""

    def __init__(self, retry_after_s: float = 1.0):
        super().__init__("no live replica available")
        self.retry_after_s = retry_after_s


class _SubmitError(Exception):
    """One forward attempt failed. ``retryable`` says whether the
    failover hop is safe (the request provably never started work on
    the replica); ``response`` carries a complete replica response
    (e.g. its 503) to relay if no hop remains."""

    def __init__(self, detail: str, *, retryable: bool,
                 response: Response | None = None):
        super().__init__(detail)
        self.detail = detail
        self.retryable = retryable
        self.response = response


def hrw_weight(key: bytes, name: str) -> int:
    """The rendezvous weight of ``name`` for ``key``: a stable 64-bit
    digest (blake2b — NOT Python's ``hash``, which is per-process
    salted and would scatter affinity across router restarts)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(name.encode())
    h.update(b"\x00")
    h.update(key)
    return int.from_bytes(h.digest(), "big")


def hrw_order(key: bytes, names: list[str]) -> list[str]:
    """Replica names ranked by rendezvous hash for ``key`` (highest
    weight first; name breaks the astronomically-unlikely tie so the
    order is total). The stability property routing leans on: removing
    a name never changes the relative order of the others, so only
    keys whose TOP choice vanished remap — each to its key-specific
    runner-up, spreading the lost slice over the fleet instead of
    shifting everyone (what modulo hashing would do)."""
    return sorted(names, key=lambda n: (-hrw_weight(key, n), n))


class ReplicaState:
    """One replica as the router sees it: its address plus the cached
    health/backpressure state the routing decision reads. Updated by
    the poll loop (``/healthz`` liveness, ``/metrics`` queue depth)
    and by forward outcomes (refused connects mark it down
    immediately; a 503 opens a shed window from its retry-after —
    faster feedback than the next poll tick)."""

    __slots__ = (
        "host", "port", "name", "state", "queue_depth", "inflight",
        "shed_until", "poll_failures", "last_poll", "healthz",
        "metrics", "role", "models",
    )

    def __init__(self, host: str, port: int, *, assume_live: bool = True,
                 role: str = "mixed"):
        self.host = host
        self.port = port
        self.name = f"{host}:{port}"
        # Disaggregation role (r18): "prefill" replicas take the
        # first hop of role-split generative traffic, "decode"
        # replicas own the streams; "mixed" (default) serves both —
        # an all-mixed fleet routes exactly as r17 did.
        self.role = role
        # assume_live=False (the CLI topology) gates routing on the
        # first successful health poll — a replica still booting its
        # engine never sees traffic; True is the embedded/unit default
        # where the caller controls replica lifetime itself.
        self.state = LIVE if assume_live else DOWN
        # Model ids this replica advertises on /healthz (r22 multi-
        # model fleets): None until a poll says otherwise — an
        # unpolled or single-model replica serves the default model
        # only, and the model filter treats it that way. The r18
        # role generalized: a fleet whose replicas advertise
        # different model sets IS the per-model replica-group
        # topology, discovered, not configured.
        self.models: frozenset | None = None
        self.queue_depth = 0
        self.inflight = 0        # router-side in-flight forwards
        self.shed_until = 0.0    # monotonic: shedding until then
        self.poll_failures = 0
        self.last_poll: float | None = None
        self.healthz: dict = {}
        self.metrics: dict = {}

    def routable(self, now: float, depth_limit: int | None) -> bool:
        if self.state != LIVE or now < self.shed_until:
            return False
        if depth_limit is not None and (
            self.queue_depth + self.inflight > depth_limit
        ):
            return False
        return True

    def load(self) -> int:
        """The power-of-two comparison key: the replica's own queue
        depth (from its last scrape) plus the router's in-flight
        forwards to it (fresher than any scrape)."""
        return self.queue_depth + self.inflight


async def _read_response_head(reader) -> tuple[int, dict[bytes, bytes]]:
    head = await reader.readuntil(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    try:
        status = int(lines[0].split(b" ", 2)[1])
    except (IndexError, ValueError):
        raise ConnectionError(f"malformed upstream status line {lines[0]!r}")
    headers: dict[bytes, bytes] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, sep, v = line.partition(b":")
        if sep:
            headers[k.strip().lower()] = v.strip()
    return status, headers


async def _iter_chunked(reader):
    """Decode an upstream chunked body incrementally — one yielded
    bytes object per upstream chunk, so relayed tokens reach the
    client with the same cadence the replica produced them."""
    while True:
        size_line = (await reader.readuntil(b"\r\n")).strip()
        size = int(size_line.split(b";")[0], 16)
        if size == 0:
            while (await reader.readuntil(b"\r\n")) != b"\r\n":
                pass
            return
        data = await reader.readexactly(size)
        if await reader.readexactly(2) != b"\r\n":
            raise ConnectionError("upstream chunk not CRLF-terminated")
        yield data


async def _fire_async(point: str) -> None:
    """The fault seam, async-safe: the engine's seams fire from the
    decode thread where ``time.sleep`` (the delay action) is the
    point, but the router runs ON the event loop — a delay fired
    inline would freeze every concurrent relay and the health poll,
    modeling a frozen router instead of one slowed hop. Disarmed cost
    stays one module-global bool check; armed, the call (sleep or
    raise) runs in a worker thread and propagates."""
    if faults.armed:
        await asyncio.get_running_loop().run_in_executor(
            None, faults.fire, point
        )


async def _close_writer(writer) -> None:
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionResetError, BrokenPipeError, OSError):
        pass


async def _get_json(
    host: str, port: int, path: str, timeout_s: float
) -> dict:
    """One GET against a replica control endpoint (healthz/metrics):
    fresh connection, bounded by ``timeout_s`` end to end."""

    async def _go():
        reader, writer = await asyncio.open_connection(host, port)
        try:
            writer.write(
                (
                    f"GET {path} HTTP/1.1\r\nhost: {host}\r\n"
                    "connection: close\r\n\r\n"
                ).encode()
            )
            await writer.drain()
            status, headers = await _read_response_head(reader)
            clen = headers.get(b"content-length")
            if clen is not None:
                body = await reader.readexactly(int(clen))
            elif headers.get(b"transfer-encoding", b"").lower() == b"chunked":
                body = b"".join([c async for c in _iter_chunked(reader)])
            else:
                body = await reader.read()
            if status != 200:
                raise ConnectionError(f"{path} -> {status}")
            return json.loads(body)
        finally:
            await _close_writer(writer)

    return await asyncio.wait_for(_go(), timeout_s)


class Router:
    """The routing core + forwarding engine. Pure asyncio, no jax, no
    device: every decision reads the cached :class:`ReplicaState`
    table and two integers of per-request hashing."""

    def __init__(
        self,
        endpoints: list[tuple[str, int]],
        *,
        policy: str = "affinity",
        affinity_prefix_bytes: int = DEFAULT_AFFINITY_PREFIX_BYTES,
        health_poll_s: float = 0.5,
        poll_timeout_s: float = 2.0,
        queue_depth_limit: int | None = None,
        assume_live: bool = True,
        rng: random.Random | None = None,
        roles: list | None = None,
    ):
        if not endpoints:
            raise ValueError("router needs at least one replica endpoint")
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        if roles is None:
            roles = ["mixed"] * len(endpoints)
        if len(roles) != len(endpoints):
            raise ValueError("one role per replica endpoint")
        bad = [r for r in roles if r not in ("prefill", "decode", "mixed")]
        if bad:
            raise ValueError(f"unknown replica roles {bad!r}")
        self.replicas = [
            ReplicaState(h, p, assume_live=assume_live, role=role)
            for (h, p), role in zip(endpoints, roles)
        ]
        # Role-split topology (r18): disaggregate generative traffic
        # whenever BOTH pools exist. An all-mixed fleet (default) has
        # neither — routing is bit-identical to r17.
        self.role_split = any(r == "prefill" for r in roles) and any(
            r == "decode" for r in roles
        )
        self._xfer_seq = 0
        if len({r.name for r in self.replicas}) != len(self.replicas):
            raise ValueError("duplicate replica endpoints")
        self.policy = policy
        self.affinity_prefix_bytes = int(affinity_prefix_bytes)
        self.health_poll_s = float(health_poll_s)
        self.poll_timeout_s = float(poll_timeout_s)
        self.queue_depth_limit = queue_depth_limit
        # Seeded: the p2c sample must not make routing tests flaky;
        # which of two equal-load replicas wins is not a contract.
        self._rng = rng or random.Random(0x5EED)
        self._rr = 0             # round_robin cursor (A/B baseline)
        self._poll_task: asyncio.Task | None = None
        # Counters (exported under router.* on /metrics).
        self.forwarded = 0
        self.affinity_hits = 0
        self.affinity_fallbacks = 0
        self.failovers = 0
        self.shed_no_replica = 0
        self.stream_upstream_errors = 0
        self.warm_peer_hints = 0
        # Disaggregation counters (r18, exported under router.role_*):
        # disagg_forwards counts two-hop role-split forwards;
        # fallback_mixed counts role-starved degradations (a pool
        # down/unroutable ⇒ the request served mixed-style by
        # whatever is routable); push_incomplete counts handoffs
        # whose transfer failed mid-push (the decode replica then
        # cold-prefills — pages conserved on both ends).
        self.role_disagg_forwards = 0
        self.role_fallback_mixed = 0
        self.role_push_incomplete = 0
        # Multi-model fleets (r22): forwards that found NO replica
        # advertising the requested model and degraded to the whole
        # routable set (the replica then 404s an id it truly lacks —
        # an honest error beats a router-synthesized one during a
        # rolling deploy where the next poll may find the model).
        self.model_fallbacks = 0

    # -- discovery/keys ---------------------------------------------------
    @staticmethod
    def parse_body(body: bytes) -> dict | None:
        """ONE parse of a ``/generate`` body, shared by the routing
        key and the disagg gate (the role-split hot path must not pay
        two full ``json.loads`` of a multi-KB prompt on the event
        loop). ``None`` for unparseable/non-object bodies — the
        replica owns rejecting those."""
        try:
            obj = json.loads(body)
        except Exception:
            return None
        return obj if isinstance(obj, dict) else None

    def routing_key(self, body: bytes) -> bytes | None:
        """The affinity key of a ``/generate`` body (convenience
        wrapper over :meth:`routing_key_of` for callers holding raw
        bytes)."""
        return self.routing_key_of(self.parse_body(body))

    def routing_key_of(self, obj: dict | None) -> bytes | None:
        """The affinity key of a parsed body: the ``prefix`` field
        when present (the shared-prompt cache unit — every request
        naming it must land where its KV lives), else the ``adapter``
        id (a tenant's requests land where its LoRA slot — and, when
        it also uses prefixes, its prefix KV — is already warm), else
        the prompt ``text``; truncated to the first K bytes. The
        router tokenizes nothing — raw UTF-8 bytes hash the same on
        every router process. ``None`` (unparseable body, no text)
        routes by load only; the replica still owns rejecting the bad
        body."""
        if obj is None:
            return None
        src = obj.get("prefix") or obj.get("adapter") or obj.get("text")
        if not isinstance(src, str) or not src:
            return None
        return src.encode("utf-8", "surrogatepass")[
            : self.affinity_prefix_bytes
        ]

    def wants_disagg(self, body: bytes) -> bool:
        """Raw-bytes wrapper over :meth:`wants_disagg_of`."""
        return self.wants_disagg_of(self.parse_body(body))

    def wants_disagg_of(self, obj: dict | None) -> bool:
        """Should this parsed ``/generate`` body take the role-split
        two-hop path? Only in a role-split fleet, and only for plain
        prompt requests: a ``prefix``-carrying request is the
        shared-prefix warmth workload the affinity + peer-fetch path
        (r14/r17) already serves — its suffix prefill is small by
        construction, so disaggregating it buys nothing and would
        complicate the prefix-region transfer. Unparseable bodies
        route normally (the replica owns rejecting them). Adapter
        requests stay single-hop too: the prefill replica would need
        the tenant's slot resident just to run the prompt, doubling
        every adapter's working-set across both role pools for no
        prefill win."""
        if not self.role_split or obj is None:
            return False
        return (
            isinstance(obj.get("text"), str)
            and bool(obj.get("text"))
            and not obj.get("prefix")
            and not obj.get("adapter")
        )

    def _pick_role(
        self, key: bytes | None, role: str,
        exclude: ReplicaState | None = None,
    ) -> ReplicaState | None:
        """The routable pick inside ONE role pool: HRW by key first
        (decode replicas keep per-key placement stable across
        requests — the warmth argument, applied to the role pool),
        power-of-two-choices otherwise; ``None`` when the pool has no
        routable member (the caller degrades to mixed routing,
        counted). Never touches the affinity hit/fallback counters —
        those describe the r14 single-hop policy."""
        now = time.monotonic()
        pool = [
            r for r in self.replicas
            if r.role == role and r is not exclude
            and r.routable(now, self.queue_depth_limit)
        ]
        if not pool:
            return None
        if key is not None:
            order = hrw_order(key, [r.name for r in pool])
            return next(r for r in pool if r.name == order[0])
        if len(pool) == 1:
            return pool[0]
        a, b = self._rng.sample(pool, 2)
        return a if a.load() <= b.load() else b

    # -- the routing decision ---------------------------------------------
    def preferred_for(self, key: bytes | None) -> ReplicaState | None:
        """The HRW head for ``key`` over ALL configured replicas —
        state-independent on purpose: it answers "who is most likely
        WARM for this prefix", which survives the preferred replica
        being down, draining, shedding, or over its depth limit (a
        draining replica still serves ``GET /kv/prefix``; a down one
        just costs the fetcher a fast refused connect). ``None``
        under round-robin or without a key — there is no warmth map
        to consult."""
        if key is None or self.policy != "affinity":
            return None
        order = hrw_order(key, [r.name for r in self.replicas])
        return next(r for r in self.replicas if r.name == order[0])

    def _serves(self, r: ReplicaState, model: str | None) -> bool:
        """Does this replica serve ``model``? The default model is
        everywhere (every process has one); a named model needs the
        replica's advertised set — a replica that never advertised
        one (single-model build, unpolled) serves the default only."""
        if model is None or model == "default":
            return True
        return r.models is not None and model in r.models

    def choose(
        self,
        key: bytes | None,
        exclude: ReplicaState | None = None,
        count: bool = True,
        model: str | None = None,
    ) -> ReplicaState:
        """Pick the replica for one request. Affinity first: the HRW
        top choice over ALL configured replicas (states excluded — the
        preference map must stay stable while a replica drains and
        comes back, or its cache investment is lost on every blip);
        the fallback ladder below it is power-of-two-choices over the
        routable set. ``model`` narrows every rung to the replica
        group advertising that id — an empty group degrades to the
        whole fleet, counted (``router.model_fallbacks``). Raises
        :class:`NoReplicaAvailable` when the routable set is empty."""
        now = time.monotonic()
        cands = [r for r in self.replicas if r is not exclude]
        if model is not None:
            group = [r for r in cands if self._serves(r, model)]
            if group:
                cands = group
            elif count:
                self.model_fallbacks += 1
        routable = [
            r for r in cands if r.routable(now, self.queue_depth_limit)
        ]
        if not routable:
            # Shed with the earliest time a shed window reopens (min 1s
            # so clients don't hammer a draining fleet).
            wait = [r.shed_until - now for r in cands if r.shed_until > now]
            raise NoReplicaAvailable(max(1.0, min(wait)) if wait else 1.0)
        if self.policy == "round_robin":
            r = routable[self._rr % len(routable)]
            self._rr += 1
            return r
        if key is not None:
            order = hrw_order(key, [r.name for r in cands])
            preferred = next(r for r in cands if r.name == order[0])
            if preferred.routable(now, self.queue_depth_limit):
                if count:
                    self.affinity_hits += 1
                return preferred
            if count:
                self.affinity_fallbacks += 1
        if len(routable) == 1:
            return routable[0]
        a, b = self._rng.sample(routable, 2)
        return a if a.load() <= b.load() else b

    # -- health / backpressure polling ------------------------------------
    async def start(self) -> None:
        """One immediate poll round (so a CLI router starts with real
        state, not assumptions), then the background cadence."""
        await self._poll_round()
        self._poll_task = asyncio.create_task(self._poll_loop())

    async def stop(self) -> None:
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass
            self._poll_task = None

    async def _poll_loop(self) -> None:
        while True:
            await asyncio.sleep(self.health_poll_s)
            await self._poll_round()

    async def _poll_round(self) -> None:
        await asyncio.gather(
            *(self._poll_one(r) for r in self.replicas),
            return_exceptions=True,
        )

    async def _poll_one(self, r: ReplicaState) -> None:
        try:
            health = await _get_json(
                r.host, r.port, "/healthz", self.poll_timeout_s
            )
        except Exception:
            r.poll_failures += 1
            # Two consecutive failures = down, not one: a single slow
            # scrape under load must not dump the replica's whole
            # affinity slice onto its peers.
            if r.poll_failures >= 2 and r.state != DOWN:
                _log.warning("replica %s marked down (poll failures)", r.name)
                r.state = DOWN
            return
        # Queue depth (the p2c load signal and the threshold check, at
        # most one tick stale): this repo's replicas surface the
        # /metrics queue-depth gauge on /healthz too, so liveness +
        # backpressure cost ONE connection per tick; a replica without
        # the field (older build, foreign server) falls back to
        # scraping its /metrics gauges — and a replica that is healthy
        # but cannot serve THAT scrape stays live with depth 0
        # (liveness already succeeded; no load signal is not an
        # outage).
        if "queue_depth" in health:
            depth = health["queue_depth"]
        else:
            try:
                gauges = (
                    await _get_json(
                        r.host, r.port, "/metrics", self.poll_timeout_s
                    )
                ).get("gauges", {})
                depth = gauges.get(
                    "generate.queue_depth",
                    gauges.get("batcher.queue_depth", 0),
                )
            except Exception:
                depth = 0
        r.poll_failures = 0
        prev = r.state
        r.state = (
            DRAINING if health.get("status") == "draining" else LIVE
        )
        if prev != r.state:
            _log.info("replica %s: %s -> %s", r.name, prev, r.state)
        r.queue_depth = int(depth or 0)
        r.healthz = health
        m = health.get("models")
        r.models = frozenset(m) if isinstance(m, dict) else None
        r.last_poll = time.monotonic()

    def _note_conn_failure(self, r: ReplicaState) -> None:
        """A refused/failed connect is better evidence than a stale
        poll: stop routing there NOW; the poll loop resurrects it."""
        if r.state != DOWN:
            _log.warning("replica %s marked down (connect failure)", r.name)
        r.state = DOWN

    # -- forwarding --------------------------------------------------------
    def external_depth(self, r: ReplicaState) -> int:
        """Fleet backlog EXCLUDING ``r``'s own share (scraped queue
        depths + router-side inflight of every OTHER replica): the
        backpressure signal forwarded to the replica on each request
        (``x-mlapi-router-depth``). Affinity means a replica's
        repeated prefixes cannot be served elsewhere, so fleet
        pressure is its future queue wait too — the replica feeds
        this into ``admission_estimate_ms()`` and the brownout
        ladder (ROADMAP item-3 → item-1 coupling). DOWN replicas are
        excluded: their scraped depth is frozen at the last
        successful poll, and a crashed replica's stale backlog must
        not keep the survivors shedding/browning out forever."""
        return max(0, sum(
            x.queue_depth + x.inflight
            for x in self.replicas if x is not r and x.state != DOWN
        ))

    def _build_upstream(self, request: Request, r: ReplicaState,
                        warm_peer: ReplicaState | None = None,
                        extra: dict | None = None) -> bytes:
        target = request.scope.get("raw_path") or request.path.encode()
        if isinstance(target, str):  # ASGI test transports pass str
            target = target.encode()
        # Spec-compliant ASGI servers keep the query string OUT of
        # raw_path (this repo's own server stuffs the full target in);
        # re-attach it so forwarded endpoints never silently lose
        # their parameters under uvicorn-style servers.
        query = request.scope.get("query_string") or b""
        if query and b"?" not in target:
            target += b"?" + query
        head = bytearray(
            b"%s %s HTTP/1.1\r\n" % (request.method.encode(), target)
        )
        head += b"host: %s\r\n" % r.name.encode()
        for k, v in request.scope.get("headers", []):
            # x-mlapi-router-depth and x-mlapi-warm-peer are
            # router-authored below; a copy of a client-sent (or
            # upstream-router-sent) one would let callers spoof fleet
            # pressure into the replica's admission estimate — or aim
            # the replica's KV fetches at an arbitrary host.
            if k.lower() not in _HOP_HEADERS and k.lower() not in (
                b"x-mlapi-router-depth",
                b"x-mlapi-warm-peer",
                # r18 disaggregation headers are router-authored too:
                # a client-sent copy could aim a prefill replica's KV
                # pushes at an arbitrary host or claim a staged
                # transfer it never produced.
                b"x-mlapi-decode-peer",
                b"x-mlapi-kv-xfer",
                # The tenant marker is router-authored from the
                # body's validated adapter id — a client-sent copy is
                # an impersonation/header-injection vector.
                b"x-mlapi-adapter",
                # The model marker is router-authored from the
                # registered route (r22) — same rule.
                b"x-mlapi-model",
            ):
                head += k + b": " + v + b"\r\n"
        head += b"content-length: %d\r\n" % len(request.body)
        # Router backpressure rides every forwarded request: the
        # fleet's backlog as this router sees it, minus the target's
        # own share (it knows its own queue better than our poll).
        head += b"x-mlapi-router-depth: %d\r\n" % self.external_depth(r)
        if warm_peer is not None:
            # Warmth hint (r17): this forward misses the key's
            # HRW-preferred replica — name it, so the target can
            # fetch the prefix KV from where it is warm instead of
            # cold-prefilling (--kv-peer-fetch replicas; others
            # ignore the header).
            head += b"x-mlapi-warm-peer: %s\r\n" % warm_peer.name.encode()
        for k, v in (extra or {}).items():
            head += b"%s: %s\r\n" % (k.encode(), v.encode())
        head += b"connection: close\r\n\r\n"
        return bytes(head) + request.body

    @staticmethod
    def _relay_headers(headers: dict[bytes, bytes]) -> dict[str, str]:
        return {
            k.decode("latin-1"): v.decode("latin-1")
            for k, v in headers.items()
            if k not in _HOP_HEADERS
        }

    async def _attempt(self, r: ReplicaState, request: Request,
                       warm_peer: ReplicaState | None = None,
                       extra: dict | None = None) -> Response:
        """One forward attempt against one replica. Returns the relay
        response (unary fully read; streams as a relaying iterator).
        Raises :class:`_SubmitError` on pre-commit failures."""
        try:
            # Bounded connect: a black-holed replica (packet-dropping
            # partition, not a refusal) must fail into the retryable
            # pre-submit path in seconds, not the OS's ~2-minute TCP
            # connect timeout.
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(r.host, r.port),
                self.poll_timeout_s,
            )
        except (OSError, asyncio.TimeoutError) as e:
            self._note_conn_failure(r)
            raise _SubmitError(
                f"connect to replica {r.name} failed: {e}", retryable=True
            ) from None
        r.inflight += 1
        stream_owns = False
        try:
            try:
                # The router_forward SUBMIT seam: fires BEFORE the
                # first request byte leaves the router, so a failover
                # after an injected raise can never duplicate work.
                await _fire_async("router_forward")
            except faults.InjectedFault as e:
                raise _SubmitError(
                    f"injected fault before submit to {r.name}: {e}",
                    retryable=True,
                ) from None
            submitted = False
            try:
                writer.write(
                    self._build_upstream(request, r, warm_peer, extra)
                )
                await writer.drain()
                submitted = True
                status, headers = await _read_response_head(reader)
            except (
                OSError,
                asyncio.IncompleteReadError,
                asyncio.LimitOverrunError,  # absurd upstream head size
                ConnectionError,
                ValueError,
            ) as e:
                self._note_conn_failure(r)
                # Request bytes on the wire with no response: the
                # replica MAY have started generating — no retry.
                raise _SubmitError(
                    f"replica {r.name} failed "
                    f"{'mid-response' if submitted else 'mid-submit'}: {e}",
                    retryable=not submitted,
                ) from None

            chunked = (
                headers.get(b"transfer-encoding", b"").lower() == b"chunked"
            )
            if not chunked:
                try:
                    clen = headers.get(b"content-length")
                    if clen is not None:
                        n = int(clen)
                        body = await reader.readexactly(n) if n else b""
                    else:
                        # No length and not chunked: close-delimited
                        # body (HTTP/1.1-legal, and our own
                        # "connection: close" request invites it from
                        # foreign upstreams) — read to EOF, same as
                        # the poll path's _get_json.
                        body = await reader.read()
                except (asyncio.IncompleteReadError, ValueError) as e:
                    # Truncated body / unparseable framing: a complete
                    # response never arrived, but the request DID — a
                    # 502, never a retry (the generation may have run).
                    raise _SubmitError(
                        f"replica {r.name} sent a malformed response: {e}",
                        retryable=False,
                    ) from None
                # The replica's own content-type rides in via the
                # relayed headers (Response's default is overridden by
                # the same-key entry in ``headers``).
                resp = Response(
                    body,
                    status=status,
                    headers=self._relay_headers(headers),
                )
                if status == 503:
                    # The replica shed at its door (overload, draining,
                    # pool exhaustion) — no work started, failover is
                    # safe. Honor its retry-after as this replica's
                    # shed window so the next requests skip it without
                    # waiting for a poll tick.
                    try:
                        after = float(headers.get(b"retry-after", b"1"))
                    except ValueError:
                        after = 1.0
                    r.shed_until = time.monotonic() + min(after, 5.0)
                    raise _SubmitError(
                        f"replica {r.name} shed 503",
                        retryable=True,
                        response=resp,
                    )
                return resp

            # Streaming relay: status is known, hand the body off to
            # the generator. The generator takes its OWN inflight
            # count and owns the connection — so a relay iterator the
            # asgi layer never starts (client gone in the gap between
            # handler return and first iteration) cannot leak the
            # count that feeds routability.
            stream_owns = True
            return StreamingResponse(
                self._relay_stream(r, reader, writer),
                status=status,
                headers=self._relay_headers(headers),
            )
        finally:
            r.inflight -= 1
            if not stream_owns:
                await _close_writer(writer)

    async def _relay_stream(self, r: ReplicaState, reader, writer):
        """Chunk-for-chunk NDJSON passthrough. Body bytes are relayed
        verbatim — the replica's terminal frames (``done``,
        ``deadline_exceeded``, ``draining``) reach the client
        byte-for-byte. An upstream failure mid-stream appends a
        well-formed error terminal frame; it NEVER retries (the tokens
        already relayed cannot be unsent) and never truncates."""
        r.inflight += 1
        try:
            try:
                async for chunk in _iter_chunked(reader):
                    # The router_forward MID-STREAM seam: one fire per
                    # relayed chunk (call-counted with the submit fires
                    # — after=N skips the submits).
                    await _fire_async("router_forward")
                    yield chunk
            except Exception as e:
                # CancelledError (the client disconnecting) is NOT
                # caught: it propagates so the asgi layer closes us,
                # and the finally tears the upstream down — which
                # cancels the replica's decode work like any client
                # disconnect would.
                self.stream_upstream_errors += 1
                _log.warning(
                    "upstream %s failed mid-stream: %r", r.name, e
                )
                yield json.dumps(
                    {
                        "error": (
                            f"replica {r.name} failed mid-stream: {e}"
                        ),
                        "code": "upstream_error",
                    }
                ).encode() + b"\n"
        finally:
            r.inflight -= 1
            await _close_writer(writer)

    def _hint_for(self, pref: ReplicaState | None,
                  target: ReplicaState) -> ReplicaState | None:
        """The warm-peer hint for one forward: the key's HRW head
        whenever the target is NOT it (fallback, failover, depth
        overflow, post-drain remap — every hop that loses warmth).
        Counted, so the bench/e2e can assert hinting happened from
        the router side."""
        if pref is None or pref is target:
            return None
        self.warm_peer_hints += 1
        return pref

    async def forward(
        self, request: Request, key: bytes | None = None,
        adapter: str | None = None, model: str | None = None,
    ) -> Response:
        """Route + forward one request, with the failover-once rule:
        at most one extra hop, and only for submits that provably
        never started work (connect failure, pre-submit injected
        fault, a whole-response 503). ``model`` routes within that
        model's replica group (r22) and stamps the router-authored
        ``x-mlapi-model`` marker on the hop."""
        self.forwarded += 1
        extra = None
        if model is not None:
            # Router-authored like x-mlapi-adapter below (client
            # copies are stripped in _build_upstream); the id charset
            # was validated at route-registration time, so no header
            # injection is possible through it.
            extra = {"x-mlapi-model": model}
        if adapter:
            from mlapi_tpu.serving.adapter_store import ADAPTER_ID_RE

            # Router-authored tenant marker on the hop (client copies
            # are stripped in _build_upstream). Validated against the
            # id charset BEFORE entering a header line — an id with
            # CR/LF or other junk would be header injection; such a
            # body forwards unmarked and the replica rejects it.
            if ADAPTER_ID_RE.match(adapter):
                extra = {**(extra or {}), "x-mlapi-adapter": adapter}
        # The key's HRW head, computed ONCE over all replicas and
        # threaded through BOTH attempts: the failover's second
        # choose() has no memory of the preferred replica (it
        # excludes the failed first and re-ranks the rest), so
        # without this the warm-peer hint would not survive the
        # retry hop — exactly the hop that needs it most.
        pref = self.preferred_for(key)
        try:
            first = self.choose(key, model=model)
        except NoReplicaAvailable as e:
            self.shed_no_replica += 1
            return json_response(
                {"detail": "no live replica available"},
                503,
                headers={"retry-after": str(int(e.retry_after_s))},
            )
        try:
            return await self._attempt(
                first, request, self._hint_for(pref, first), extra
            )
        except _SubmitError as e1:
            if e1.retryable:
                try:
                    # count=False: the request already charged its
                    # affinity hit/fallback on the first choose — the
                    # failover hop landing on the HRW runner-up is
                    # not a second "hit" (it missed its real
                    # preferred replica; failovers counts it).
                    second = self.choose(
                        key, exclude=first, count=False, model=model
                    )
                except NoReplicaAvailable:
                    second = None
                if second is not None:
                    self.failovers += 1
                    _log.info(
                        "failover %s -> %s (%s)",
                        first.name, second.name, e1.detail,
                    )
                    try:
                        return await self._attempt(
                            second, request,
                            self._hint_for(pref, second), extra,
                        )
                    except _SubmitError as e2:
                        return self._submit_error_response(e2, e1)
            return self._submit_error_response(e1)

    async def forward_disagg(
        self, request: Request, key: bytes | None
    ) -> Response:
        """The role-split two-hop forward (r18): hop 1 sends the
        request to a PREFILL replica (p2c by load — prompt work is
        bursty and has no warmth to preserve) naming the HRW-chosen
        DECODE replica and a fresh transfer id; the prefill replica
        streams each finished chunk's KV straight to the decode
        replica and answers with the handoff verdict. Hop 2 forwards
        the client's request to that decode replica — with the
        transfer id only when every chunk landed, so the decode
        replica either installs the pushed KV (zero prefill FLOPs)
        or cold-prefills, never waits on a wire. The fallback ladder
        degrades a role-starved fleet to MIXED routing, counted: no
        routable decode replica ⇒ the plain r14 path over whatever
        is routable; no routable prefill replica ⇒ the decode
        replica takes the cold prefill itself."""
        dec = self._pick_role(key, "decode")
        if dec is None:
            # Decode pool down: whatever is routable serves the whole
            # request, r14-style.
            self.role_fallback_mixed += 1
            return await self.forward(request, key)
        pre = self._pick_role(None, "prefill")
        if pre is None:
            # Prefill pool down: a routable replica (the decode pool,
            # in practice) accepts the cold prefill via the PLAIN
            # forward — which keeps the failover-once ladder, so a
            # decode replica dying between the health poll and this
            # forward still fails over instead of erroring the client
            # in the already-degraded state.
            self.role_fallback_mixed += 1
            return await self.forward(request, key)
        self.forwarded += 1
        self.role_disagg_forwards += 1
        self._xfer_seq += 1
        xfer = f"xf{self._xfer_seq}-{self._rng.getrandbits(48):012x}"
        complete = False
        try:
            resp = await self._attempt(
                pre, request,
                extra={
                    "x-mlapi-decode-peer": dec.name,
                    "x-mlapi-kv-xfer": xfer,
                },
            )
            if resp.status != 200:
                # The prefill replica REJECTED the request itself
                # (422 and friends): relay — the decode replica would
                # reject the same body the same way.
                return resp
            try:
                obj = json.loads(resp.body)
            except Exception:
                obj = {}
            if not obj.get("handoff"):
                # A replica that ignored the role headers (older
                # build, operator-mislabeled role) served the whole
                # generation: that IS the answer — relay it.
                return resp
            complete = bool(obj.get("complete"))
        except _SubmitError as e:
            _log.info(
                "prefill hop to %s failed (%s); decode replica "
                "cold-prefills", pre.name, e.detail,
            )
        if not complete:
            self.role_push_incomplete += 1
        try:
            return await self._attempt(
                dec, request,
                extra={"x-mlapi-kv-xfer": xfer} if complete else None,
            )
        except _SubmitError as e1:
            if e1.retryable:
                # Failover-once, decode pool first: the pushed KV
                # died with the target, so the alternate always
                # cold-prefills (no xfer header).
                second = self._pick_role(key, "decode", exclude=dec)
                if second is None:
                    try:
                        second = self.choose(key, exclude=dec, count=False)
                    except NoReplicaAvailable:
                        second = None
                if second is not None:
                    self.failovers += 1
                    _log.info(
                        "disagg failover %s -> %s (%s)",
                        dec.name, second.name, e1.detail,
                    )
                    try:
                        return await self._attempt(second, request)
                    except _SubmitError as e2:
                        return self._submit_error_response(e2, e1)
            return self._submit_error_response(e1)

    @staticmethod
    def _submit_error_response(
        e: _SubmitError, prior: _SubmitError | None = None
    ) -> Response:
        # Prefer relaying a real replica response (its 503 carries the
        # retry-after the client should honor) over synthesizing one.
        for err in (e, prior):
            if err is not None and err.response is not None:
                return err.response
        return json_response(
            {"detail": f"upstream replica failure: {e.detail}"}, 502
        )

    # -- observability ------------------------------------------------------
    def _state_counts(self) -> dict[str, int]:
        counts = {LIVE: 0, DRAINING: 0, DOWN: 0}
        for r in self.replicas:
            counts[r.state] += 1
        return counts

    def health_snapshot(self) -> dict:
        """The router-level ``/healthz``: ok while at least one
        replica is routable (the layer above should keep sending
        traffic), degraded otherwise."""
        now = time.monotonic()
        counts = self._state_counts()
        routable = sum(
            r.routable(now, self.queue_depth_limit) for r in self.replicas
        )
        # Per-model replica groups (r22): the health rollup of each
        # advertised model id — routable members vs total advertisers.
        # Discovered from the polls, so an all-single-model fleet has
        # no groups and the block is absent (bit-identical to r21).
        groups: dict = {}
        for r in self.replicas:
            for mid in r.models or ():
                g = groups.setdefault(mid, {"routable": 0, "total": 0})
                g["total"] += 1
                g["routable"] += int(
                    r.routable(now, self.queue_depth_limit)
                )
        return {
            "status": "ok" if routable else "degraded",
            **({"model_groups": groups} if groups else {}),
            "router": True,
            "policy": self.policy,
            "affinity_prefix_bytes": self.affinity_prefix_bytes,
            "replicas_live": counts[LIVE],
            "replicas_draining": counts[DRAINING],
            "replicas_down": counts[DOWN],
            "replicas": [
                {
                    "name": r.name,
                    "state": r.state,
                    **({"role": r.role} if self.role_split else {}),
                    "queue_depth": r.queue_depth,
                    "inflight": r.inflight,
                    "shedding": now < r.shed_until,
                    "last_poll_age_s": (
                        round(now - r.last_poll, 3)
                        if r.last_poll is not None
                        else None
                    ),
                }
                for r in self.replicas
            ],
        }

    async def metrics_snapshot(self) -> dict:
        """The aggregated ``/metrics``: counters SUMMED across
        replicas (a counter is a rate source — the fleet total is the
        meaningful number), gauges LABELED per replica (a gauge is a
        state — summing two queue depths hides the hot replica), plus
        the router's own counters and state gauges. Scrapes are fresh
        (this endpoint is the fleet dashboard); a replica that fails
        its scrape contributes its last polled snapshot, flagged
        stale."""
        results = await asyncio.gather(
            *(
                _get_json(r.host, r.port, "/metrics", self.poll_timeout_s)
                for r in self.replicas
            ),
            return_exceptions=True,
        )
        counters: dict = {}
        gauges: dict = {}
        stale = []
        for r, snap in zip(self.replicas, results):
            if isinstance(snap, BaseException):
                snap = r.metrics  # last good scrape, may be {}
                stale.append(r.name)
            else:
                r.metrics = snap
                # A fresh scrape is a better load signal than the last
                # poll tick; fold it into the routing state too.
                g = snap.get("gauges", {})
                r.queue_depth = int(
                    g.get(
                        "generate.queue_depth",
                        g.get("batcher.queue_depth", r.queue_depth),
                    )
                    or 0
                )
            for k, v in snap.get("counters", {}).items():
                if isinstance(v, (int, float)):
                    counters[k] = counters.get(k, 0) + v
            for k, v in snap.get("gauges", {}).items():
                gauges[f"replica.{r.name}.{k}"] = v
        counters["router.forwarded"] = self.forwarded
        counters["router.affinity_hits"] = self.affinity_hits
        counters["router.affinity_fallbacks"] = self.affinity_fallbacks
        counters["router.failovers"] = self.failovers
        counters["router.shed_no_replica"] = self.shed_no_replica
        counters["router.stream_upstream_errors"] = (
            self.stream_upstream_errors
        )
        counters["router.warm_peer_hints"] = self.warm_peer_hints
        if self.role_split:
            # Role-split fleets only: an all-mixed topology's
            # /metrics stays bit-identical to r17.
            counters["router.role_disagg_forwards"] = (
                self.role_disagg_forwards
            )
            counters["router.role_fallback_mixed"] = (
                self.role_fallback_mixed
            )
            counters["router.role_push_incomplete"] = (
                self.role_push_incomplete
            )
        if any(r.models is not None for r in self.replicas) or (
            self.model_fallbacks
        ):
            # Multi-model fleets only — same bit-identity rule as the
            # role-split block above.
            counters["router.model_fallbacks"] = self.model_fallbacks
        state_counts = self._state_counts()
        gauges["router.replicas_live"] = state_counts[LIVE]
        gauges["router.replicas_draining"] = state_counts[DRAINING]
        gauges["router.replicas_down"] = state_counts[DOWN]
        for r in self.replicas:
            gauges[f"router.replica.{r.name}.queue_depth"] = r.queue_depth
            gauges[f"router.replica.{r.name}.inflight"] = r.inflight
        return {
            "counters": counters,
            "gauges": gauges,
            "replicas_stale": stale,
        }


def build_router_app(router: Router, model_ids=None) -> App:
    """The router as an ASGI app on the framework's own server: the
    replica API surface forwarded (``/generate`` with affinity,
    ``/predict`` and ``/files/`` by load), plus the router-level
    ``/healthz`` and aggregated ``/metrics``. ``model_ids`` (the
    supervisor's ``--model`` ids, r22) additionally fronts
    ``/models/<id>/{generate,predict}``, each routed within that
    model's replica group. Handlers take the raw request — the
    REPLICA owns validation, so a 422 relays with the exact byte
    shape a direct client would have seen."""
    import re as _re

    app = App(title="mlapi-tpu-router")
    app.state["router"] = router

    @app.on_startup
    async def _start():
        faults.arm_from_env()
        await router.start()
        _log.info(
            "routing over %d replicas (%s)",
            len(router.replicas), router.policy,
        )

    @app.on_shutdown
    async def _stop():
        await router.stop()

    @app.post("/generate")
    async def generate(request: Request):
        obj = router.parse_body(request.body)  # parsed ONCE
        key = router.routing_key_of(obj)
        if router.wants_disagg_of(obj):
            # Role-split fleet + plain prompt: the two-hop
            # prefill→decode path (r18). Prefix-carrying requests
            # stay on the affinity path below — their warmth story is
            # the r14/r17 machinery; adapter-carrying ones too (the
            # gate above keeps a tenant's slot working-set on ONE
            # replica).
            return await router.forward_disagg(request, key)
        aid = obj.get("adapter") if obj else None
        return await router.forward(
            request, key=key,
            adapter=aid if isinstance(aid, str) else None,
        )

    @app.post("/predict")
    async def predict(request: Request):
        # No prefix economics on classification rows: route by load
        # (power of two choices over the routable set) — unless the
        # row names a tenant adapter, which routes by the same HRW
        # affinity as /generate (the tenant's slot lives somewhere).
        obj = router.parse_body(request.body)
        aid = obj.get("adapter") if obj else None
        if isinstance(aid, str) and aid:
            return await router.forward(
                request,
                key=aid.encode("utf-8", "surrogatepass")[
                    : router.affinity_prefix_bytes
                ],
                adapter=aid,
            )
        return await router.forward(request)

    def _install_model_routes(mid: str) -> None:
        # Closure-per-id, like app.py's per-model install loop: the
        # route table is static (exact-path match, no params), built
        # once from the same --model set the replicas serve.
        @app.post(f"/models/{mid}/generate")
        async def model_generate(request: Request, _mid=mid):
            obj = router.parse_body(request.body)
            aid = obj.get("adapter") if obj else None
            return await router.forward(
                request, key=router.routing_key_of(obj),
                adapter=aid if isinstance(aid, str) else None,
                model=_mid,
            )

        @app.post(f"/models/{mid}/predict")
        async def model_predict(request: Request, _mid=mid):
            return await router.forward(request, model=_mid)

    for mid in model_ids or ():
        if not _re.fullmatch(r"[A-Za-z0-9._-]+", mid):
            raise ValueError(f"model id {mid!r} is not URL-path-safe")
        _install_model_routes(mid)

    @app.post("/files/")
    async def files(request: Request):
        return await router.forward(request)

    @app.get("/healthz")
    async def healthz():
        return router.health_snapshot()

    @app.get("/metrics")
    async def metrics():
        return await router.metrics_snapshot()

    return app
