"""The host-loop speculative-decoding phase of generative serving.

One :class:`SpecPhase` per :class:`TextGenerationEngine`: it owns the
warmed-shape set and runs the draft-propose / target-verify rounds —
solo (:meth:`run_solo`) and batched (:meth:`run_batched`) — plus the
startup warm grid (:meth:`warm`). The engine's ``_run_batch`` hands it
the live cache and host mirrors at a round boundary and resumes
chunked decoding from whatever ``(cache, pos)`` comes back; yield
discipline routes through ``engine._spec_should_yield`` (tests
monkeypatch it there). Split out of ``engine.py`` (r04 VERDICT
"Next" #7). The library twins live in ``ops/speculative.py``.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from mlapi_tpu.serving import faults

_log = logging.getLogger(__name__)


class SpecPhase:
    def __init__(self, engine):
        self.eng = engine
        # (bucket, total[, batch, "batched"]) spec-program shapes
        # proven compiled — strict mode runs the phase only for these.
        self.warmed: set = set()

    def run_solo(self, r, cache, pos, total, bucket, tok, step,
                    produced, n_pad, keys, history, temps, topk, topp,
                    ensure=None):
        """Run speculative rounds for a single request against the
        engine's live target cache; returns ``(cache, pos)`` for
        the normal decode loop to resume from. Mutates the host
        mirrors (``tok``, ``step``, ``produced``) in place — the
        handoff contract with ``_run_batch``. Library twins:
        ``ops/speculative.speculative_generate`` (greedy rows —
        byte-exact stream) and ``.speculative_sample`` (sampled rows
        under ``spec_sample=True`` — exact target distribution); this
        variant adds the engine's per-row pad mask, streaming pushes,
        admission handoff, and RE-ENGAGEMENT: ``history`` (the row's
        emitted tokens so far) replays into a fresh draft cache
        through already-compiled chunk programs, so a stream whose
        transient joiners departed speculates again for its tail.

        Each round is TWO device dispatches (scan-propose + verify)
        regardless of k — through the tunneled attach this, not the
        acceptance rate, is what sets the wall-clock win.

        ``ensure`` (paged targets): ``cache = ensure(cache, lo, hi)``
        maps virtual slots ``[lo, hi)`` to pool pages before each
        verify block writes them — the phase's stand-in for the chunk
        loop's boundary allocation. The DRAFT cache stays contiguous
        (the draft has no pool), so the draft-side programs are
        untouched by paging."""
        eng = self.eng
        from mlapi_tpu.models.gpt import (
            decode_chunk_fn, extend_chunk_fn, prefill_fn,
        )
        from mlapi_tpu.ops.speculative import (
            propose_fn, sample_verify_fn, verify_fn,
        )

        k = eng.spec_k
        # The draft prefill/replay are EXPENSIVE compiles: strict mode
        # requires them pre-warmed regardless of attach RTT (same rule
        # as the admission joiner prefill).
        if eng._strict_admit and (bucket, total) not in self.warmed:
            return cache, pos
        # Cheap disqualifiers BEFORE any device work: nothing to
        # speculate, no block room, or joiners already waiting.
        if r.n_new - produced[0] <= 1 or pos + 1 + k + 1 > total:
            return cache, pos
        if eng._spec_should_yield():
            return cache, pos

        npj = jnp.asarray(n_pad)
        zt = jnp.zeros((1,), jnp.float32)
        z0 = jnp.zeros((1,), jnp.int32)
        o1 = jnp.ones((1,), jnp.float32)
        keys_j = jnp.asarray(keys)

        # Draft prefill over the SAME padded prompt row (its KV layout
        # mirrors the target's, pads masked identically) ...
        row = np.full((1, bucket), eng.tokenizer.pad_id, np.int32)
        row[0, bucket - len(r.row):] = r.row
        _, d_cache = prefill_fn(eng.draft_model, total)(
            eng.draft_params, jnp.asarray(row), keys_j, zt, npj, z0, o1,
        )
        # ... then replay the already-emitted tokens (all but the
        # unconsumed last, which seeds the first round) in
        # fixed-width chunks plus single-step remainder — every
        # program already compiled for this (bucket, total).
        replay = history[:-1]
        d_replay_upto = bucket
        ri = 0
        while len(replay) - ri >= eng.chunk:
            blk = np.asarray([replay[ri:ri + eng.chunk]], np.int32)
            d_cache, _ = extend_chunk_fn(
                eng.draft_model, eng.chunk, total
            )(
                eng.draft_params, d_cache, jnp.asarray(blk),
                jnp.int32(d_replay_upto), npj,
            )
            d_replay_upto += eng.chunk
            ri += eng.chunk
        self.warmed.add((bucket, total))

        def dstep(dcache, token, at):
            toks, dcache, _ = decode_chunk_fn(eng.draft_model, 1)(
                eng.draft_params, dcache,
                jnp.asarray(np.asarray([token], np.int32)),
                jnp.int32(at), npj, zt, keys_j, jnp.int32(0), z0, o1,
                jnp.int32(0), jnp.int32(0),
            )
            return int(np.asarray(toks)[0, 0]), dcache

        while ri < len(replay):  # sub-chunk replay remainder
            _, d_cache = dstep(d_cache, replay[ri], d_replay_upto)
            d_replay_upto += 1
            ri += 1

        sampled = bool(temps[0] > 0.0)
        temps_j = jnp.asarray(temps)
        topk_j = jnp.asarray(topk)
        topp_j = jnp.asarray(topp)
        d_upto = t_upto = pos
        d_pend = [int(tok[0])]
        while not r.cancelled and produced[0] < r.n_new:
            if eng._expire_if_due(r, "decode"):
                break  # round boundary = a deadline dispatch boundary
            if eng._spec_should_yield():
                break  # joiners waiting: normal loop admits them
            budget = r.n_new - produced[0]
            if budget <= 1 or t_upto + 1 + k + 1 > total:
                break
            # Draft phase: ONE scanned dispatch consumes the pending
            # accepted tokens and chains all k proposals. Greedy rows
            # (temp 0) argmax inside the same program; sampled rows
            # draw from the draft's warped distribution at the
            # DRAFT-tagged per-token streams.
            if ensure is not None:
                # The verify block writes [t_upto, t_upto + k + 1).
                cache = ensure(cache, t_upto, t_upto + k + 1)
            step0 = int(produced[0])
            d_cache, props, q_probs = propose_fn(
                eng.draft_model, len(d_pend), k, sampled
            )(
                eng.draft_params, d_cache,
                jnp.asarray(np.asarray(d_pend, np.int32)),
                jnp.int32(d_upto), npj, keys_j, temps_j, topk_j,
                topp_j, jnp.int32(step0),
            )
            d_upto += len(d_pend) + k - 1
            usable = min(k, budget - 1)
            faults.fire("spec_verify")
            if sampled:
                cache, packed = sample_verify_fn(eng.model, k + 1)(
                    eng.params, cache, jnp.int32(int(tok[0])), props,
                    jnp.int32(t_upto), npj, q_probs, keys_j, temps_j,
                    topk_j, topp_j, jnp.int32(step0),
                    jnp.int32(usable),
                )
                packed = np.asarray(packed)
                m = int(packed[k + 1])
                emitted = packed[: m + 1].tolist()
                kth = int(packed[k - 1])  # props[k-1] when m == k
            else:
                proposals = np.asarray(props).tolist()
                cache, expect = verify_fn(eng.model, k + 1)(
                    eng.params, cache,
                    jnp.asarray(
                        np.asarray([[int(tok[0]), *proposals]], np.int32)
                    ),
                    jnp.int32(t_upto), npj,
                )
                expect = np.asarray(expect)[0]
                m = 0
                while m < usable and proposals[m] == int(expect[m]):
                    m += 1
                emitted = [*proposals[:m], int(expect[m])]
                kth = proposals[-1]
            r.push({"token_ids": emitted})
            history.extend(emitted)  # keeps replay state current
            produced[0] += m + 1
            step[0] = produced[0]
            t_upto += m + 1
            tok[0] = emitted[-1]
            eng.spec_rounds += 1
            eng.spec_drafted += usable
            eng.spec_accepted += m
            if m == k:
                d_pend = [kth, emitted[-1]]
            else:
                d_upto = t_upto
                d_pend = [emitted[-1]]
        return cache, t_upto

    def run_batched(self, reqs, cache, pos, total, bucket,
                            prompt, tok, step, produced, done, n_pad,
                            keys, b_cur, ensure=None,
                            paged_realign=None):
        """Speculative rounds for a WHOLE freshly-formed greedy batch:
        every row drafts k proposals and verifies them in one block
        per round, advancing by its OWN acceptance length (the
        rank-polymorphic per-row position layout). Rows that finish
        (or cancel) freeze and ride as dummies — their writes land
        beyond their valid bound, masked until the batch ends.

        Handoff: the phase exits at a round boundary when admission
        candidates arrive (or every row is done) and REALIGNS the
        cache — each row rolls right by ``max(t_upto) - t_upto_b``
        with ``n_pad`` bumped by the same amount, which keeps every
        effective position identical (wpe indices and stored rotary
        phases key on effective position) — so the scalar-``pos``
        chunk loop resumes exactly as if the batch had always been
        synchronized. Engages only at batch FORMATION; after a
        handoff the batch stays on the chunk loop (library twin with
        the full algebra: ``ops.speculative.speculative_generate_batched``).

        Paged targets pass ``ensure`` (per-round page mapping — see
        :meth:`run_solo`) and ``paged_realign(cache, delta, top)``,
        which replaces ``realign_fn``'s byte roll: a host page-table
        shift when every delta is a page multiple, the counted
        device row-gather rewrite otherwise (DESIGN §16).
        """
        eng = self.eng
        from mlapi_tpu.models.gpt import prefill_fn, realign_fn
        from mlapi_tpu.ops.speculative import (
            propose_batched_fn, verify_fn,
        )

        k = eng.spec_k
        key = (bucket, total, b_cur, "batched")
        if eng._strict_admit and key not in self.warmed:
            return cache, pos

        if eng._spec_should_yield():
            return cache, pos  # joiners already staged: skip the
            # whole-batch draft prefill, not just round one
        zb = jnp.zeros((b_cur,), jnp.int32)
        zt = jnp.zeros((b_cur,), jnp.float32)
        ob = jnp.ones((b_cur,), jnp.float32)
        npj = jnp.asarray(n_pad)
        keys_j = jnp.asarray(keys)
        _, d_cache = prefill_fn(eng.draft_model, total)(
            eng.draft_params, jnp.asarray(prompt), keys_j, zt, npj,
            zb, ob,
        )
        self.warmed.add(key)

        b = len(reqs)
        t_upto = np.full((b_cur,), pos, np.int64)
        d_upto = np.full((b_cur,), pos, np.int64)
        d_pend = [[int(tok[i])] for i in range(b_cur)]

        while True:
            if eng._spec_should_yield():
                break  # joiners waiting: realign and hand off
            for i in range(b):
                if not done[i]:
                    # Round boundary = dispatch boundary: expired rows
                    # cancel (terminal frame pushed) and freeze below.
                    eng._expire_if_due(reqs[i], "decode")
            active = [
                i for i in range(b)
                if not done[i] and not reqs[i].cancelled
                and reqs[i].n_new - produced[i] >= 1
            ]
            if not active:
                break
            # Desync-headroom invariant: after ANY round, the realign
            # frontier (max position, growing by <= k+1) plus the
            # laggiest row's remaining budget (shrinking by >= 1)
            # must still fit the cache — otherwise a lopsided round
            # could strand a slow row past the window and the chunk
            # loop would truncate it. Stop speculating one round
            # early instead; the synchronized chunk loop finishes
            # within the formation guarantee.
            rem = max(reqs[i].n_new - produced[i] for i in active)
            if int(t_upto.max()) + k + 1 + rem - 1 > total:
                break
            pend_buf = np.zeros((b_cur, 2), np.int32)
            n_in = np.ones((b_cur,), np.int32)
            for i in range(b_cur):
                pend = d_pend[i]
                n_in[i] = len(pend)
                pend_buf[i, : len(pend)] = pend
            d_cache, props, _ = propose_batched_fn(eng.draft_model, k)(
                eng.draft_params, d_cache, jnp.asarray(pend_buf),
                jnp.asarray(n_in),
                jnp.asarray(d_upto.astype(np.int32)), npj, keys_j,
                zt, zb, ob, zb,
            )
            props = np.asarray(props)
            d_upto += n_in + k - 1

            if ensure is not None:
                # Every row's verify block writes
                # [t_upto_b, t_upto_b + k + 1).
                cache = ensure(
                    cache, int(t_upto.min()), int(t_upto.max()) + k + 1
                )
            block = np.concatenate(
                [np.asarray(tok[:b_cur], np.int32)[:, None], props],
                axis=1,
            )
            faults.fire("spec_verify")
            cache, expect = verify_fn(eng.model, k + 1)(
                eng.params, cache, jnp.asarray(block),
                jnp.asarray(t_upto.astype(np.int32)), npj,
            )
            expect = np.asarray(expect)
            eng.spec_rounds += 1
            for i in active:
                r = reqs[i]
                budget = r.n_new - produced[i]
                usable = min(k, budget - 1)
                m = 0
                while m < usable and props[i, m] == int(expect[i, m]):
                    m += 1
                bonus = int(expect[i, m])
                emitted = [int(t) for t in props[i, :m]] + [bonus]
                r.push({"token_ids": emitted})
                produced[i] += m + 1
                step[i] = produced[i]
                t_upto[i] += m + 1
                tok[i] = bonus
                eng.spec_drafted += usable
                eng.spec_accepted += m
                if m == k:
                    d_pend[i] = [int(props[i, -1]), bonus]
                else:
                    d_upto[i] = t_upto[i]
                    d_pend[i] = [bonus]
                if produced[i] >= r.n_new:
                    r.push(None)
                    done[i] = True
            for i in range(b_cur):
                if i >= b or done[i] or (
                    i < b and reqs[i].cancelled
                ):
                    # Frozen/dummy rows: keep their state pinned so
                    # the realign delta stays correct.
                    d_upto[i] = t_upto[i]
                    d_pend[i] = d_pend[i][-1:]

        top = int(t_upto.max())
        if int(t_upto.min()) < top:
            delta = (top - t_upto).astype(np.int32)
            if paged_realign is not None:
                cache = paged_realign(cache, delta, top)
            else:
                cache = realign_fn()(cache, jnp.asarray(delta))
            n_pad += delta  # in place: the chunk loop's mirror
        return cache, top

    def _target_cache(self, bsz: int, total: int):
        """A target-side cache pytree of the shape the phase's verify
        programs will ACTUALLY take for a ``bsz``-row batch at tier
        ``total``: contiguous for contiguous engines; for paged
        engines the pool leaves + a null ``[bsz, npv]`` table — the
        exact operand shapes ``BatchRun`` dispatches, which is what
        makes the warmed keys honest for paged batches (the r10
        strict-mode decline existed because this used to warm
        contiguous shapes a paged batch never dispatches). Null-table
        warm writes land in the never-read null page, so the pool is
        untouched; callers must hand the donated result back through
        :meth:`_rebind_pool`."""
        eng = self.eng
        if eng.pool is None:
            return eng.model.init_cache(bsz, total)
        from mlapi_tpu.ops.quant import paged_cache_tree

        npv = -(-total // eng.pool.page)
        return paged_cache_tree(
            eng.pool.layers, np.zeros((bsz, npv), np.int32)
        )

    def _rebind_pool(self, cache) -> None:
        """Donating warm programs consumed the pool's device arrays;
        re-bind them from the returned cache (no-op contiguous)."""
        if self.eng.pool is not None:
            from mlapi_tpu.ops.quant import paged_pools_of

            self.eng.pool.layers = paged_pools_of(cache)

    def warm(self) -> int:
        """Compile the speculative-phase programs (draft prefill, the
        scanned propose for both pending widths, the verify block —
        greedy argmax and, under ``spec_sample``, the sampled
        acceptance-rejection variant — and the replay-remainder step)
        for every prompt bucket at the default cache tier, off the
        request path. PAGED engines warm the POOL-SHAPED target
        programs (verify blocks over pool leaves + tables, and the
        sub-page realign repack) — the missing piece that kept
        strict-admit mode declining paged speculation (r10 → r11)."""
        eng = self.eng
        from mlapi_tpu.models.gpt import (
            decode_chunk_fn, extend_chunk_fn, prefill_fn,
        )
        from mlapi_tpu.ops.speculative import (
            propose_fn, sample_verify_fn, verify_fn,
        )

        shapes = 0
        zt = jnp.zeros((1,), jnp.float32)
        z0 = jnp.zeros((1,), jnp.int32)
        o1 = jnp.ones((1,), jnp.float32)
        key1 = jnp.asarray(eng._key_data(0)[None])
        k = eng.spec_k
        for bucket in eng.prompt_buckets:
            total = eng._cache_len(bucket, eng.default_max_new_tokens)
            if bucket + 1 + k + 1 > total:
                continue
            row = np.full((1, bucket), eng.tokenizer.pad_id, np.int32)
            npj = jnp.asarray(np.asarray([bucket - 1], np.int32))
            _, d_cache = prefill_fn(eng.draft_model, total)(
                eng.draft_params, jnp.asarray(row), key1, zt, npj,
                z0, o1,
            )
            # Rounds start from 1 pending token (partial acceptance)
            # or 2 (a fully-accepted round's unfed k-th proposal);
            # sampled speculation compiles its own propose variant.
            variants = (False, True) if eng.spec_sample else (False,)
            for n_in in (1, 2):
                for sampled in variants:
                    d_cache, _, _ = propose_fn(
                        eng.draft_model, n_in, k, sampled
                    )(
                        eng.draft_params, d_cache,
                        jnp.asarray(np.zeros((n_in,), np.int32)),
                        jnp.int32(bucket), npj, key1,
                        o1 if sampled else zt, z0, o1,
                        jnp.int32(0),
                    )
            _, d_cache, _ = decode_chunk_fn(eng.draft_model, 1)(
                eng.draft_params, d_cache, jnp.asarray(
                    np.zeros((1,), np.int32)
                ),
                jnp.int32(bucket), npj, zt, key1, jnp.int32(0), z0, o1,
                jnp.int32(0), jnp.int32(0),
            )
            block = np.zeros((1, k + 1), np.int32)
            wcache, _ = verify_fn(eng.model, k + 1)(
                eng.params, self._target_cache(1, total),
                jnp.asarray(block), jnp.int32(bucket), npj,
            )
            self._rebind_pool(wcache)
            if eng.spec_sample:
                wcache, _ = sample_verify_fn(eng.model, k + 1)(
                    eng.params, self._target_cache(1, total),
                    jnp.int32(0),
                    jnp.asarray(np.zeros((k,), np.int32)),
                    jnp.int32(bucket), npj,
                    jnp.full((k, eng.model.vocab_size),
                             1.0 / eng.model.vocab_size, np.float32),
                    key1, o1, z0, o1, jnp.int32(0), jnp.int32(k),
                )
                self._rebind_pool(wcache)
            if bucket + eng.chunk <= total:
                # Re-engagement replays history in chunk-wide blocks.
                extend_chunk_fn(eng.draft_model, eng.chunk, total)(
                    eng.draft_params, d_cache,
                    jnp.asarray(
                        np.zeros((1, eng.chunk), np.int32)
                    ),
                    jnp.int32(bucket), npj,
                )
            self.warmed.add((bucket, total))
            shapes += 1
            # Batched-speculation grid: the whole-batch draft
            # prefill, the per-row propose scan, the vector-position
            # verify retrace, and the realign roll, per batch size.
            from mlapi_tpu.models.gpt import realign_fn
            from mlapi_tpu.ops.speculative import propose_batched_fn

            # No batch of size 2 can ever form when max_batch < 2 —
            # skip the whole batched grid rather than paying its
            # draft-prefill/propose/verify/realign compiles at startup.
            bsz = 2
            while eng.max_batch > 1 and bsz <= max(
                2, 1 << (eng.max_batch - 1).bit_length()
            ):
                bt = total  # the enclosing loop's tier
                rows_b = np.full(
                    (bsz, bucket), eng.tokenizer.pad_id, np.int32
                )
                np_b = jnp.asarray(
                    np.full((bsz,), bucket - 1, np.int32)
                )
                keys_b = jnp.asarray(
                    np.stack([eng._key_data(0)] * bsz)
                )
                ztb = jnp.zeros((bsz,), jnp.float32)
                zbb = jnp.zeros((bsz,), jnp.int32)
                obb = jnp.ones((bsz,), jnp.float32)
                _, dcb = prefill_fn(eng.draft_model, bt)(
                    eng.draft_params, jnp.asarray(rows_b), keys_b,
                    ztb, np_b, zbb, obb,
                )
                propose_batched_fn(eng.draft_model, k)(
                    eng.draft_params, dcb,
                    jnp.asarray(np.zeros((bsz, 2), np.int32)),
                    jnp.asarray(np.ones((bsz,), np.int32)),
                    jnp.asarray(np.full((bsz,), bucket, np.int32)),
                    np_b, keys_b, ztb, zbb, obb, zbb,
                )
                wcache, _ = verify_fn(eng.model, k + 1)(
                    eng.params, self._target_cache(bsz, bt),
                    jnp.asarray(np.zeros((bsz, k + 1), np.int32)),
                    jnp.asarray(np.full((bsz,), bucket, np.int32)),
                    np_b,
                )
                self._rebind_pool(wcache)
                if eng.pool is None:
                    realign_fn()(
                        eng.model.init_cache(bsz, bt), zbb,
                    )
                else:
                    # The paged handoff's page-aligned case is a host
                    # table op (nothing to compile); warm the counted
                    # sub-page device repack so a strict-mode batch
                    # never pays its compile mid-phase.
                    from mlapi_tpu.models.gpt import paged_realign_fn

                    wcache = paged_realign_fn()(
                        self._target_cache(bsz, bt), zbb,
                    )
                    self._rebind_pool(wcache)
                self.warmed.add((bucket, bt, bsz, "batched"))
                shapes += 1
                bsz *= 2
        return shapes

