"""Inference engine: params resident on device, one warmed jit forward.

Inverts the reference's hot-path design, which re-loads the pickled
model from disk **on every request** (``main.py:19``) and then runs
the matmul twice (``predict`` then ``predict_proba``,
``main.py:21-22``). Here:

- The checkpoint is loaded **once** at startup (onto the mesh if one
  is given).
- The forward pass is jit-compiled once per batch-bucket size at
  warmup, so no request ever pays XLA compilation.
- Prediction *and* probability come out of a single device call:
  ``argmax`` + ``max(softmax)`` over one set of logits, with only two
  scalars per row transferred back to the host.
- Requests are padded to a small set of bucket sizes so arbitrary
  batch sizes never trigger recompilation (static shapes — XLA
  requirement, SURVEY §7 step 4).
"""

from __future__ import annotations

import bisect
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mlapi_tpu.utils.logging import get_logger
from mlapi_tpu.utils.vocab import LabelVocab

_log = get_logger("serving.engine")

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)


class InferenceEngine:
    """Batched classification inference over a jitted forward pass.

    Rows are float32 feature vectors; see
    :class:`TextClassificationEngine` for the token-id variant.
    """

    kind = "tabular"
    input_dtype = np.float32

    def __init__(
        self,
        model,
        params,
        vocab: LabelVocab,
        feature_names: Sequence[str],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: jax.sharding.Mesh | None = None,
        meta: dict | None = None,
    ):
        self.model = model
        self.vocab = vocab
        self.feature_names = tuple(feature_names)
        self.num_features = int(
            getattr(model, "num_features", 0) or len(self.feature_names) or 1
        )
        self.buckets = tuple(sorted(buckets))
        self.mesh = mesh
        self.meta = dict(meta or {})
        if mesh is not None:
            from mlapi_tpu.parallel import DATA_AXIS, params_for_model

            axis = mesh.shape[DATA_AXIS]
            bad = [b for b in self.buckets if b % axis]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by data-axis size {axis}"
                )
            # Serve in the model's declared layout (e.g. Wide&Deep's
            # vocab-sharded tables) — the reason to serve on a mesh at
            # all is that the params don't fit (or shouldn't be
            # copied) per chip.
            params = params_for_model(model, params, mesh)
        else:
            params = jax.device_put(params)
        self.params = params

        def forward(p, x):
            logits = self.model.apply(p, x)
            probs = jax.nn.softmax(logits, axis=-1)
            # ONE fused [B, 2] output (id, max-prob) — a single
            # device→host transfer. Two separate outputs would cost two
            # round trips, which doubles latency when the chip is
            # reached over a network tunnel (measured: 65 ms per
            # readback on the dev tunnel).
            return jnp.stack(
                [jnp.argmax(logits, axis=-1).astype(jnp.float32),
                 jnp.max(probs, axis=-1)],
                axis=-1,
            )

        self._forward = jax.jit(forward)

    @classmethod
    def from_checkpoint(
        cls,
        path,
        model=None,
        *,
        mesh: jax.sharding.Mesh | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
    ) -> "InferenceEngine":
        """Build an engine from a committed checkpoint dir.

        The model is reconstructed from the checkpoint's own config
        (``model`` registry name + kwargs) unless one is passed in,
        and the engine class follows the model's ``input_kind``
        (tabular feature rows vs text token ids).
        """
        from mlapi_tpu.checkpoint import load_checkpoint
        from mlapi_tpu.models import get_model

        if model is None:
            # Peek the manifest for the model config, then restore with
            # signature validation against the freshly-built model.
            meta = _load_meta_only(path)
            model = get_model(
                meta.config["model"], **meta.config.get("model_kwargs", {})
            )
            feature_names = meta.config.get("feature_names", ())
        else:
            feature_names = ()

        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            model.init(jax.random.key(0)),
        )
        params, meta = load_checkpoint(path, abstract)

        if hasattr(model, "generate"):
            # Generative LM: no label vocab — the output space is the
            # tokenizer's.
            from mlapi_tpu.text import load_tokenizer
            from mlapi_tpu.text.tokenizer import tokenizer_from_fingerprint

            tokenizer = (
                tokenizer_from_fingerprint(meta.config["tokenizer"])
                if "tokenizer" in meta.config
                else load_tokenizer(model.vocab_size)
            )
            return TextGenerationEngine(
                model,
                params,
                tokenizer=tokenizer,
                mesh=mesh,
                meta={"step": meta.step, "config_hash": meta.config_hash},
            )

        if meta.vocab is None:
            raise ValueError(f"checkpoint {path} has no label vocab; cannot serve")
        feature_names = meta.config.get("feature_names", feature_names)

        if getattr(model, "input_kind", "tabular") == "text":
            from mlapi_tpu.text import load_tokenizer
            from mlapi_tpu.text.tokenizer import tokenizer_from_fingerprint

            if "tokenizer" in meta.config:
                # Rebuild exactly the training tokenizer or refuse —
                # serving must never silently substitute a different
                # tokenization scheme.
                tokenizer = tokenizer_from_fingerprint(meta.config["tokenizer"])
            else:
                tokenizer = load_tokenizer(model.vocab_size)
            default_len = min(128, getattr(model, "max_positions", 128))
            return TextClassificationEngine(
                model,
                params,
                meta.vocab,
                tokenizer=tokenizer,
                max_len=meta.config.get("max_len", default_len),
                mesh=mesh,
                buckets=buckets,
                meta={"step": meta.step, "config_hash": meta.config_hash},
            )
        return InferenceEngine(
            model,
            params,
            meta.vocab,
            feature_names,
            mesh=mesh,
            buckets=buckets,
            meta={"step": meta.step, "config_hash": meta.config_hash},
        )

    # -- shape management -------------------------------------------------
    def bucket_for(self, n: int) -> int:
        i = bisect.bisect_left(self.buckets, n)
        return self.buckets[min(i, len(self.buckets) - 1)]

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def warmup(self) -> None:
        """Compile every bucket shape before serving traffic."""
        d = self.num_features
        for b in self.buckets:
            x = np.zeros((b, d), self.input_dtype)
            jax.block_until_ready(self._predict_padded(x))
        _log.info("warmed %d bucket shapes up to batch=%d", len(self.buckets),
                  self.max_batch)

    def _predict_padded(self, x: np.ndarray):
        if self.mesh is not None:
            from mlapi_tpu.parallel import shard_batch_for_mesh

            x = shard_batch_for_mesh(x, self.mesh)
        return self._forward(self.params, x)

    # -- public API -------------------------------------------------------
    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Classify ``[n, d]`` rows → (label ids ``[n]``, max-probs
        ``[n]``); pads to bucket, chunks past the largest bucket."""
        x = np.asarray(x, self.input_dtype)
        if x.ndim != 2:
            raise ValueError(f"expected [n, d] features, got shape {x.shape}")
        n = len(x)
        ids_out = np.empty((n,), np.int32)
        probs_out = np.empty((n,), np.float32)
        start = 0
        while start < n:
            chunk = x[start : start + self.max_batch]
            b = self.bucket_for(len(chunk))
            padded = np.zeros((b, x.shape[1]), self.input_dtype)
            padded[: len(chunk)] = chunk
            fused = np.asarray(self._predict_padded(padded))  # one transfer
            ids_out[start : start + len(chunk)] = fused[: len(chunk), 0].astype(
                np.int32
            )
            probs_out[start : start + len(chunk)] = fused[: len(chunk), 1]
            start += len(chunk)
        return ids_out, probs_out

    def predict_labels(self, x: np.ndarray) -> tuple[list[str], np.ndarray]:
        ids, probs = self.predict(x)
        return self.vocab.decode(ids), probs


class TextClassificationEngine(InferenceEngine):
    """Batched text classification: tokenizer + BERT-style model.

    Rows are fixed-length int32 token-id vectors (``max_len``); the
    attention mask is recomputed inside the model (``ids != pad``),
    so the batcher/bucketing machinery is identical to the tabular
    engine — only the row dtype and the request encoding differ.
    """

    kind = "text"
    input_dtype = np.int32

    def __init__(
        self,
        model,
        params,
        vocab: LabelVocab,
        *,
        tokenizer,
        max_len: int = 128,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: jax.sharding.Mesh | None = None,
        meta: dict | None = None,
    ):
        super().__init__(
            model, params, vocab, feature_names=(), buckets=buckets,
            mesh=mesh, meta=meta,
        )
        model_vocab = getattr(model, "vocab_size", None)
        if model_vocab is not None and tokenizer.vocab_size > model_vocab:
            # JAX gather clamps out-of-range ids silently — refuse the
            # pairing instead of mispredicting.
            raise ValueError(
                f"tokenizer emits ids up to {tokenizer.vocab_size - 1} but "
                f"the model's embedding table has {model_vocab} rows"
            )
        self.tokenizer = tokenizer
        self.max_len = int(max_len)
        self.num_features = self.max_len  # row width for warmup/stacking

    def encode(self, text: str) -> np.ndarray:
        """One request's text → a fixed-length id row."""
        ids, _ = self.tokenizer.encode(text, self.max_len)
        return ids


class TextGenerationEngine:
    """Serving engine for generative LMs (``gpt_lm``).

    Unlike the classification engines there is no label vocab and no
    micro-batcher: one request is one ``model.generate`` program
    (prefill + ``lax.scan`` decode), compiled per
    (prompt-bucket, max_new_tokens, temperature) signature and warmed
    for the default shape at startup.
    """

    kind = "generative"

    def __init__(
        self,
        model,
        params,
        *,
        tokenizer,
        mesh: jax.sharding.Mesh | None = None,
        meta: dict | None = None,
        default_max_new_tokens: int = 32,
        prompt_buckets: Sequence[int] = (16, 64, 128),
    ):
        if tokenizer.vocab_size > model.vocab_size:
            raise ValueError(
                f"tokenizer emits ids up to {tokenizer.vocab_size - 1} but "
                f"the model's embedding table has {model.vocab_size} rows"
            )
        self.model = model
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.meta = dict(meta or {})
        self.default_max_new_tokens = default_max_new_tokens
        self.prompt_buckets = tuple(
            b for b in sorted(prompt_buckets) if b < model.max_positions
        ) or (model.max_positions // 2,)
        if mesh is not None:
            from mlapi_tpu.parallel import params_for_model

            params = params_for_model(model, params, mesh)
        else:
            params = jax.device_put(params)
        self.params = params

    # Shared surface with the classification engines (healthz, app).
    @property
    def vocab(self):
        from mlapi_tpu.utils.vocab import LabelVocab

        return LabelVocab(())  # no label space; output is text

    def warmup(self) -> None:
        """Compile the default-shape generate program off the request
        path (each new (bucket, tokens, temperature) signature still
        compiles on first use). Clamped to the model's context window
        so a small-context LM still comes up."""
        bucket = self.prompt_buckets[0]
        n_new = min(
            self.default_max_new_tokens, self.model.max_positions - bucket
        )
        if n_new < 1:
            bucket = max(1, self.model.max_positions // 2)
            n_new = self.model.max_positions - bucket
        ids = np.zeros((1, bucket), np.int32)
        jax.block_until_ready(
            self.model.generate(
                self.params, jnp.asarray(ids), max_new_tokens=n_new
            )
        )
        _log.info(
            "warmed generate: prompt_bucket=%d, max_new_tokens=%d",
            bucket, n_new,
        )

    def _bucket(self, n: int) -> int:
        i = bisect.bisect_left(self.prompt_buckets, n)
        return self.prompt_buckets[min(i, len(self.prompt_buckets) - 1)]

    def generate_text(
        self,
        text: str,
        *,
        max_new_tokens: int | None = None,
        temperature: float = 0.0,
        seed: int = 0,
    ) -> dict:
        """One prompt → generated continuation (text + ids)."""
        n_new = int(max_new_tokens or self.default_max_new_tokens)
        raw = self.tokenizer.token_ids(text)
        limit = self.model.max_positions - n_new
        if limit <= 0:
            raise ValueError(
                f"max_new_tokens={n_new} leaves no room for a prompt "
                f"(max_positions={self.model.max_positions})"
            )
        raw = raw[-limit:] if raw else [self.tokenizer.pad_id]
        # Left-pad to a bucket so common prompt lengths never
        # recompile; the model treats every position causally, and
        # pad-prefix tokens wash out of the final-position logits with
        # trained models. A prompt longer than the largest bucket gets
        # its exact length (one-off compile) rather than silent
        # truncation.
        bucket = min(max(self._bucket(len(raw)), len(raw)), limit)
        prompt = np.full((1, bucket), self.tokenizer.pad_id, np.int32)
        used = min(len(raw), bucket)
        prompt[0, -used:] = raw[-used:]

        out = self.model.generate(
            self.params,
            jnp.asarray(prompt),
            max_new_tokens=n_new,
            temperature=float(temperature),
            rng=jax.random.key(seed),
        )
        out_ids = [int(i) for i in np.asarray(out)[0]]
        return {
            "text": self.tokenizer.decode(out_ids),
            "token_ids": out_ids,
            "prompt_tokens": used,  # tokens that actually conditioned
        }


def _load_meta_only(path):
    """Read just the manifest (no params I/O)."""
    import json
    from pathlib import Path

    from mlapi_tpu.checkpoint.io import CheckpointMeta, _MANIFEST

    manifest = Path(path) / _MANIFEST
    if not manifest.exists():
        raise FileNotFoundError(f"{path} is not a committed checkpoint")
    return CheckpointMeta.from_json(json.loads(manifest.read_text()))
