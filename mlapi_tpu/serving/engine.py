"""Inference engine: params resident on device, one warmed jit forward.

Inverts the reference's hot-path design, which re-loads the pickled
model from disk **on every request** (``main.py:19``) and then runs
the matmul twice (``predict`` then ``predict_proba``,
``main.py:21-22``). Here:

- The checkpoint is loaded **once** at startup (onto the mesh if one
  is given).
- The forward pass is jit-compiled once per batch-bucket size at
  warmup, so no request ever pays XLA compilation.
- Prediction *and* probability come out of a single device call:
  ``argmax`` + ``max(softmax)`` over one set of logits, with only two
  scalars per row transferred back to the host.
- Requests are padded to a small set of bucket sizes so arbitrary
  batch sizes never trigger recompilation (static shapes — XLA
  requirement, SURVEY §7 step 4).

The GENERATIVE engine's subsystems live in sibling modules with the
engine as their hub (r04 split): request/prefix-entry types in
``requests.py``, the shared-prefix KV cache in ``prefix.py``, the
host speculation phase in ``spec_phase.py``, the batch-1 fused fast
path in ``fused_single.py``, and the chained-dispatch drain machinery
in ``dispatch.py``. ``_run_batch`` here remains the batch LIFECYCLE —
formation, continuous admission, growth/compaction, handoffs — the
one place the pieces compose.
"""

from __future__ import annotations

import asyncio
import bisect
import functools
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mlapi_tpu.serving import faults
from mlapi_tpu.serving.fused_single import FusedSinglePath
from mlapi_tpu.serving.prefix import PrefixCache

# Request-side data types live in serving/requests.py; re-exported
# because the engine API and the test suite name them from this module.
from mlapi_tpu.serving.requests import (
    DeadlineExceeded,
    DrainCancelled,
    GenRequest,
    _PrefixEntry,
    _SyncSink,
)
from mlapi_tpu.serving.spec_phase import SpecPhase

from mlapi_tpu.utils.logging import get_logger
from mlapi_tpu.utils.vocab import LabelVocab

_log = get_logger("serving.engine")

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)


class InferenceEngine:
    """Batched classification inference over a jitted forward pass.

    Rows are float32 feature vectors; see
    :class:`TextClassificationEngine` for the token-id variant.
    """

    kind = "tabular"
    input_dtype = np.float32

    def __init__(
        self,
        model,
        params,
        vocab: LabelVocab,
        feature_names: Sequence[str],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: jax.sharding.Mesh | None = None,
        meta: dict | None = None,
    ):
        self.model = model
        self.vocab = vocab
        self.feature_names = tuple(feature_names)
        self.num_features = int(
            getattr(model, "num_features", 0) or len(self.feature_names) or 1
        )
        self.buckets = tuple(sorted(buckets))
        self.mesh = mesh
        self.meta = dict(meta or {})
        if mesh is not None:
            from mlapi_tpu.parallel import batch_shard_size, params_for_model

            # Batches shard over data AND (when present) fsdp — the
            # divisibility unit is their product.
            axis = batch_shard_size(mesh)
            bad = [b for b in self.buckets if b % axis]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by batch-sharding "
                    f"axes of total size {axis}"
                )
            # Serve in the model's declared layout (e.g. Wide&Deep's
            # vocab-sharded tables) — the reason to serve on a mesh at
            # all is that the params don't fit (or shouldn't be
            # copied) per chip. A 3-axis mesh additionally
            # ZeRO-shards every large leaf over ``fsdp``
            # (params_for_model): weights all-gather per use, so a
            # model too big per chip serves from sharded storage.
            params = params_for_model(model, params, mesh)
        else:
            params = jax.device_put(params)
        self.params = params

        def forward(p, x):
            logits = self.model.apply(p, x)
            probs = jax.nn.softmax(logits, axis=-1)
            # ONE fused [B, 2] output (id, max-prob) — a single
            # device→host transfer. Two separate outputs would cost two
            # round trips, which doubles latency when the chip is
            # reached over a network tunnel (measured: 65 ms per
            # readback on the dev tunnel).
            return jnp.stack(
                [jnp.argmax(logits, axis=-1).astype(jnp.float32),
                 jnp.max(probs, axis=-1)],
                axis=-1,
            )

        self._forward = jax.jit(forward)

    @classmethod
    def from_checkpoint(
        cls,
        path,
        model=None,
        *,
        mesh: jax.sharding.Mesh | None = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        quantize: str | None = None,
        kv_quant: str | None = None,
        decode_attn_impl: str | None = None,
        kv_page_size: int | None = None,
        kv_pages: int | None = None,
        prefill_page_native: bool = True,
        prefill_interleave: bool = True,
        kv_tier_bytes: int = 0,
        kv_tier_disk_dir: str | None = None,
        kv_peer_fetch: bool = False,
        replica_role: str = "mixed",
        draft_checkpoint=None,
        spec_sample: bool = False,
        sched_max_batches: int = 2,
        adapter_slots: int = 0,
        adapter_store_bytes: int = 0,
        adapter_disk_dir: str | None = None,
    ) -> "InferenceEngine":
        """Build an engine from a committed checkpoint dir.

        The model is reconstructed from the checkpoint's own config
        (``model`` registry name + kwargs) unless one is passed in,
        and the engine class follows the model's ``input_kind``
        (tabular feature rows vs text token ids).

        ``quantize="int8"`` converts the loaded float weights to
        weight-only per-channel int8 at load time and serves through
        the transparent :class:`~mlapi_tpu.models.quantized.QuantizedModel`
        wrapper — half the parameter HBM, dequantization fused into
        each matmul inside the jitted programs. Composes with
        ``mesh``: the ``q`` leaves take the inner model's TP layout,
        per-channel scales ride the channel axis
        (``parallel.mesh.place_params``).

        ``kv_quant="int8"`` stores every decode KV cache as int8
        payload + per-token-per-head f32 scales (``ops/quant.py``):
        ~2x less decode HBM per cached token and ~2x the
        cache/prefix/slot budget at equal hardware. The format is a
        MODEL field, so every jitted program (prefill, decode chunks,
        fused generation, admission scatter, prefix widen, spec
        mirrors) keys on it and stays format-consistent — including
        the draft, which decodes against its own int8 cache.
        Orthogonal to ``quantize`` (weights) and ``mesh``; generative
        checkpoints only.

        ``decode_attn_impl="flash"`` routes every single-token decode
        step through the Pallas split-K flash-decode kernel
        (``ops/pallas/decode_attention``) instead of the reference
        einsum — with an int8 cache the kernel reads int8 tiles from
        HBM and dequantizes in registers, so the format's 2x byte
        saving reaches the decode READ, not just storage. A model
        field like ``kv_quant`` (program factories key on it; the
        draft mirrors it); generative checkpoints only.

        ``kv_page_size=N`` switches serving KV allocation from
        contiguous per-slot tier buffers to the block-granular paged
        pool (``kv_pages`` sizes it; defaults to the
        contiguous-equivalent budget): sequences hold only the pages
        covering their actual length, shared prefixes become
        ref-counted shared pages with copy-on-write divergence, and
        batch growth/compaction become page-table bookkeeping instead
        of cache gathers. Token streams are pinned identical to the
        contiguous layout across both ``kv_quant`` formats and both
        decode impls (DESIGN §15). Generative checkpoints only.

        ``kv_tier_bytes=N`` enables the hierarchical host-RAM KV tier
        (``serving/kv_tier.py``): evicted prefix KV page sets spill to
        host memory (optionally ``kv_tier_disk_dir``-backed files) in
        their stored format instead of being discarded, and re-arrivals
        restore by ``device_put`` with zero prefill FLOPs — greedy
        streams are pinned token-identical across {evict → restore} vs
        {never evicted} (DESIGN §19). 0 (default) keeps the r12
        discard behavior bit for bit. Generative checkpoints only.

        ``kv_peer_fetch=True`` lets router replicas exchange prefix-KV
        blobs peer to peer (``serving/kv_peer.py``): a replica that
        misses a prefix locally fetches the stored-format blob from
        the router-hinted warm peer and restores it instead of
        cold-prefilling, and serves its own warm blobs on ``GET
        /kv/prefix`` (DESIGN §23). Off (default): bit-identical to
        the flag never existing. Generative checkpoints only.
        """
        import dataclasses

        from mlapi_tpu.checkpoint import load_checkpoint
        from mlapi_tpu.models import get_model

        if model is None:
            # Peek the manifest for the model config, then restore with
            # signature validation against the freshly-built model.
            meta = _load_meta_only(path)
            model = get_model(
                meta.config["model"], **meta.config.get("model_kwargs", {})
            )
            feature_names = meta.config.get("feature_names", ())
        else:
            feature_names = ()

        # eval_shape: abstract tree only — a full random init of a
        # large model would allocate (and page) every parameter just
        # to read shapes.
        abstract = jax.eval_shape(lambda: model.init(jax.random.key(0)))
        params, meta = load_checkpoint(path, abstract)

        if kv_quant is not None:
            if kv_quant != "int8":
                raise ValueError(f"unsupported kv_quant={kv_quant!r}")
            if not hasattr(model, "generate"):
                raise ValueError(
                    "kv_quant applies to generative checkpoints (they "
                    f"hold KV caches); {type(model).__name__} has none"
                )
            try:
                # The format is a model FIELD (not engine state) so
                # every lru_cache'd program factory keys on it.
                model = dataclasses.replace(model, kv_quant="int8")
            except TypeError:
                raise ValueError(
                    f"{type(model).__name__} declares no kv_quant "
                    "cache-format field"
                ) from None

        if decode_attn_impl is not None:
            if decode_attn_impl not in ("einsum", "flash"):
                raise ValueError(
                    f"unsupported decode_attn_impl={decode_attn_impl!r}"
                )
            if not hasattr(model, "generate"):
                raise ValueError(
                    "decode_attn_impl applies to generative checkpoints "
                    f"(they decode); {type(model).__name__} does not"
                )
            try:
                # Same discipline as kv_quant: a MODEL field, so every
                # cached program factory keys on the decode impl.
                model = dataclasses.replace(
                    model, decode_attn_impl=decode_attn_impl
                )
            except TypeError:
                raise ValueError(
                    f"{type(model).__name__} declares no "
                    "decode_attn_impl field"
                ) from None

        # Engine dispatch keys off the INNER model: the quantized
        # wrapper defines the full decoder protocol, so probing the
        # wrapper would route every quantized checkpoint — tabular
        # classifiers included — to the generative engine.
        inner = model
        if quantize is not None:
            if quantize != "int8":
                raise ValueError(f"unsupported quantize={quantize!r}")
            from mlapi_tpu.models.quantized import QuantizedModel
            from mlapi_tpu.ops.quant import quantize_tree, quantized_bytes

            params = quantize_tree(params)
            stored, full = quantized_bytes(params)
            _log.info(
                "weight-only int8: params %.1f MB (f32 would be %.1f MB)",
                stored / 1e6, full / 1e6,
            )
            model = QuantizedModel(model)

        if hasattr(inner, "generate"):
            # Generative LM: no label vocab — the output space is the
            # tokenizer's.
            from mlapi_tpu.text import load_tokenizer
            from mlapi_tpu.text.tokenizer import tokenizer_from_fingerprint

            tokenizer = (
                tokenizer_from_fingerprint(meta.config["tokenizer"])
                if "tokenizer" in meta.config
                else load_tokenizer(model.vocab_size)
            )
            draft = None
            if draft_checkpoint is not None:
                dmeta = _load_meta_only(draft_checkpoint)
                if dmeta.config.get("tokenizer") != meta.config.get(
                    "tokenizer"
                ):
                    raise ValueError(
                        "draft checkpoint was trained with a different "
                        "tokenizer than the target"
                    )
                dmodel = get_model(
                    dmeta.config["model"],
                    **dmeta.config.get("model_kwargs", {}),
                )
                if kv_quant is not None:
                    # The draft's spec-phase cache mirrors ride the
                    # same format as the target's — format-consistent
                    # by construction.
                    dmodel = dataclasses.replace(
                        dmodel, kv_quant="int8"
                    )
                if decode_attn_impl is not None:
                    # The draft decodes too — same impl as the target.
                    dmodel = dataclasses.replace(
                        dmodel, decode_attn_impl=decode_attn_impl
                    )
                dabstract = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    jax.eval_shape(
                        lambda: dmodel.init(jax.random.key(0))
                    ),
                )
                dparams, _ = load_checkpoint(draft_checkpoint, dabstract)
                draft = (dmodel, dparams)
            return TextGenerationEngine(
                model,
                params,
                tokenizer=tokenizer,
                mesh=mesh,
                draft=draft,
                spec_sample=spec_sample,
                kv_page_size=kv_page_size,
                kv_pages=kv_pages,
                prefill_page_native=prefill_page_native,
                prefill_interleave=prefill_interleave,
                kv_tier_bytes=kv_tier_bytes,
                kv_tier_disk_dir=kv_tier_disk_dir,
                kv_peer_fetch=kv_peer_fetch,
                replica_role=replica_role,
                sched_max_batches=sched_max_batches,
                adapter_slots=adapter_slots,
                adapter_store_bytes=adapter_store_bytes,
                adapter_disk_dir=adapter_disk_dir,
                meta={"step": meta.step, "config_hash": meta.config_hash,
                      **({"quantized": quantize} if quantize else {}),
                      **({"kv_quant": kv_quant} if kv_quant else {}),
                      **({"decode_attn_impl": decode_attn_impl}
                         if decode_attn_impl else {}),
                      **({"kv_page_size": kv_page_size}
                         if kv_page_size else {}),
                      **({"kv_tier_bytes": kv_tier_bytes}
                         if kv_tier_bytes else {}),
                      **({"kv_peer_fetch": True}
                         if kv_peer_fetch else {}),
                      **({"replica_role": replica_role}
                         if replica_role != "mixed" else {}),
                      **({"adapter_slots": adapter_slots}
                         if adapter_slots else {}),
                      **({"sched_max_batches": sched_max_batches}
                         if sched_max_batches == 1 else {}),
                      **({"draft": str(draft_checkpoint)}
                         if draft_checkpoint else {})},
            )

        if kv_page_size is not None or kv_pages is not None:
            raise ValueError(
                "kv_page_size/kv_pages apply to generative checkpoints "
                f"(they hold KV caches); {type(inner).__name__} has none"
            )
        if kv_tier_bytes or kv_tier_disk_dir:
            raise ValueError(
                "kv_tier_bytes/kv_tier_disk_dir apply to generative "
                f"checkpoints (they cache prefix KV); "
                f"{type(inner).__name__} has none"
            )
        if kv_peer_fetch:
            raise ValueError(
                "kv_peer_fetch applies to generative checkpoints "
                f"(they cache prefix KV); {type(inner).__name__} has "
                f"none"
            )
        if replica_role != "mixed":
            raise ValueError(
                "replica_role applies to generative checkpoints "
                f"(they split prefill from decode); "
                f"{type(inner).__name__} has neither"
            )
        if adapter_slots or adapter_store_bytes or adapter_disk_dir:
            raise ValueError(
                "adapter_slots/adapter_store_bytes/adapter_disk_dir "
                "apply to generative checkpoints (they serve per-"
                f"tenant LoRA adapters); {type(inner).__name__} does "
                f"not"
            )
        # ``sched_max_batches`` is a generative-only knob (it shapes
        # the decode unit queue; ``--no-scheduler`` was retired in
        # r22 — ``sched_max_batches=1`` IS serial mode) —
        # classification checkpoints simply ignore it rather than
        # forcing every caller to special-case the default.
        if meta.vocab is None:
            raise ValueError(f"checkpoint {path} has no label vocab; cannot serve")
        feature_names = meta.config.get("feature_names", feature_names)

        if getattr(inner, "input_kind", "tabular") == "text":
            from mlapi_tpu.text import load_tokenizer
            from mlapi_tpu.text.tokenizer import tokenizer_from_fingerprint

            if "tokenizer" in meta.config:
                # Rebuild exactly the training tokenizer or refuse —
                # serving must never silently substitute a different
                # tokenization scheme.
                tokenizer = tokenizer_from_fingerprint(meta.config["tokenizer"])
            else:
                tokenizer = load_tokenizer(model.vocab_size)
            default_len = min(128, getattr(model, "max_positions", 128))
            return TextClassificationEngine(
                model,
                params,
                meta.vocab,
                tokenizer=tokenizer,
                max_len=meta.config.get("max_len", default_len),
                mesh=mesh,
                buckets=buckets,
                meta={"step": meta.step, "config_hash": meta.config_hash,
                      **({"quantized": quantize} if quantize else {})},
            )
        return InferenceEngine(
            model,
            params,
            meta.vocab,
            feature_names,
            mesh=mesh,
            buckets=buckets,
            meta={"step": meta.step, "config_hash": meta.config_hash,
                      **({"quantized": quantize} if quantize else {})},
        )

    # -- shape management -------------------------------------------------
    def bucket_for(self, n: int) -> int:
        i = bisect.bisect_left(self.buckets, n)
        return self.buckets[min(i, len(self.buckets) - 1)]

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def warmup(self) -> None:
        """Compile every bucket shape before serving traffic."""
        d = self.num_features
        for b in self.buckets:
            x = np.zeros((b, d), self.input_dtype)
            jax.block_until_ready(self._predict_padded(x))
        _log.info("warmed %d bucket shapes up to batch=%d", len(self.buckets),
                  self.max_batch)

    def _predict_padded(self, x: np.ndarray):
        if self.mesh is not None:
            from mlapi_tpu.parallel import shard_batch_for_mesh

            x = shard_batch_for_mesh(x, self.mesh)
        return self._forward(self.params, x)

    # -- public API -------------------------------------------------------
    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Classify ``[n, d]`` rows → (label ids ``[n]``, max-probs
        ``[n]``); pads to bucket, chunks past the largest bucket."""
        x = np.asarray(x, self.input_dtype)
        if x.ndim != 2:
            raise ValueError(f"expected [n, d] features, got shape {x.shape}")
        n = len(x)
        ids_out = np.empty((n,), np.int32)
        probs_out = np.empty((n,), np.float32)
        start = 0
        while start < n:
            chunk = x[start : start + self.max_batch]
            b = self.bucket_for(len(chunk))
            padded = np.zeros((b, x.shape[1]), self.input_dtype)
            padded[: len(chunk)] = chunk
            fused = np.asarray(self._predict_padded(padded))  # one transfer
            ids_out[start : start + len(chunk)] = fused[: len(chunk), 0].astype(
                np.int32
            )
            probs_out[start : start + len(chunk)] = fused[: len(chunk), 1]
            start += len(chunk)
        return ids_out, probs_out

    def predict_labels(self, x: np.ndarray) -> tuple[list[str], np.ndarray]:
        ids, probs = self.predict(x)
        return self.vocab.decode(ids), probs


class TextClassificationEngine(InferenceEngine):
    """Batched text classification: tokenizer + BERT-style model.

    Rows are fixed-length int32 token-id vectors (``max_len``); the
    attention mask is recomputed inside the model (``ids != pad``),
    so the batcher/bucketing machinery is identical to the tabular
    engine — only the row dtype and the request encoding differ.
    """

    kind = "text"
    input_dtype = np.int32

    def __init__(
        self,
        model,
        params,
        vocab: LabelVocab,
        *,
        tokenizer,
        max_len: int = 128,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: jax.sharding.Mesh | None = None,
        meta: dict | None = None,
    ):
        super().__init__(
            model, params, vocab, feature_names=(), buckets=buckets,
            mesh=mesh, meta=meta,
        )
        model_vocab = getattr(model, "vocab_size", None)
        if model_vocab is not None and tokenizer.vocab_size > model_vocab:
            # JAX gather clamps out-of-range ids silently — refuse the
            # pairing instead of mispredicting.
            raise ValueError(
                f"tokenizer emits ids up to {tokenizer.vocab_size - 1} but "
                f"the model's embedding table has {model_vocab} rows"
            )
        self.tokenizer = tokenizer
        self.max_len = int(max_len)
        self.num_features = self.max_len  # row width for warmup/stacking

    def encode(self, text: str) -> np.ndarray:
        """One request's text → a fixed-length id row."""
        ids, _ = self.tokenizer.encode(text, self.max_len)
        return ids



@functools.cache
def _dispatch_rtt_ms(samples: int = 3) -> float:
    """Best-of-N device dispatch+readback round trip, in ms. The first
    call compiles a trivial program (excluded by taking the min of the
    post-warm samples)."""
    import time

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    float(f(x))  # compile + warm
    best = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        float(f(x))
        best = min(best, (time.perf_counter() - t0) * 1e3)
    return best


@functools.cache
def _compact_fn():
    """Jitted leading-dim gather over the KV cache: select a new set
    of device rows (still-active rows for compaction, old rows plus
    dummy repeats for batch growth). The gather changes the leading
    dim, so it cannot alias the old buffers — peak HBM during a
    resize is briefly old + new cache (then the old one frees).
    Compiled once per (from, to, cache-tier) shape; the batch resizes
    along the power-of-two chain only, which the warmup grid covers.
    Per-row request vectors (temps/keys/pads/steps) live on the host
    and are re-uploaded with each chunk dispatch — only the cache is
    device-resident state."""

    def _run(cache, sel):
        return jax.tree.map(lambda a: a[sel], cache)

    return jax.jit(_run)


class TextGenerationEngine:
    """Serving engine for generative LMs (``gpt_lm``).

    Decoding is *incremental and batched*: prompts are left-padded to
    a bucket (pads masked, positions shifted — output is
    bucket-invariant, see ``GptLM.decode_step``) and decoded in
    ``chunk``-token jitted scans against a donated KV cache. Two
    consequences the one-shot design lacked:

    - **Batching**: up to ``max_batch`` concurrent ``/generate``
      requests share one prefill + one decode stream — N requests cost
      ~1 request's device time (the classification batcher's win,
      brought to generation). Per-row temperature/PRNG-stream means
      mixed greedy/sampled requests batch together.
    - **Streaming**: each decoded chunk is pushed to the requester as
      it lands, so time-to-first-token is one prefill + one chunk, not
      the whole generation.

    Compile count is bounded by shape buckets only: programs are keyed
    on (batch, prompt bucket, cache length), never on
    ``max_new_tokens``/temperature/seed (request parameters are traced
    or sliced on the host).
    """

    kind = "generative"

    def __init__(
        self,
        model,
        params,
        *,
        tokenizer,
        mesh: jax.sharding.Mesh | None = None,
        meta: dict | None = None,
        default_max_new_tokens: int = 32,
        prompt_buckets: Sequence[int] = (16, 64, 128),
        max_batch: int = 8,
        chunk: int | None = None,
        max_wait_ms: float = 2.0,
        max_queue: int = 256,
        draft: tuple | None = None,
        spec_k: int = 4,
        spec_sample: bool = False,
        fused_single: bool = True,
        fused_max_new: int | None = None,
        kv_page_size: int | None = None,
        kv_pages: int | None = None,
        prefill_page_native: bool = True,
        prefill_interleave: bool = True,
        kv_tier_bytes: int = 0,
        kv_tier_disk_dir: str | None = None,
        kv_peer_fetch: bool = False,
        kv_peer_timeout_s: float = 5.0,
        replica_role: str = "mixed",
        sched_max_batches: int = 2,
        adapter_slots: int = 0,
        adapter_store_bytes: int = 0,
        adapter_disk_dir: str | None = None,
    ):
        if tokenizer.vocab_size > model.vocab_size:
            raise ValueError(
                f"tokenizer emits ids up to {tokenizer.vocab_size - 1} but "
                f"the model's embedding table has {model.vocab_size} rows"
            )
        # Speculative decoding: (draft_model, draft_params). Used only
        # while the live batch is a single greedy row — the
        # single-stream latency lever; batched throughput stays
        # continuous batching's job.
        if draft is not None:
            d_model, d_params = draft
            if d_model.vocab_size != model.vocab_size:
                raise ValueError(
                    "draft and target models must share a vocabulary"
                )
            if d_model.max_positions < model.max_positions:
                raise ValueError(
                    f"draft window ({d_model.max_positions}) must cover "
                    f"the target's ({model.max_positions})"
                )
            self.draft_model = d_model
            if mesh is not None:
                # The draft rides the same mesh as the target (its own
                # declared TP layout): fused/host spec programs take
                # BOTH param trees, and mixing a sharded target with a
                # single-device draft would force GSPMD to reshard the
                # draft on every dispatch.
                from mlapi_tpu.parallel import params_for_model

                self.draft_params = params_for_model(
                    d_model, d_params, mesh
                )
            else:
                self.draft_params = jax.device_put(d_params)
        else:
            self.draft_model = None
            self.draft_params = None
        self.spec_k = max(1, int(spec_k))
        # Opt-in: run SAMPLED (temperature > 0) single-row requests
        # through acceptance-rejection speculation (Leviathan/Chen —
        # ops/speculative.speculative_sample's scheme). The emitted
        # stream keeps the exact target sampling distribution and a
        # solo run is deterministic per seed, but a stream interleaved
        # with admission churn is NOT byte-reproducible across runs
        # (re-engagement shifts the draft's stream offsets) — hence a
        # deployment flag (--spec-sample), not a default.
        self.spec_sample = bool(spec_sample)
        # Fused-chunk widths (r20, serving/fused_single.py): a batch
        # of non-streaming rows decodes in TIER-WIDE chunks through
        # the same decode-chunk program family — the r03 dispatch
        # saving (through a high-RTT attach every dispatch costs ~one
        # round trip, so fewer, wider chunks are the single-stream
        # RTT lever), but at unit granularity: each fused chunk is
        # one schedulable unit, so deadlines, speculation, brownout,
        # faults, and drain apply between fused chunks and a
        # concurrent lane stalls at most one fused-chunk dispatch
        # (sched_lane_stall_max). The r03-r05 whole-generation fused
        # programs (one uninterruptible dispatch per generation, with
        # per-path deadline/disagg decline gates) are retired —
        # BENCH_r16.json holds the measurement. ``fused_max_new``
        # caps the WIDTH ladder, bounding the largest single
        # dispatch; fused_single=False pins the plain ``chunk``.
        self.fused_single = bool(fused_single)
        self.fused_max_new = int(
            fused_max_new
            if fused_max_new is not None
            else max(64, default_max_new_tokens)
        )
        if mesh is not None and getattr(
            model, "decode_attn_impl", "einsum"
        ) == "flash" and "model" in getattr(
            mesh, "axis_names", ()
        ) and mesh.shape["model"] > 1:
            # Model-axis TP + flash decode: pin the mesh ON the model
            # so ``cached_attend`` wraps the opaque ``pallas_call`` in
            # an explicit ``shard_map`` over the head axis — GSPMD
            # cannot see into the kernel and might otherwise
            # all-gather the head-sharded cache operands around it
            # (ROADMAP open item). The field already exists (ring
            # attention uses it); program factories key on it for
            # free. The draft mirrors the move below.
            import dataclasses

            try:
                model = dataclasses.replace(model, mesh=mesh)
            except TypeError:
                pass  # wrapped/legacy models: GSPMD decides, as before
            if self.draft_model is not None:
                try:
                    self.draft_model = dataclasses.replace(
                        self.draft_model, mesh=mesh
                    )
                except TypeError:
                    pass
        self.model = model
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.meta = dict(meta or {})
        self.default_max_new_tokens = default_max_new_tokens
        self.prompt_buckets = tuple(
            b for b in sorted(prompt_buckets) if b < model.max_positions
        ) or (model.max_positions // 2,)
        self.max_batch = int(max_batch)
        self.max_wait_s = max_wait_ms / 1e3
        self.max_queue = int(max_queue)
        if mesh is not None:
            from mlapi_tpu.parallel import params_for_model

            params = params_for_model(model, params, mesh)
        else:
            params = jax.device_put(params)
        self.params = params
        if chunk is None:
            # Streaming latency is chunk-count x dispatch round trip,
            # so the right chunk depends on where the chip is: ~0.1 ms
            # away (local attach) favours small chunks (fine-grained
            # streaming + compaction); ~70 ms away (network tunnel)
            # favours fewer, larger chunks — a 32-token request drops
            # from 5 device round trips to 3. Measure, don't assume.
            rtt_ms = _dispatch_rtt_ms()
            chunk = 16 if rtt_ms > 15.0 else 8
            _log.info(
                "auto decode chunk=%d (device dispatch rtt %.1f ms)",
                chunk, rtt_ms,
            )
        self.chunk = max(1, int(chunk))
        # Paged KV cache: a device-resident pool of fixed-size pages +
        # per-row page tables replaces per-slot contiguous tier
        # buffers (serving/paged_pool.py; DESIGN §15). Opt-in via
        # kv_page_size; kv_pages defaults to the contiguous-equivalent
        # budget (every slot at the default tier) so flipping paging
        # on never costs MORE HBM — the win is that short/ragged
        # sequences stop paying their padded tier and shared prefixes
        # stop being copied per row.
        if kv_pages is not None and kv_page_size is None:
            raise ValueError("kv_pages requires kv_page_size")
        self.pool = None
        if kv_page_size is not None:
            from mlapi_tpu.serving.paged_pool import PagePool

            max_total = self._cache_len(
                self.prompt_buckets[-1], self.default_max_new_tokens
            )
            if kv_pages is None:
                kv_pages = (
                    self.max_batch * -(-max_total // int(kv_page_size))
                    + 1  # the reserved null page
                )
            self.pool = PagePool(
                model, page_size=int(kv_page_size),
                num_pages=int(kv_pages),
            )
        # Hierarchical KV tier (r13, serving/kv_tier.py): a host-RAM
        # (optionally disk-backed) LRU store of evicted prefix page
        # sets, multiplying the effective prefix budget by the
        # host-RAM/HBM ratio. 0 = off (the default): evictions discard
        # exactly as before — streams and counters bit-identical to
        # r12. Attached to the pool (spill seam) and consulted by the
        # PrefixCache (restore seams).
        self.kv_tier = None
        if kv_tier_disk_dir and not kv_tier_bytes:
            raise ValueError(
                "kv_tier_disk_dir requires kv_tier_bytes > 0 (the "
                "bytes budget enables the tier; a silently-ignored "
                "disk dir would store nothing)"
            )
        if kv_tier_bytes:
            from mlapi_tpu.serving.kv_tier import KVTier

            self.kv_tier = KVTier(
                int(kv_tier_bytes), disk_dir=kv_tier_disk_dir
            )
            if self.pool is not None:
                self.pool.tier = self.kv_tier
        # Peer-to-peer prefix-KV fetch (r17, serving/kv_peer.py): on
        # a device-cache AND local-tier miss, fetch the prefix blob
        # from the router-hinted warm peer (x-mlapi-warm-peer)
        # instead of cold-prefilling, and serve this replica's own
        # warm blobs on GET /kv/prefix. Off (the default): no
        # endpoint, no hint map, no fetch — streams and counters
        # bit-identical to r16.
        self.kv_peer = None
        if kv_peer_fetch:
            from mlapi_tpu.serving.kv_peer import KVPeer

            self.kv_peer = KVPeer(self, timeout_s=kv_peer_timeout_s)
        # Prefill/decode disaggregation (r18, serving/kv_peer.py):
        # role-split replicas. A "prefill" replica serves
        # disaggregated requests as prefill-only runs, pushing each
        # finished chunk's KV to the decode replica the router named;
        # a "decode" replica exposes POST /kv/push, stages the chunks,
        # and its formation installs the assembled blob into a
        # private table row — zero decode-side prefill FLOPs. "mixed"
        # (the default): no push state, no endpoint, no role headers
        # read — bit-identical to r17. The role is a ROUTING
        # specialization, not a capability fence: either role still
        # serves a plain /generate end to end (the router's
        # role-starved fallback ladder depends on that).
        if replica_role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"replica_role must be prefill|decode|mixed, got "
                f"{replica_role!r}"
            )
        self.replica_role = replica_role
        self.kv_push = None
        if replica_role != "mixed":
            from mlapi_tpu.serving.kv_peer import KVPush

            self.kv_push = KVPush(self)
        # Many-adapter LoRA serving (serving/adapter_store.py): ONE
        # HBM-resident base amortized across per-tenant adapters.
        # adapter_slots > 0 allocates a device slot pool (per-target
        # stacked (A, B) pools, slot 0 pinned all-zero for base rows)
        # plus a host-RAM (optionally disk-backed) LRU store the slots
        # install from, plus the fleet fetch tier (GET /adapter/<id>
        # from the router-hinted warm peer — the kv_peer wire idiom).
        # 0 = off (the default): no pools, no store, no endpoint —
        # requests naming an adapter are rejected loudly and every
        # base-model program traces byte-identical to before.
        self.adapters = None
        self.adapter_store = None
        self.adapter_peer = None
        if (adapter_store_bytes or adapter_disk_dir) and not adapter_slots:
            raise ValueError(
                "adapter_store_bytes/adapter_disk_dir require "
                "adapter_slots > 0 (the slot pool enables adapter "
                "serving; a silently-ignored store budget would "
                "serve nothing)"
            )
        if adapter_slots:
            from mlapi_tpu.serving.adapter_store import (
                AdapterPeer, AdapterSlots, AdapterStore,
            )

            self.adapters = AdapterSlots(self, int(adapter_slots))
            # Host tier defaults to 256 MiB — hundreds of rank-8/16
            # adapters for the model sizes this repo serves; the flag
            # overrides for bigger fleets.
            self.adapter_store = AdapterStore(
                int(adapter_store_bytes) or (1 << 28),
                disk_dir=adapter_disk_dir,
            )
            self.adapter_peer = AdapterPeer(self)
        # Page-native prefill (r10): bucket prefill and admission write
        # K/V straight into pool pages through the page table — the
        # contiguous-then-adopt copy (one full extra write of
        # everything prefill just produced) drops to exactly zero
        # bytes. False keeps the r09 adopt path (legacy), which is
        # what makes the `generate.prefill_adopt_bytes` gauge a live
        # comparison, not a dead assertion. Contiguous engines ignore
        # both flags.
        self.prefill_page_native = bool(prefill_page_native)
        # Chunked-prefill interleaving (r10): a long-prompt joiner's
        # fixed-width prefill chunks become schedulable units
        # interleaved one-for-one with the running batch's decode
        # chunks, so in-flight streams stall by at most ONE
        # prefill-chunk dispatch instead of the whole prompt
        # (paged engines only — activation is a page-table install).
        self.prefill_interleave = bool(prefill_interleave)
        # KV-cache storage format and decode-attention impl, owned by
        # the MODEL (program factories key on them); mirrored here for
        # /metrics and bench.
        self.kv_quant = getattr(model, "kv_quant", "none")
        self.decode_attn_impl = getattr(model, "decode_attn_impl", "einsum")
        self._kv_slot_bytes: int | None = None
        self._decode_step_bytes: int | None = None
        # Batcher state (started by the app's startup hook).
        self._queue: asyncio.Queue | None = None
        self._task: asyncio.Task | None = None
        # Cross-thread collector wake: set from the scheduler's
        # dispatch thread (lane retired, request deferred) via
        # ``_wake_collector`` so staged work re-enters dispatch
        # without waiting out the poll interval.
        self._kick: asyncio.Event | None = None
        self._aloop: asyncio.AbstractEventLoop | None = None
        # Continuous-batching handoff: requests the collector has
        # popped while a batch is RUNNING, waiting to be admitted at a
        # chunk boundary (decode thread) or swept into the next batch
        # (collector, after the running one ends).
        import threading

        self._admit: list = []
        # Staged requests the RUNNING batch can never take (token
        # budget exceeds its remaining cache): handed back here for
        # the collector's next batch, so they don't camp in _admit
        # blocking compaction and queue draining.
        self._deferred: list = []
        self._alock = threading.Lock()
        # Admission is gated to warmed shapes once a full warmup ran,
        # so a joiner can never stall the running batch on an XLA
        # compile; before/without full warmup (tests, CPU), admission
        # is unrestricted. The expensive compile (joiner prefill) is
        # keyed on the prompt bucket alone; scatter/growth gathers are
        # trivial and may compile on demand when dispatch RTT is low.
        self._strict_admit = False
        self._warmed_joiner: set = set()
        self._warmed_scatter: set = set()
        self._warmed_growth: set = set()
        self._admit_eager_override: bool | None = None
        # Shared-prefix KV caching: ALL prefix state (entry LRU, build
        # events, widened-KV cache, hit/miss counters) lives in the
        # PrefixCache module; the engine only routes calls to it.
        self.prefix = PrefixCache(self)
        # Stats (read by /metrics and the coalescing test).
        self.requests = 0
        self.batch_calls = 0
        self.chunk_calls = 0
        self.rejected = 0
        self.cancelled_batches = 0
        self.compactions = 0
        self.admitted = 0
        self.growths = 0
        self.prefill_chunks = 0
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.fused_calls = 0
        # Page-native prefill + interleaving observability (r10). All
        # byte counters are exact dtype/shape arithmetic
        # (ops/quant.kv_tree_bytes), never wall-clock:
        # - prefill_adopt_bytes: bytes the legacy contiguous-then-
        #   adopt formation/admission path re-copied into pool pages
        #   (MUST read 0 on the page-native path).
        # - prefix_adopt_bytes: once-per-entry-lifetime prefix KV
        #   adoption (cache residency, not a per-batch copy).
        # - kv_prefix_copy_fallback: stacked (cross-prefix) groups
        #   that could NOT share pages because a region shift was not
        #   page-aligned (fell back to r09 copy semantics).
        # - interleaved_prefills / interleave_max_stall /
        #   prefill_chunk_queue_depth: chunked-prefill interleaving —
        #   max_stall is the largest run of consecutive prefill-chunk
        #   dispatches while live decode rows waited (the bound the
        #   design pins at 1).
        # - spec_realign_table_ops / spec_realign_repacks: paged
        #   batched-speculation handoffs realigned as a host table
        #   shift vs the loud device row-gather fallback.
        self.prefill_adopt_bytes = 0
        self.prefix_adopt_bytes = 0
        self.kv_prefix_copy_fallback = 0
        self.interleaved_prefills = 0
        self.interleave_max_stall = 0
        self.prefill_chunk_queue_depth = 0
        self.spec_realign_table_ops = 0
        self.spec_realign_repacks = 0
        # Robustness layer (r12). Deadlines: every request may carry a
        # wall-clock budget (``deadline_ms``; this engine default
        # applies when the request names none); each dispatch boundary
        # checks expiry via ``_expire_if_due`` and cancels the row the
        # way client disconnects already do, after a terminal
        # DeadlineExceeded frame. ``None`` default = no deadline — the
        # pre-r12 stream bytes, untouched.
        self.default_deadline_ms: float | None = None
        # SLO-aware admission control: before enqueueing, a deadlined
        # request's feasibility is estimated from the LatencyStats p95
        # reservoirs and the current queue depth
        # (``admission_estimate_ms``); infeasible requests shed 503 +
        # computed retry-after at the door instead of occupying a slot
        # and timing out later. Sustained queue pressure engages the
        # counted brownout ladder (clamp n_new to the default tier,
        # suppress speculation, evict idle prefix page sets) before
        # shedding.
        self.admission_control = True
        # Graceful drain: ``drain()`` flips this, sheds new admissions
        # (503 + retry-after), lets in-flight streams finish inside
        # the budget, then cancels leftovers with DrainCancelled
        # terminal frames.
        self.draining = False
        # The reqs list of the batch the decode thread is currently
        # running (None between batches) — what drain() must wait out
        # or cancel. Written by the decode thread, read from the loop.
        self._running: list | None = None
        # The reqs list the collector has claimed but not yet finished
        # running (None otherwise) — the straggler-collection window
        # plus the executor handoff, during which those requests are
        # in neither the queue, the staging lists, nor _running.
        # drain() must treat this window as in-flight work (idle
        # check + budget-exhausted cancellation) or it can declare
        # the engine idle with a batch still forming.
        self._forming: list | None = None
        # The collector's window-incompatible leftovers, kept between
        # its iterations: claimed off the queue but in neither the
        # staging lists nor a formed batch. An engine attribute — not
        # a collector local — so drain()'s budget-exhausted sweep can
        # deliver their DrainCancelled frames too.
        self._carry: list = []
        # Robustness counters (exported on /metrics + bench snapshot).
        self.shed_queue_full = 0
        self.shed_deadline_infeasible = 0
        self.shed_draining = 0
        self.deadline_expired_queued = 0
        self.deadline_expired_prefill = 0
        self.deadline_expired_decode = 0
        self.brownout_spec_suppressed = 0
        self.brownout_tokens_clamped = 0
        # Mixed-tenant batching observability: batch runs dispatched
        # with the single-tenant grouped fast path (one x @ A @ B per
        # target) vs the gathered BGMV path (per-row slot gather).
        # Counted once per batch run, like fused_calls — never per
        # chunk. Both 0 with adapter_slots off.
        self.adapter_grouped_batches = 0
        self.adapter_gathered_batches = 0
        # Continuous-batching scheduler v2 (r15, serving/scheduler.py;
        # DEFAULT-ON since r20 — the one execution model): one
        # typed-unit queue (prefill chunk / decode chunk / spec round
        # / admission / compaction / score) across up to
        # ``sched_max_batches`` CONCURRENT BatchRuns, SLO-prioritized
        # by WEIGHTED deadline slack (per-tenant weights from the
        # ledger below) with TTFT/ITL targets fed from the
        # LatencyStats reservoirs. ``sched_max_batches=1`` pins ONE
        # lane — the legacy serial semantics (one live batch +
        # in-lane admission) on the same machinery (the
        # ``--no-scheduler`` flag was retired in r22). The scheduler
        # object itself is created by start() and torn down by stop().
        self.sched_max_batches = max(1, int(sched_max_batches))
        self.sched = None
        # Per-tenant quotas/weights/pressure (serving/registry.py
        # TenantLedger, r22), attached by the app/__main__ when any
        # tenant flag is configured. None = single-tenant semantics,
        # bit for bit.
        self.tenants = None
        # Per-unit-type dispatch counters + queue observability
        # (exported on /metrics as sched_*).
        self.sched_units_prefill = 0
        self.sched_units_decode = 0
        self.sched_units_spec = 0
        self.sched_units_admit = 0
        self.sched_units_compact = 0
        # Scoring batches from co-resident ScorePaths dispatched as
        # typed units between this engine's decode chunks (r22).
        self.sched_units_score = 0
        self.sched_deadline_preempts = 0
        self.sched_pages_deferred = 0
        # Group held back because its adapters could not all claim a
        # device slot right now (free + hold-free-evictable < needed)
        # — the adapter-slot term of the same reservation gate.
        self.sched_adapters_deferred = 0
        # Per-tenant terms of the same gate (r22): the POOL had room
        # but the group's TENANT was at its page/slot quota. The
        # ledger counts the same deferral per tenant.
        self.sched_tenant_pages_deferred = 0
        self.sched_tenant_adapters_deferred = 0
        # Tenant-scoped brownout rung (engages before the fleet-wide
        # ladder): submits clamped because ONE tenant's live depth
        # crossed its share of the queue.
        self.brownout_tenant_clamped = 0
        self.sched_batches_live_max = 0
        # Largest run of consecutive units ONE lane dispatched while
        # another lane was live — the cross-lane head-of-line bound
        # (r10's interleave_max_stall generalized across batches).
        # With fused-chunk widths folded into units, the design pins
        # a concurrent lane's stall behind a fused batch at ONE
        # fused-chunk dispatch; always counters, never wall-clock.
        self.sched_lane_stall_max = 0
        # Router backpressure (r15 satellite): the fleet backlog the
        # router observed when it forwarded the last request here
        # (x-mlapi-router-depth, EXCLUDING this replica's own share).
        # Feeds admission_estimate_ms and the brownout ladder so a
        # replica sheds/degrades on FLEET pressure, not just its own
        # queue; stays 0 without a router in front.
        self.router_queue_depth = 0
        # TTFT / inter-token reservoirs, recorded at the push seam.
        from mlapi_tpu.serving.requests import LatencyStats

        self.latency = LatencyStats()
        # (chunk width, table width) pairs whose paged chunked-extend
        # program is compiled — strict mode gates interleaved
        # admission on this set.
        self._warmed_extend: set = set()
        # Host-loop speculation phase: rounds + warmed-shape state
        # live in serving/spec_phase.py.
        self.spec = SpecPhase(self)
        # Batch-1 fused fast path: eligibility, dispatch, and warmed
        # state live in serving/fused_single.py.
        self.fused = FusedSinglePath(self)
        # Batch-resize (compaction) shapes proven compiled — in
        # strict non-eager mode a resize outside this set is skipped
        # (decode stays at full width) rather than compiled mid-batch.
        self._warmed_shrink: set = set()

    @property
    def queue_depth(self) -> int:
        base = self._queue.qsize() if self._queue is not None else 0
        # Scheduler mode: groups the collector has formed but the
        # scheduler has not yet laned are still WAITING work — without
        # this term they would vanish from backpressure, admission
        # estimates, and the router's scrape the moment the collector
        # popped them (/healthz queue_depth must reflect the typed-unit
        # queue, not just the submit queue).
        sched = self.sched.backlog if self.sched is not None else 0
        with self._alock:
            return base + len(self._admit) + len(self._deferred) + sched

    @property
    def sched_queue_depth(self) -> int:
        """Typed-unit queue depth: one runnable unit per live lane
        plus one formation unit per pending group (0, scheduler
        off)."""
        return self.sched.queue_depth if self.sched is not None else 0

    @property
    def sched_batches_live(self) -> int:
        return self.sched.batches_live if self.sched is not None else 0

    # -- robustness: deadlines, admission control, brownout ---------------

    def _expire_if_due(self, r, stage: str) -> bool:
        """THE deadline check, called at every dispatch boundary the
        scheduler owns (collector pop, formation, admission staging,
        prefill chunks, decode chunks, spec rounds). An expired
        request gets its terminal :class:`DeadlineExceeded` frame and
        is cancelled exactly the way a client disconnect is — the
        existing cancellation path frees the decode row and releases
        its pages through the refcount machinery. Returns True when
        this call expired the request. Deadline-less requests cost one
        attribute read."""
        d = getattr(r, "deadline", None)
        if d is None or r.cancelled or time.perf_counter() < d:
            return False
        counter = f"deadline_expired_{stage}"
        setattr(self, counter, getattr(self, counter) + 1)
        try:
            r.push(DeadlineExceeded(stage))
        except Exception:  # a dead consumer loop must not mask others
            pass
        r.cancel()
        return True

    def admission_estimate_ms(self) -> float:
        """Estimated queue-wait + TTFT for a request submitted NOW,
        from the r10 LatencyStats p95 reservoirs and the live queue
        depths: each ``max_batch``-worth of backlog ahead costs about
        one batch turnaround (p95 TTFT + the default token budget at
        the p95 inter-token rate), and the request then pays its own
        p95 TTFT. Returns 0 until traffic has populated the
        reservoirs — a cold server never sheds on a guess. Running as
        a router replica, the router-scraped fleet backlog
        (``router_queue_depth`` — everyone ELSE's queued work) rides
        into the backlog term: affinity means a re-arriving prefix
        cannot go elsewhere, so fleet pressure is this replica's
        future queue wait too (ROADMAP item-3 remainder: router
        backpressure feeding the item-1 scheduler)."""
        s = self.latency.summary()
        ttft = s["ttft_p95_ms"] or 0.0
        itl = s["intertoken_p50_ms"] or 0.0
        batch_ms = ttft + self.default_max_new_tokens * itl
        backlog = (
            self.queue_depth + self.prefill_chunk_queue_depth
            + self.router_queue_depth
        ) / max(1, self.max_batch)
        return backlog * batch_ms + ttft

    def _brownout_level(self) -> int:
        """Queue pressure → brownout rung: 0 normal, 1 at >= 50% of
        ``max_queue`` (clamp token budgets, suppress speculation), 2
        at >= 75% (additionally evict idle prefix page sets). The
        levers degrade work per request BEFORE the queue-full shed
        fires — Snap ML's degrade-per-tier, not fall-over-globally.
        The router-scraped fleet backlog counts as pressure too (at
        most one local queue's worth, so a huge fleet spike engages
        the ladder without instantly pinning every replica at rung
        2)."""
        if not self.admission_control:
            return 0
        q = self.queue_depth + min(self.router_queue_depth, self.max_queue)
        if q * 4 >= self.max_queue * 3:
            return 2
        if q * 2 >= self.max_queue:
            return 1
        return 0

    async def drain(self, timeout_s: float | None = None) -> None:
        """Graceful drain: stop admitting (submit sheds 503 +
        retry-after, ``/healthz`` reports draining), let in-flight
        streams run to completion inside ``timeout_s``, then cancel
        whatever remains with proper :class:`DrainCancelled` terminal
        frames so no consumer ever hangs on a half-dead stream.
        Idempotent; ``stop()`` afterwards is still the caller's job
        (the app's shutdown hook does both)."""
        self.draining = True
        if timeout_s is None:
            timeout_s = getattr(self, "drain_timeout_s", 10.0)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(0.0, float(timeout_s))
        while loop.time() < deadline:
            with self._alock:
                backlog = len(self._admit) + len(self._deferred)
            queued = (
                self._queue is not None and not self._queue.empty()
            )
            if (
                not queued
                and not backlog
                and not self._carry
                and self._running is None
                and self._forming is None
                and (self.sched is None or self.sched.idle)
            ):
                return
            await asyncio.sleep(0.05)
        # Budget exhausted: terminal frames for everything still in
        # flight or queued, then cancel the rows (pages come back via
        # the cancellation path; stop() handles the collector task).
        leftovers: list = []
        if self._queue is not None:
            while not self._queue.empty():
                leftovers.append(self._queue.get_nowait())
        with self._alock:
            leftovers += self._admit + self._deferred
            self._admit.clear()
            self._deferred.clear()
        # The collector's carry list: claimed off the queue but in
        # neither the queue, the staging lists, nor a formed batch.
        # Cancel-only (no clear) — the collector owns the list and
        # drops cancelled rows at its next formation.
        leftovers += list(self._carry)
        if self.sched is not None:
            # The typed-unit queue: pending groups are popped (they
            # will never be laned), live lanes' requests are
            # cancel-only — each lane notices at its next unit
            # boundary exactly like a disconnect and releases its
            # pages on the way out.
            leftovers += self.sched.sweep_requests()
        running = self._running
        if running is not None:
            leftovers += list(running)
        forming = self._forming
        if forming is not None:
            # May overlap ``running`` (the collector keeps its claim
            # until the batch finishes); the ``cancelled`` guard below
            # makes the second visit a no-op.
            leftovers += list(forming)
        for r in leftovers:
            if getattr(r, "cancelled", False):
                continue
            try:
                r.push(DrainCancelled())
            except Exception:
                pass
            r.cancel()
        # Give the decode thread a moment to notice the cancels and
        # finish the batch — bounded, never a hang.
        grace = loop.time() + 2.0
        while (
            self._running is not None
            or (self.sched is not None and not self.sched.idle)
        ) and loop.time() < grace:
            await asyncio.sleep(0.05)

    @property
    def _admit_eager(self) -> bool:
        """May the admission path compile a TRIVIAL program (KV
        scatter, growth gather) on demand? Yes on a low-RTT attach
        (local chip / CPU: sub-second compile, nobody notices); no
        through a network tunnel, where even a trivial remote compile
        stalls the running batch for seconds — there, only pre-warmed
        shapes are admitted."""
        if self._admit_eager_override is not None:
            return self._admit_eager_override
        self._admit_eager_override = _dispatch_rtt_ms() < 15.0
        return self._admit_eager_override

    # Shared surface with the classification engines (healthz, app).
    @property
    def vocab(self):
        from mlapi_tpu.utils.vocab import LabelVocab

        return LabelVocab(())  # no label space; output is text

    # -- shapes ------------------------------------------------------------
    def _bucket(self, n: int) -> int:
        i = bisect.bisect_left(self.prompt_buckets, n)
        return self.prompt_buckets[min(i, len(self.prompt_buckets) - 1)]

    @property
    def default_tier(self) -> int:
        """The power-of-two (of ``chunk``) tier covering the default
        token budget — the floor every warm grid and the fused ladder
        share (ONE definition; four copies of this loop had to agree
        before it existed)."""
        tier = self.chunk
        while tier < self.default_max_new_tokens:
            tier *= 2
        return tier

    def _cache_len(self, bucket: int, n_new: int) -> int:
        """Static KV-cache length for a batch, quantized so the
        program count stays logarithmic: new-token room is at least
        the default (every ``n_new <= default`` request shares ONE
        warmed shape) and beyond that rounds up to power-of-two
        multiples of ``chunk``; clamped to the model's window. A
        slightly roomier cache costs a few KB of HBM and zero decode
        steps (the loop stops at the requested token count) — compile
        ambushes on the request path cost p99."""
        want = max(n_new, self.default_max_new_tokens)
        tier = self.chunk
        while tier < want:
            tier *= 2
        return min(self.model.max_positions, bucket + tier)

    def kv_cache_slot_bytes(self) -> int:
        """DETERMINISTIC per-slot KV-cache STORAGE bytes at the
        default bucket/tier config (largest prompt bucket, default
        token tier): ``addressable_shards[...].data.nbytes`` summed
        over a batch-1 cache — the committed-number discipline the
        FSDP PR set (byte counts are exact where this box's
        wall-clock swings ±25-30%). One continuous-batching slot, one
        prefix-cache entry of this tier, and one spec mirror row each
        cost this much device HBM; ``kv_quant="int8"`` roughly
        halving it is the storage half of the int8-KV claim, reported
        on ``/metrics`` and in the bench block. The READ half —
        whether decode traffic actually shrinks — depends on the
        decode impl too: see :meth:`decode_bytes_per_step`."""
        if self._kv_slot_bytes is None:
            from mlapi_tpu.train.bench import bytes_per_device

            total = self._cache_len(
                self.prompt_buckets[-1], self.default_max_new_tokens
            )
            cache = self.model.init_cache(1, total)
            jax.block_until_ready(cache)
            self._kv_slot_bytes = int(bytes_per_device(cache))
        return self._kv_slot_bytes

    def decode_bytes_per_step(self) -> int:
        """Modeled HBM bytes ONE decode step's attention read moves
        per slot at the default bucket/tier config — the number that
        makes the int8 READ saving observable in production
        (``/metrics`` gauge ``generate.decode_bytes_per_step``), not
        just in bench. Pure host arithmetic over abstract cache
        shapes (``jax.eval_shape`` — no device allocation), so it is
        exact and deterministic. The model, per (cache format,
        ``decode_attn_impl``):

        - **flash**: the kernel streams the STORED tiles — int8
          payload + f32 scales, or the compute-dtype arrays, at the
          cache's native KV-head width (queries group in-register) —
          so the read is exactly the storage bytes.
        - **einsum**: the einsum operand is the full-precision cache
          at QUERY-head width — ``kv_cache_kv`` dequantizes at the
          read seam and GQA models broadcast KV heads to query heads
          (``_repeat_kv``), both materialized between the seam and
          the einsum. ONE consistent accounting: whenever the operand
          differs from storage (by format or head width), the
          materializing producer reads the stored cache first and the
          einsum then reads the operand — storage PLUS operand bytes;
          when they coincide (MHA, ``kv_quant="none"``) there is one
          read of the stored cache. These lines are WHY the flash
          kernel exists: it is the only path where the storage format
          (and the GQA grouping) reaches the read.

        Computed once per engine (it is constant for the engine's
        lifetime) — /metrics scrapes read the cached value.
        """
        if self._decode_step_bytes is not None:
            return self._decode_step_bytes
        total = self._cache_len(
            self.prompt_buckets[-1], self.default_max_new_tokens
        )
        abstract = jax.eval_shape(lambda: self.model.init_cache(1, total))
        stored = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(abstract)
        )
        if self.decode_attn_impl == "flash":
            self._decode_step_bytes = stored
            return stored
        # The einsum operand: full-precision payload at query-head
        # width (cache payloads store KV heads; GQA's broadcast
        # multiplies by the group factor).
        cdt = jnp.dtype(getattr(self.model, "compute_dtype", "float32"))
        heads = int(getattr(self.model, "num_heads", 0))
        full = 0
        for layer in abstract.values():
            for name in ("k", "v", "k_q", "v_q"):
                if name not in layer:
                    continue
                leaf = layer[name]
                group = max(1, heads // leaf.shape[2]) if heads else 1
                full += int(np.prod(leaf.shape)) * group * cdt.itemsize
        # Operand == storage (MHA, no format): one read. Otherwise
        # the producer reads storage and the einsum reads the
        # materialized operand.
        self._decode_step_bytes = (
            full if full == stored else stored + full
        )
        return self._decode_step_bytes

    def extend_bytes_per_chunk(self) -> int:
        """Modeled HBM bytes ONE multi-token extend chunk's attention
        read moves per slot at the default bucket/tier config —
        ``decode_bytes_per_step``'s accounting applied to the OTHER
        half of the token pipeline (``/metrics`` gauge
        ``generate.extend_bytes_per_chunk``). The read model is
        EXACTLY the decode one, by construction: an extend dispatch
        streams the same stored cache (flash — the U-row Q tile rides
        into each program, so a tile is still read once) or
        materializes the same full-precision query-head-width operand
        (einsum — ``kv_cache_kv``'s dequant and the GQA broadcast
        don't depend on the query width), so the int8 flash saving
        2D/(D+4) (1.94x at bf16 D=128) carries over verbatim. What
        differs is AMORTIZATION: a chunk pays this read once for its
        whole U-token span, where the decode loop pays
        ``decode_bytes_per_step`` per token — which is why chunked
        prefill, admission mini-prefills and speculative verify were
        worth making kernel-native at all (every server token now
        reads the cache at its stored byte format). Same
        ``jax.eval_shape`` host arithmetic: exact, deterministic, no
        device work."""
        return self.decode_bytes_per_step()

    # -- paged-pool accounting (state lives in serving/paged_pool.py) -----
    @property
    def kv_pages_total(self) -> int:
        return self.pool.pages_total if self.pool is not None else 0

    @property
    def kv_pages_in_use(self) -> int:
        return self.pool.pages_in_use if self.pool is not None else 0

    @property
    def kv_pages_shared(self) -> int:
        return self.pool.pages_shared if self.pool is not None else 0

    @property
    def kv_page_utilization(self) -> float:
        return self.pool.utilization if self.pool is not None else 0.0

    def kv_page_bytes(self) -> int:
        """Exact device bytes of ONE page across every layer (pure
        dtype/shape arithmetic) — the unit of the paged capacity
        model: a sequence of ``t`` cached tokens holds
        ``ceil(t / page)`` pages, so its padding waste is bounded by
        one page instead of (tier - t) slots."""
        return self.pool.page_bytes if self.pool is not None else 0

    @property
    def faults_injected(self) -> int:
        """Armed-fault fires since the harness was last armed (0 when
        disarmed) — state lives in ``serving/faults.py``."""
        return faults.injected_count()

    # -- host-tier accounting (state lives in serving/kv_tier.py) ---------
    # All byte counters are exact dtype/shape arithmetic (the
    # ``ops/quant.kv_tree_bytes`` closed form applied per blob), never
    # wall-clock; every gauge reads 0 with the tier disabled.
    @property
    def kv_prefix_restore_hits(self) -> int:
        """Blob applications: entry rebuilds + pool-page restores —
        each one a prefill (or adopt) the tier made unnecessary."""
        return self.kv_tier.restore_hits if self.kv_tier else 0

    @property
    def kv_prefix_restore_misses(self) -> int:
        return self.kv_tier.restore_misses if self.kv_tier else 0

    @property
    def kv_prefix_restore_bytes(self) -> int:
        return self.kv_tier.restore_bytes if self.kv_tier else 0

    @property
    def kv_prefix_restore_failures(self) -> int:
        return self.kv_tier.restore_failures if self.kv_tier else 0

    @property
    def kv_prefix_spill_count(self) -> int:
        return self.kv_tier.spill_count if self.kv_tier else 0

    @property
    def kv_prefix_spill_bytes(self) -> int:
        return self.kv_tier.spill_bytes if self.kv_tier else 0

    @property
    def kv_prefix_spill_failures(self) -> int:
        return self.kv_tier.spill_failures if self.kv_tier else 0

    @property
    def kv_tier_bytes_in_use(self) -> int:
        return self.kv_tier.bytes_in_use if self.kv_tier else 0

    @property
    def kv_tier_entries(self) -> int:
        return self.kv_tier.entries if self.kv_tier else 0

    @property
    def kv_tier_evictions(self) -> int:
        return self.kv_tier.evictions if self.kv_tier else 0

    # -- peer-fetch accounting (state lives in serving/kv_peer.py) --------
    # Byte counters are exact wire-payload arithmetic (every blob's
    # ``num_pages x kv_page_bytes`` closed form), never wall-clock;
    # all zero with --kv-peer-fetch off.
    @property
    def kv_peer_fetch_hits(self) -> int:
        """Peer blobs APPLIED (entry rebuilt from the wire) — each
        one a cold prefill the fleet's warmth made unnecessary."""
        return self.kv_peer.fetch_hits if self.kv_peer else 0

    @property
    def kv_peer_fetch_misses(self) -> int:
        return self.kv_peer.fetch_misses if self.kv_peer else 0

    @property
    def kv_peer_fetch_bytes(self) -> int:
        return self.kv_peer.fetch_bytes if self.kv_peer else 0

    @property
    def kv_peer_fetch_failures(self) -> int:
        return self.kv_peer.fetch_failures if self.kv_peer else 0

    @property
    def kv_peer_serve_count(self) -> int:
        return self.kv_peer.serve_count if self.kv_peer else 0

    @property
    def kv_peer_serve_bytes(self) -> int:
        return self.kv_peer.serve_bytes if self.kv_peer else 0

    # -- adapter accounting (state lives in serving/adapter_store.py).
    # Byte counters are exact wire/dtype-shape arithmetic (header
    # nbytes, ``slot_bytes`` closed forms), never wall-clock; all zero
    # with adapter_slots off.
    @property
    def adapter_slots_total(self) -> int:
        return self.adapters.slots_total if self.adapters else 0

    @property
    def adapter_slots_in_use(self) -> int:
        return self.adapters.slots_in_use if self.adapters else 0

    @property
    def adapter_evictions(self) -> int:
        return self.adapters.evictions if self.adapters else 0

    @property
    def adapter_installs(self) -> int:
        return self.adapters.installs if self.adapters else 0

    @property
    def adapter_slot_bytes(self) -> int:
        """Device bytes ONE resident adapter costs (per-target
        ``a [d_in, r] + b [r, d_out]`` rows at the base kernel dtype):
        the HBM-amortization claim is asserted as ``base params bytes
        + N x adapter_slot_bytes`` for N resident tenants. 0 until the
        first install fixes the engine-wide rank."""
        return self.adapters.slot_bytes() if self.adapters else 0

    @property
    def adapter_resident_bytes(self) -> int:
        """The closed-form HBM total the amortization claim pins:
        base parameter bytes + slots_in_use x adapter_slot_bytes."""
        if self.adapters is None:
            return 0
        base = sum(
            v.size * v.dtype.itemsize
            for v in jax.tree.leaves(self.params)
            if hasattr(v, "dtype")
        )
        return base + self.adapters.slots_in_use * (
            self.adapters.slot_bytes()
        )

    @property
    def adapter_fetch_hits(self) -> int:
        """Peer adapter blobs fetched AND stored — each one a tenant
        onboarded without its weights riding the client request."""
        return self.adapter_peer.fetch_hits if self.adapter_peer else 0

    @property
    def adapter_fetch_misses(self) -> int:
        return self.adapter_peer.fetch_misses if self.adapter_peer else 0

    @property
    def adapter_fetch_bytes(self) -> int:
        return self.adapter_peer.fetch_bytes if self.adapter_peer else 0

    @property
    def adapter_fetch_failures(self) -> int:
        return self.adapter_peer.fetch_failures if self.adapter_peer else 0

    @property
    def adapter_serve_count(self) -> int:
        return self.adapter_peer.serve_count if self.adapter_peer else 0

    @property
    def adapter_serve_bytes(self) -> int:
        return self.adapter_peer.serve_bytes if self.adapter_peer else 0

    @property
    def adapter_store_bytes_in_use(self) -> int:
        return self.adapter_store.bytes_in_use if self.adapter_store else 0

    @property
    def adapter_store_entries(self) -> int:
        return self.adapter_store.entries if self.adapter_store else 0

    @property
    def adapter_store_evictions(self) -> int:
        return self.adapter_store.evictions if self.adapter_store else 0

    def register_adapter(self, aid: str, payload: dict) -> int:
        """Install a pre-scaled adapter payload (``{layer: {target:
        {a, b}}}``, ``b`` already carrying alpha/rank — see
        ``models/lora.export_adapter``) into the HOST store under
        ``aid``; device slots install lazily at first request. The
        CLI's ``--adapter id=path`` and tests load through here.
        Returns the stored wire-image byte count."""
        from mlapi_tpu.serving import adapter_store as _as

        if self.adapter_store is None:
            raise ValueError(
                "engine built without adapter slots "
                "(--adapter-slots 0): cannot register adapters"
            )
        if not _as.ADAPTER_ID_RE.match(aid or ""):
            raise ValueError(f"bad adapter id {aid!r}")
        _as.adapter_rank(payload)  # loud on ragged/empty payloads
        nbytes = self.adapter_store.put(aid, payload)
        return nbytes

    # -- disaggregation accounting (state lives in serving/kv_peer.py's
    # KVPush) — byte counters are exact payload arithmetic (each
    # chunk's ``span × per-slot kv bytes`` closed form), never
    # wall-clock; everything 0 on a mixed replica.
    @property
    def kv_push_sent(self) -> int:
        return self.kv_push.push_sent if self.kv_push else 0

    @property
    def kv_push_send_failures(self) -> int:
        return self.kv_push.push_send_failures if self.kv_push else 0

    @property
    def kv_push_bytes_sent(self) -> int:
        return self.kv_push.push_bytes_sent if self.kv_push else 0

    @property
    def kv_push_recv(self) -> int:
        return self.kv_push.push_recv if self.kv_push else 0

    @property
    def kv_push_recv_failures(self) -> int:
        return self.kv_push.push_recv_failures if self.kv_push else 0

    @property
    def kv_push_bytes_recv(self) -> int:
        return self.kv_push.push_bytes_recv if self.kv_push else 0

    @property
    def kv_push_applied(self) -> int:
        """Pushed transfers installed as live decode rows — moving
        while ``prefix_builds`` AND ``prefill_chunks`` stay flat IS
        the zero-decode-side-prefill claim."""
        return self.kv_push.push_applied if self.kv_push else 0

    @property
    def kv_push_bytes_applied(self) -> int:
        return self.kv_push.push_bytes_applied if self.kv_push else 0

    @property
    def kv_push_fallbacks(self) -> int:
        return self.kv_push.push_fallbacks if self.kv_push else 0

    # -- prefix-cache counters (state lives in serving/prefix.py) ---------
    @property
    def prefix_hits(self) -> int:
        return self.prefix.hits

    @property
    def prefix_misses(self) -> int:
        return self.prefix.misses

    @property
    def prefix_fallbacks(self) -> int:
        return self.prefix.fallbacks

    @property
    def prefix_builds(self) -> int:
        """Actual cold prefills (``_build`` ran): the counter the
        router's prefix-affinity claim is asserted against — affinity
        keeps repeated prefixes on one replica, so the fleet-wide sum
        of ``builds`` stays at one per distinct prefix instead of one
        per (prefix, replica) pair. Tier restores move ``misses`` but
        never this."""
        return self.prefix.builds

    def _resolve_adapter(self, aid: str) -> None:
        """Resolve an adapter id into the HOST store (encode executor
        thread — never the dispatch thread): already registered, or
        already resident on device, or fetched from the router-hinted
        warm peer and staged. Raises ``AdapterUnavailable`` (mapped to
        404) when this replica cannot serve the tenant — feature off,
        malformed id, or no blob anywhere — BEFORE the request ever
        queues, so a mistyped tenant id costs a hash lookup, not a
        batch slot."""
        from mlapi_tpu.serving.adapter_store import (
            ADAPTER_ID_RE, AdapterUnavailable,
        )

        if self.adapters is None:
            raise AdapterUnavailable(
                "this replica serves no adapters (--adapter-slots 0)"
            )
        if not isinstance(aid, str) or not ADAPTER_ID_RE.match(aid):
            raise AdapterUnavailable(f"malformed adapter id {aid!r:.80}")
        if self.adapters.resident(aid) or self.adapter_store.has(aid):
            return
        got = self.adapter_peer.fetch(aid) if self.adapter_peer else None
        if got is not None:
            self.adapter_store.put(aid, got[0])
            return
        raise AdapterUnavailable(
            f"adapter {aid!r} is not registered on this replica"
        )

    def _encode(self, text: str, n_new: int, temperature: float, seed: int,
                loop, top_k: int = 0, top_p: float = 1.0,
                prefix: str | None = None,
                stream: bool = False,
                deadline_ms: float | None = None,
                push_to=None, kv_xfer: str | None = None,
                adapter: str | None = None) -> GenRequest:
        entry = None
        raw = None
        if adapter is not None:
            self._resolve_adapter(adapter)
            if prefix:
                # The prefix cache holds BASE-model KV; reusing it
                # under a tenant's adapted weights would condition the
                # suffix on the wrong model. Fold the prefix into the
                # prompt instead — identical semantics, zero cache
                # pollution — and count it where the cache's other
                # declined reuses land.
                self.prefix.count_fallback()
                text = prefix + text
                prefix = None
        if prefix:
            raw = self.tokenizer.token_ids(text)
            if not raw:
                # An empty suffix would condition on a fabricated pad
                # placeholder behind the prefix — serve the prefix
                # alone through the plain path instead (identical
                # output by the pinned equivalence).
                self.prefix.count_fallback()
                text = prefix + text
                raw = None  # re-tokenize the concatenation below
            else:
                # The suffix runs as ONE fused block forward against
                # the cached prefix KV (extend_core), so the KV path
                # wins for every nonempty prefix — no length
                # heuristic needed.
                entry = self.prefix.entry(prefix)
        p_len = entry.bucket if entry else 0
        limit = self.model.max_positions - n_new - p_len
        if limit <= 0:
            raise ValueError(
                f"max_new_tokens={n_new}"
                + (f" plus a {p_len}-slot prefix" if p_len else "")
                + f" leaves no room for a prompt "
                  f"(max_positions={self.model.max_positions})"
            )
        if raw is None:
            raw = self.tokenizer.token_ids(text)
        if entry is not None and len(raw) > limit:
            # The plain path documents left-truncation of oversized
            # prompts; on the KV path that would truncate the SUFFIX
            # while keeping the whole prefix — silently different
            # conditioning than the concatenated prompt. Refuse loud.
            raise ValueError(
                f"prefix + text + max_new_tokens exceed the model "
                f"window (suffix is {len(raw)} tokens, {limit} fit "
                f"behind the {p_len}-slot prefix)"
            )
        raw = raw[-limit:] if raw else [self.tokenizer.pad_id]
        # Left-pad to a bucket so common prompt lengths never
        # recompile; pads are masked out by the model (n_pad), so the
        # answer is identical whichever bucket the prompt lands in. A
        # prompt longer than the largest bucket rounds up to a
        # multiple of it and prefills in fixed-width chunks (ONE
        # compiled program per cache tier, any length — see
        # ``extend_chunk_fn``); only when even that multiple exceeds
        # the window does it take its exact length (one-off compile)
        # rather than silent truncation.
        if len(raw) > self.prompt_buckets[-1]:
            cp = self.prompt_buckets[-1]
            bucket = -(-len(raw) // cp) * cp
            if bucket > limit:
                bucket = len(raw)
        else:
            bucket = self._bucket(len(raw))
        bucket = min(bucket, limit)
        row = np.full((bucket,), self.tokenizer.pad_id, np.int32)
        used = min(len(raw), bucket)
        row[-used:] = raw[-used:]
        pushed = None
        if kv_xfer is not None and self.kv_push is not None:
            # Decode-role arrival naming a pushed transfer: take the
            # assembled blob (encode executor thread — the host
            # concat runs here, never on the dispatch thread) and
            # validate its geometry against what THIS replica's
            # encode just produced. Anything short of an exact match
            # — incomplete/failed transfer, bucket/used drift across
            # configs — is a counted fallback to the cold prefill;
            # the stream still serves, just without the saved FLOPs.
            pushed = self.kv_push.take(kv_xfer)
            if pushed is not None and (
                pushed.bucket != bucket or pushed.used != used
                or entry is not None
            ):
                _log.debug(
                    "pushed transfer %s geometry drifted "
                    "(%d/%d vs local %d/%d); cold prefill",
                    kv_xfer, pushed.bucket, pushed.used, bucket, used,
                )
                pushed = None
            if pushed is None:
                self.kv_push.count_fallback()
        return GenRequest(
            row, used, n_new, temperature, seed, loop, top_k, top_p,
            prefix=entry, stream=stream, stats=self.latency,
            deadline_ms=deadline_ms, push_to=push_to, pushed=pushed,
            adapter=adapter,
        )

    # -- the batched decode (runs on a worker thread) ----------------------
    @staticmethod
    def _key_data(seed: int) -> np.ndarray:
        return np.asarray(jax.random.key_data(jax.random.key(seed)))

    def _pack_rows(self, reqs, bucket: int, b_pad: int):
        """Pack the per-row host mirrors for a batch: left-padded
        prompt rows plus the pad/sampling vectors, dummy rows (pad to
        ``b_pad``) fully masked. ONE definition shared by the chunked
        batch formation and the fused-batched fast path — the two
        paths' byte-identity contract rests on packing rows the same
        way. Returns ``(prompt, n_pad, temps, topk, topp, keys)``."""
        b = len(reqs)
        prompt = np.full((b_pad, bucket), self.tokenizer.pad_id, np.int32)
        n_pad = np.full((b_pad,), max(bucket - 1, 0), np.int32)
        temps = np.zeros((b_pad,), np.float32)
        topk = np.zeros((b_pad,), np.int32)
        topp = np.ones((b_pad,), np.float32)
        for i, r in enumerate(reqs):
            prompt[i, bucket - len(r.row):] = r.row
            n_pad[i] = bucket - r.used
            temps[i] = r.temperature
            topk[i] = r.top_k
            topp[i] = r.top_p
        keys = np.stack(
            [self._key_data(r.seed) for r in reqs]
            + [self._key_data(0)] * (b_pad - b)
        )
        return prompt, n_pad, temps, topk, topp, keys

    def _form_batch(self, reqs: list, admit: bool,
                    fused_ok: bool = True):
        """The formation preamble shared by ``_run_batch`` and the
        unit scheduler's lane start — ONE definition, because the
        serial/concurrent identity contract rests on both gating
        formation identically. Sweeps queue-expired requests
        (terminal frame, never a device dispatch) and returns the
        formed :class:`BatchRun` — or ``None`` when everyone expired.
        Requests whose deadline passed during the queue wait never
        reach the device; the sweep edits ``reqs`` in place
        (admission appends to this list object and error delivery
        iterates it). ``fused_ok=False`` pins the plain chunk width
        (warmup's chunked grid compiles those shapes deliberately);
        otherwise the fused-chunk width is decided per dispatch
        boundary inside the run (``serving/fused_single.py``)."""
        from mlapi_tpu.serving.batch_run import BatchRun

        alive = [
            r for r in reqs if not self._expire_if_due(r, "queued")
        ]
        if not alive:
            return None
        reqs[:] = alive
        self.batch_calls += 1
        return BatchRun(self, reqs, admit, fused_ok)

    def _run_batch(self, reqs: list, admit: bool = False,
                   fused_ok: bool = True) -> None:
        """Serve one coalesced batch through the continuous-batch
        lifecycle, which lives in ``serving/batch_run.py`` as
        :class:`BatchRun` (formation + prefill, speculative handoff,
        mid-batch admission, compaction, chained chunk decode at
        plain or fused-chunk widths — see that module's seam table).

        Error delivery stays HERE: admission appends joiners to
        ``reqs`` in place, so a mid-batch failure is delivered to
        every waiter, including requests admitted after formation.
        Each gets the exception object; a ``None`` sentinel marks
        normal completion (pushed by the lifecycle stages).
        """
        try:
            self._running = reqs
            run = self._form_batch(reqs, admit, fused_ok)
            if run is not None:
                run.run()
        except Exception as e:  # noqa: BLE001 — delivered to every waiter
            _log.error("generation batch of %d failed: %s", len(reqs), e)
            for r in reqs:
                try:
                    r.push(e)
                except Exception:  # a dead loop must not mask others
                    pass
        finally:
            self._running = None

    # -- asyncio batcher ---------------------------------------------------
    async def start(self) -> None:
        if self._task is None:
            self._queue = asyncio.Queue(maxsize=self.max_queue)
            self._kick = asyncio.Event()
            self._aloop = asyncio.get_running_loop()
            if self.sched is None:
                from mlapi_tpu.serving.scheduler import UnitScheduler

                self.sched = UnitScheduler(
                    self, max_batches=self.sched_max_batches
                )
            self._task = asyncio.create_task(
                self._collect_loop(), name="genbatcher"
            )

    def _wake_collector(self) -> None:
        """Nudge the collector out of its blocking waits (queue pop /
        dispatch backoff) from ANY thread — lanes retire and requests
        defer on the scheduler's dispatch thread, and the staged work
        those events unblock must not sit until the 50 ms poll. Safe
        before start() and after the loop dies (wakes are then moot:
        stop()'s sweeps deliver everything)."""
        loop, ev = self._aloop, self._kick
        if loop is None or ev is None:
            return
        try:
            loop.call_soon_threadsafe(ev.set)
        except RuntimeError:
            pass  # loop already closed — nothing left to wake

    def _defer(self, cand) -> None:
        """Park an admission candidate for the collector to reclaim
        (lane incompatible / no room / pages exhausted) and wake it —
        the ONE deferral seam for the 8 batch-run decline sites, so a
        deferred request re-enters dispatch immediately instead of
        riding the poll interval."""
        with self._alock:
            self._deferred.append(cand)
        self._wake_collector()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001
                # A collector that died on its own (e.g. an injected
                # fault) already delivered its waiters' error frames
                # in its finally; stop() must still complete so
                # start() can bring up a fresh collector.
                _log.warning("collector had died: %r", e)
            self._task = None
        if self.sched is not None:
            # Off the loop: stop() joins the dispatch thread, which
            # may be mid-unit (device work takes as long as it takes).
            sched, self.sched = self.sched, None
            await asyncio.get_running_loop().run_in_executor(
                None, sched.stop
            )
        if self._queue is not None:
            while not self._queue.empty():
                req = self._queue.get_nowait()
                req.push(RuntimeError("generation engine stopped"))


    def _spec_should_yield(self) -> bool:
        """Admission candidates end a speculative phase at the next
        round boundary — the handoff seam (tests patch this to force
        a deterministic mid-phase handoff; in production a joiner can
        land during the phase's first compiles, in which case
        yielding before round one is the correct behavior). Under the
        unit scheduler, OTHER runnable lanes/pending groups end the
        phase the same way: a spec round is one unit, and a solo
        phase must not monopolize the dispatch thread while another
        batch has work."""
        with self._alock:
            if self._admit:
                return True
        s = self.sched
        return s is not None and s.queue_depth > 1

    def _compatible(self, group: list, r) -> bool:
        """Can ``r`` join ``group`` without clamping anyone? The batch
        decodes to ``max(n_new)`` from a ``max(bucket)``-wide prompt;
        both maxima together (plus the prefix region, if any) must
        still fit the model's window (each request alone always does —
        ``_encode`` guarantees it).

        Prefix-cached requests batch with each other across DIFFERENT
        prefixes (cross-batch prefix regions): each row's prefix KV is
        right-aligned to the group's common region end
        ``max(prefix_len)`` and masked by its own per-row ``lo``.
        Prefix and plain requests never mix (a plain row would pay the
        whole region in dead cache slots). In strict (tunnel) mode a
        cross-prefix group needs its stacked program shapes pre-warmed
        (``prefix.mix_warmed``, populated at entry registration);
        unwarmed combinations fall back to same-prefix grouping."""
        if (r.prefix_fp is None) != (group[0].prefix_fp is None):
            return False
        # Disaggregated requests run SOLO (r18): a prefill-only run
        # pushes ITS row's chunk KV at each boundary and a pushed-KV
        # row installs a whole-prompt blob at formation — neither
        # composes with co-batched rows' shapes yet (batched prefill
        # handoff is a future optimization, noted in DESIGN §24).
        for x in (r, group[0]):
            if x.push_to is not None or x.pushed is not None:
                return False
        p_len = 0
        if r.prefix_fp is not None:
            p_len = max(r.prefix_len, *(g.prefix_len for g in group))
            mixed = any(g.prefix_fp != r.prefix_fp for g in group)
            if (
                mixed
                and self._strict_admit
                and p_len not in self.prefix.mix_warmed
            ):
                return False
        bucket = max(len(r.row), *(len(g.row) for g in group))
        n_new = max(r.n_new, *(g.n_new for g in group))
        return p_len + bucket + n_new <= self.model.max_positions

    async def _collect_loop(self) -> None:
        """The ONE collector (r20): forms window-compatible groups
        (deadline-slack carry seed, r12) and routes every formed
        group through ``_dispatch_group`` — in-lane admission when a
        live lane can take it at a unit boundary (continuous
        batching), a new scheduler lane otherwise, a bounded wait
        when neither has room. Serial mode (``sched_max_batches=1``;
        the ``--no-scheduler`` flag is retired) is the SAME loop: one
        live batch plus in-lane admission — the legacy collector's
        semantics on the scheduler's machinery, which is why the
        legacy scheduler-off loop could be deleted.

        Backpressure: dispatch blocks (rule 3) while lanes and the
        staging lists are full, which stops the pop below — stalled
        arrivals then fill the bounded queue and shed as 503s, the
        same ``max_queue`` contract as always."""
        loop = asyncio.get_running_loop()
        # self._carry (window-incompatible leftovers, served next) is
        # initialized in __init__ and cleared in the finally below —
        # no reset here, so items seeded between start() and the first
        # iteration (or left by a crashed predecessor, already pushed
        # terminal frames) can never be silently dropped.
        reqs: list = []
        get = None   # in-flight queue pop (outer so the finally sees it)
        kick = None  # in-flight kick wait (outer for the same reason)
        try:
            while True:
                # Clear-then-check: every wake source (deferral, lane
                # retirement) mutates state BEFORE setting _kick, so a
                # mutation landing after this clear re-sets the event
                # and the waits below wake, while one landing before
                # it is visible to this iteration's sweep.
                self._kick.clear()
                # Requests a lane could not take come first. They
                # were staged independently, so re-apply the window
                # compatibility check and the max_batch cap when
                # forming from them. ``_admit`` holds staged
                # candidates a LIVE lane may still take at its next
                # unit boundary — reclaim those only once no batch is
                # live (lane admission defers what it can never
                # admit, so nothing camps there).
                with self._alock:
                    self._carry = self._deferred + self._carry
                    self._deferred.clear()
                    if (
                        self.sched is not None
                        and self.sched.batches_live == 0
                    ):
                        self._carry = self._admit + self._carry
                        self._admit.clear()
                if self._carry:
                    # Deadline-slack pick (absolute deadlines compare
                    # directly); deadline-less carries keep FIFO order
                    # behind every deadlined one — the r12 ``_carry[0]``
                    # head-of-line fix: a tight-deadline
                    # window-incompatible request no longer waits
                    # behind every earlier carried one.
                    seed_i = min(
                        range(len(self._carry)),
                        key=lambda i: (
                            self._carry[i].deadline is None,
                            self._carry[i].deadline or 0.0,
                            i,
                        ),
                    )
                    reqs = [self._carry.pop(seed_i)]
                    self._forming = reqs
                    rest: list = []
                    for r in self._carry:
                        if (
                            len(reqs) < self.max_batch
                            and self._compatible(reqs, r)
                        ):
                            reqs.append(r)
                        else:
                            rest.append(r)
                    self._carry = rest
                else:
                    # Blocking pop, multiplexed with the cross-thread
                    # kick: a deferral or lane retirement while the
                    # queue is idle must re-enter the sweep above, not
                    # wait for the next arrival.
                    get = asyncio.ensure_future(self._queue.get())
                    kick = asyncio.ensure_future(self._kick.wait())
                    await asyncio.wait(
                        {get, kick}, return_when=asyncio.FIRST_COMPLETED
                    )
                    if get.done() and not get.cancelled():
                        reqs = [get.result()]
                        # No await between the pop resuming and this
                        # assignment, so drain() can never observe the
                        # claimed request in neither the queue nor
                        # here.
                        self._forming = reqs
                        get = None
                        # A fault here kills the COLLECTOR between
                        # claiming a request and serving it — the
                        # finally below must still deliver terminal
                        # frames to everything claimed, queued, or
                        # staged.
                        faults.fire("collector_pop")
                        kick.cancel()
                        await asyncio.wait({kick})
                        kick = None
                    else:
                        # The kick won (or an external cancel lands on
                        # the wait above and propagates): retract the
                        # pop without dropping an item it claims in
                        # the same instant — the same race-free dance
                        # as the fill window below.
                        kick.cancel()
                        await asyncio.wait({kick})
                        kick = None
                        get.cancel()
                        await asyncio.wait({get})
                        if get.cancelled():
                            get = None
                            continue  # re-sweep staged work
                        reqs = [get.result()]
                        self._forming = reqs
                        get = None
                        faults.fire("collector_pop")
                if self.max_wait_s > 0:
                    deadline = loop.time() + self.max_wait_s
                    while len(reqs) < self.max_batch:
                        timeout = deadline - loop.time()
                        if timeout <= 0:
                            break
                        # NOT asyncio.wait_for: on py<3.12 wait_for
                        # can SWALLOW an external cancel that lands
                        # just as the inner pop completes (the classic
                        # lost-cancellation race) — a killed collector
                        # then keeps collecting and stop() deadlocks.
                        # Plain asyncio.wait never consumes the
                        # waiter's cancellation, and the outer ``get``
                        # keeps a claimed request visible to the
                        # finally below.
                        get = asyncio.ensure_future(self._queue.get())
                        done, _ = await asyncio.wait({get}, timeout=timeout)
                        if not done:
                            # Window expired with the pop pending:
                            # retract it without dropping an item the
                            # pop claims in the same instant.
                            get.cancel()
                            await asyncio.wait({get})
                            if get.cancelled():
                                get = None
                                break
                        nxt = get.result()
                        get = None
                        if self._compatible(reqs, nxt):
                            reqs.append(nxt)
                        else:
                            self._carry.append(nxt)
                            break  # keep the window short; serve it next
                else:
                    while (
                        len(reqs) < self.max_batch
                        and not self._queue.empty()
                    ):
                        nxt = self._queue.get_nowait()
                        if self._compatible(reqs, nxt):
                            reqs.append(nxt)
                        else:
                            self._carry.append(nxt)
                            break
                await self._dispatch_group(reqs)
                reqs = []
                self._forming = None
        finally:
            self._forming = None
            # Cancellation (stop()) or a collector crash must not
            # strand waiters — neither those already popped off the
            # queue NOR those still queued or awaiting admission (a
            # handler awaiting ``gen.queue.get()`` on a queued request
            # would otherwise hang forever after an unexpected
            # collector death). What was handed to the scheduler is
            # the scheduler's to deliver: its stop() sweeps lanes and
            # pending groups.
            if kick is not None:
                kick.cancel()
            err = RuntimeError("generation engine stopped")
            queued = []
            if get is not None:
                if get.done() and not get.cancelled():
                    queued.append(get.result())
                else:
                    get.cancel()
            if self._queue is not None:
                while not self._queue.empty():
                    queued.append(self._queue.get_nowait())
            with self._alock:
                queued += self._admit + self._deferred
                self._admit.clear()
                self._deferred.clear()
            for r in (*reqs, *self._carry, *queued):
                try:
                    r.push(err)
                except Exception:
                    pass
            self._carry = []

    async def _dispatch_group(self, reqs: list) -> None:
        """Route one formed group, preferring the cheapest seat:

        1. IN-LANE ADMISSION — a live lane whose window fits every
           request takes the group at its next unit boundary (the
           continuous-batching growth path: no new lane, no extra
           prefill program beyond the r10 interleave). Staging is
           once-only (``GenRequest.staged``): a candidate the lane
           then defers re-enters HERE and takes a lane of its own
           instead of ping-ponging between the lists.
        2. PENDING GROUP — hand off to the scheduler, which lanes it
           when a slot and the page budget allow, in deadline-slack
           order; its units then interleave with the other lanes' at
           the typed-unit queue. Bounded at one ``max_batch`` of
           pending requests, so ``max_queue`` keeps meaning something
           during long runs.
        3. WAIT — staging and backlog both full: block on the kick
           (lane retirement / deferral) with a 50 ms poll backstop,
           then re-check. The group stays in ``self._forming`` the
           whole time, so drain() and the terminal-frame sweep always
           see it.
        """
        while True:
            sched = self.sched
            if sched is None:
                raise RuntimeError("scheduler stopped")
            self._kick.clear()
            with self._alock:
                room = (
                    self.max_batch - len(self._admit) - len(self._deferred)
                    >= len(reqs)
                )
            if room and all(not r.staged for r in reqs):
                for lane_reqs in sched.lane_groups():
                    if lane_reqs and all(
                        self._compatible(lane_reqs, r) for r in reqs
                    ):
                        for r in reqs:
                            r.staged = True
                        with self._alock:
                            self._admit.extend(reqs)
                        return
            if sched.backlog < self.max_batch:
                sched.submit(reqs)
                return
            waiter = asyncio.ensure_future(self._kick.wait())
            try:
                await asyncio.wait({waiter}, timeout=0.05)
            finally:
                waiter.cancel()

    async def submit(
        self,
        text: str,
        *,
        max_new_tokens: int | None = None,
        temperature: float = 0.0,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
        prefix: str | None = None,
        stream: bool = False,
        deadline_ms: float | None = None,
        push_to=None,
        kv_xfer: str | None = None,
        adapter: str | None = None,
        tenant: str | None = None,
    ) -> GenRequest:
        """Queue one prompt for batched decode; consume ``req.queue``
        for ``{"token_ids": [...]}`` chunks until the ``None``
        sentinel (exceptions are delivered in-band).

        Disaggregation (r18): ``push_to=(host, port, xfer)`` runs the
        prompt as a PREFILL-ONLY batch (``n_new`` forced to 1 — the
        run ends at the sampled first token) whose chunk KV streams
        to the named decode replica; ``kv_xfer=<id>`` resolves a
        staged pushed transfer so formation installs the prompt KV
        instead of prefilling. Both default None — the pre-r18 path,
        bit for bit.

        ``deadline_ms`` is the request's end-to-end wall-clock budget
        (engine default when ``None``; see ``default_deadline_ms``).
        A deadlined request the admission estimate says cannot finish
        in time sheds HERE — 503 + computed retry-after — instead of
        occupying a queue slot and timing out mid-decode.

        ``tenant`` names the quota/fairness identity (r22, see
        ``serving/registry.py``); it defaults to the adapter id, then
        to the anonymous tenant."""
        from mlapi_tpu.serving.scoring import OverloadedError

        if self._task is None:
            raise RuntimeError("generation engine not started")
        if self._task.done():
            # A dead collector must fail requests fast, not let them
            # queue forever; surface what killed it.
            exc = (
                None if self._task.cancelled() else self._task.exception()
            )
            raise RuntimeError(
                f"generation collector died: {exc!r}"
            ) from exc
        if self.draining:
            # Drain window: new admissions go elsewhere; retry-after
            # hints how long the restart (drain budget) takes.
            self.shed_draining += 1
            self.rejected += 1
            raise OverloadedError(
                "generate",
                retry_after_s=getattr(self, "drain_timeout_s", 10.0),
                detail="server draining: retry against another replica",
            )
        n_new = int(max_new_tokens or self.default_max_new_tokens)
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        tenant = tenant or adapter or ""
        led = self.tenants
        if led is not None and tenant:
            # Tenant-scoped brownout rung (r22): engages BEFORE the
            # fleet-wide ladder — one tenant's live depth crossing a
            # QUARTER of the queue clamps that tenant's token budget
            # at half the pressure the fleet's rung 1 needs (50%), so
            # the hot tenant degrades itself before it degrades
            # everyone. Same lever, same counter discipline.
            if (
                led.depth(tenant) * 4 >= self.max_queue
                and n_new > self.default_max_new_tokens
            ):
                n_new = self.default_max_new_tokens
                self.brownout_tenant_clamped += 1
                led.note_brownout(tenant)
        level = self._brownout_level()
        if level >= 1 and n_new > self.default_max_new_tokens:
            # Brownout lever 1: clamp oversized budgets to the default
            # tier — bounded work per admitted request under pressure.
            n_new = self.default_max_new_tokens
            self.brownout_tokens_clamped += 1
        if level >= 2 and self.pool is not None:
            # Brownout lever 3: proactively evict an idle (LRU,
            # unreferenced) prefix page set so live sequences keep
            # allocating instead of hitting PagePoolExhausted. With
            # the host tier attached the eviction SPILLS instead of
            # discarding (PagePool._spill_and_release), so the brownout
            # trades HBM for host RAM, not for a future re-prefill.
            # Through the executor: the spill is a device gather plus
            # (disk tier) an npz write — run inline it would freeze
            # every stream on the loop for exactly as long as the
            # server is under the pressure that triggered it
            # (mlapi-lint MLA008, caught r19 — the r13 review moved
            # this work outside the pool LOCK; off the LOOP is the
            # other half).
            await asyncio.get_running_loop().run_in_executor(
                None, self.pool.evict_idle, 1
            )
        if (
            self.admission_control
            and deadline_ms is not None
            and deadline_ms > 0
        ):
            est = self.admission_estimate_ms()
            if est > deadline_ms:
                # Infeasible: it would expire in the queue anyway —
                # shed now, and tell the client when the backlog
                # should have cleared.
                self.shed_deadline_infeasible += 1
                self.rejected += 1
                raise OverloadedError(
                    "generate",
                    retry_after_s=max(1.0, (est - deadline_ms) / 1e3),
                    detail=(
                        f"deadline infeasible: estimated queue wait + "
                        f"TTFT {est:.0f} ms exceeds the {deadline_ms:.0f} "
                        f"ms budget"
                    ),
                )
        # Encode OFF the event loop: a first-use prefix runs a device
        # prefill (and possibly an XLA compile) inside _encode — on
        # the loop thread that would freeze every stream and timer in
        # the server for its duration.
        loop = asyncio.get_running_loop()
        req = await loop.run_in_executor(
            None,
            lambda: self._encode(
                text, n_new, float(temperature), int(seed), loop,
                int(top_k), float(top_p), prefix=prefix,
                stream=bool(stream), deadline_ms=deadline_ms,
                push_to=push_to, kv_xfer=kv_xfer, adapter=adapter,
            ),
        )
        if push_to is not None:
            # Prefill-only AFTER encoding: geometry (bucket/limit) was
            # computed with the CLIENT's token budget — identical to
            # what the decode replica computes for the same body — but
            # this run ends at the sampled first token.
            req.n_new = 1
        if self.draining or self._task is None or self._task.done():
            # Drain (or a full stop) may have COMPLETED during the
            # encode executor await: this request passed the front-door
            # check but was invisible to drain's idle sweep (not yet
            # queued, staged, or running), so enqueueing now would land
            # it in a queue no collector will ever pop — a stream with
            # no terminal frame. Shed exactly like the front door.
            self.shed_draining += 1
            self.rejected += 1
            raise OverloadedError(
                "generate",
                retry_after_s=getattr(self, "drain_timeout_s", 10.0),
                detail="server draining: retry against another replica",
            )
        req.tenant = tenant
        try:
            self._queue.put_nowait(req)
        except asyncio.QueueFull:
            self.rejected += 1
            self.shed_queue_full += 1
            raise OverloadedError("generate", retry_after_s=2.0) from None
        if led is not None and tenant:
            # Live-depth accounting: entered once here, exited once
            # at the terminal frame (GenRequest.finish — fires on
            # every delivery path, including cancels). No await
            # between the put and this, so the collector cannot
            # retire the request before its exit hook exists.
            led.enter(tenant)
            req.on_done = lambda t=tenant: led.exit(t)
        self.requests += 1
        return req

    # -- synchronous single-shot (tests, bench, CLI) -----------------------
    def generate_text(
        self,
        text: str,
        *,
        max_new_tokens: int | None = None,
        temperature: float = 0.0,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
        prefix: str | None = None,
        deadline_ms: float | None = None,
        push_to=None,
        kv_xfer: str | None = None,
        adapter: str | None = None,
    ) -> dict:
        """One prompt → generated continuation (text + ids), through
        the same ``_run_batch`` the batcher uses — including its
        batch-1 fused fast path (one XLA program per generation) when
        eligible; pass ``fused_single=False`` at construction to pin
        the chunked programs (e.g. when reproducing a chunked-path
        decode bug). ``push_to``/``kv_xfer`` mirror :meth:`submit`'s
        disaggregation hooks (engine-level tests and drills)."""
        n_new = int(max_new_tokens or self.default_max_new_tokens)
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        req = self._encode(
            text, n_new, float(temperature), int(seed), None,
            int(top_k), float(top_p), prefix=prefix,
            deadline_ms=deadline_ms, push_to=push_to, kv_xfer=kv_xfer,
            adapter=adapter,
        )
        if push_to is not None:
            # Same contract as submit(): encode with the client's
            # budget (geometry parity with the decode replica), then
            # run prefill-only.
            req.n_new = 1
        out_ids: list[int] = []
        sink = _SyncSink(req, out_ids)
        self._run_batch([sink])
        if sink.error is not None:
            raise sink.error
        return {
            "text": self.tokenizer.decode(out_ids),
            "token_ids": out_ids,
            "prompt_tokens": req.prompt_tokens,  # incl. prefix tokens
        }

    def warmup(self, *, full: bool | None = None) -> None:
        """Compile every (prompt bucket × power-of-two batch) prefill
        and decode program at the default-``max_new_tokens`` cache
        tier, off the request path. Combined with batch padding
        (``_run_batch``) and cache-tier quantization (``_cache_len``),
        this means NO request with ``n_new <= default_max_new_tokens``
        ever pays an XLA compile — the classification engine's
        contract, honoured by generation too. Larger ``n_new`` tiers
        (power-of-two chunk multiples, log-many) compile on first use.
        Because ``decode_attn_impl`` (like ``kv_quant``) is a model
        field every program factory keys on, this same grid
        precompiles the flash-decode kernel per (bucket, cache tier)
        when the model selects it — no kernel-specific warm pass.

        ``full=False`` (or env ``MLAPI_TPU_WARMUP=minimal``, used by
        the CPU test suite) warms only the smallest bucket at batch=1.
        """
        import os

        if full is None:
            full = os.environ.get("MLAPI_TPU_WARMUP", "full") != "minimal"
        buckets = self.prompt_buckets if full else self.prompt_buckets[:1]
        # Cover every shape _run_batch can produce: it pads the batch
        # dim to the NEXT power of two, so for max_batch=6 the grid
        # must include 8 (batches of 5-6 pad up past max_batch).
        batches = [1]
        while full and batches[-1] < self.max_batch:
            batches.append(batches[-1] * 2)
        shapes = 0
        for bucket in buckets:
            n_new = min(
                self.default_max_new_tokens,
                self.model.max_positions - bucket,
            )
            if n_new < 1:
                continue
            # Largest n_new that still lands in the default cache tier
            # (so warm programs are byte-identical to default traffic).
            tier = self.default_tier
            for bsz in batches:
                # Row 0 runs two chunks, the rest finish after chunk
                # one: chunk 1 executes the FULL-width decode program,
                # then the batch compacts bsz → bsz/2 for chunk 2 —
                # one _run_batch call compiles the prefill, the
                # decode-chunk program, and that halving's compaction
                # gather. Across the grid this covers the whole
                # halving chain (8→4, 4→2, 2→1). All n_new values stay
                # within the default cache tier, so these are the
                # exact programs default traffic reuses.
                long_n = min(n_new, 2 * self.chunk + 1, tier)
                sinks = []
                for j in range(bsz):
                    row = np.full((bucket,), self.tokenizer.pad_id, np.int32)
                    req = GenRequest(
                        row, 1,
                        long_n if j == 0 else min(2, long_n),
                        0.0, 0, None,
                    )
                    sinks.append(_SyncSink(req, []))
                # fused_ok=False: the warm grid exists to compile the
                # PLAIN-chunk programs (prefill/decode/compaction);
                # the fused-chunk width ladder has its own grid below.
                self._run_batch(sinks, fused_ok=False)
                if sinks[0].error is not None:
                    raise sinks[0].error
                shapes += 1
        # Pre-compute the /metrics per-slot KV byte gauge here, off
        # the request path — lazily it would build a largest-bucket
        # cache on-device inside the first monitoring scrape.
        self.kv_cache_slot_bytes()
        if self.fused_single:
            shapes += self.fused.warm(full)
        if full:
            shapes += self._warm_admission(batches)
            if self.draft_model is not None:
                shapes += self.spec.warm()
            # From here on, a joiner is only admitted into a RUNNING
            # batch when its admission program is already compiled —
            # an unwarmed shape waits for the next batch instead of
            # stalling the running one on an XLA compile.
            self._strict_admit = True
        _log.info(
            "warmed generate: %d (bucket x batch x admission) shapes, "
            "chunk=%d",
            shapes, self.chunk,
        )

    def _warm_admission(self, batches: list) -> int:
        """Compile the continuous-batching admission programs off the
        request path. The expensive program — the joiner's [1, bucket]
        prefill — is keyed on the prompt bucket ALONE (one compile per
        bucket, reusing ``prefill_fn(model, bucket)``); the trivial
        KV-scatter and growth-gather programs are warmed across the
        default-tier (cache × batch) grid. Populates the warmed-shape
        sets that gate strict admission; other cache tiers' scatters
        compile on demand when ``_admit_eager`` allows (low-RTT
        attach) and defer otherwise."""
        from mlapi_tpu.models.gpt import admit_scatter_fn, prefill_fn

        tier = self.default_tier
        shapes = 0
        minis = {}
        for bj in self.prompt_buckets:
            prompt = np.full((1, bj), self.tokenizer.pad_id, np.int32)
            _, minis[bj] = prefill_fn(self.model, bj)(
                self.params, jnp.asarray(prompt),
                jnp.asarray(self._key_data(0)[None]),
                jnp.asarray(np.zeros((1,), np.float32)),
                jnp.asarray(np.asarray([max(bj - 1, 0)], np.int32)),
                jnp.asarray(np.zeros((1,), np.int32)),
                jnp.asarray(np.ones((1,), np.float32)),
            )
            self._warmed_joiner.add(bj)
            shapes += 1
        if self.pool is not None:
            # Paged admission: growth and compaction are host-side
            # page-table ops (no device gather to warm), and the
            # admission program is batch-size-independent — one [1, W]
            # row lands in one table row whatever the running batch
            # is. Page-native mode warms the joiner's direct-to-pages
            # prefill (the ONE admission program — prefill and landing
            # fused); legacy mode warms the adopt scatter it pairs
            # with the contiguous joiner prefill above. Both key on
            # (bucket, table width), the shape pair they compile on.
            # All warm writes go through a null table, i.e. into the
            # never-read null page — the pool is untouched.
            from mlapi_tpu.models.gpt import (
                paged_extend_fn, paged_prefill_fn, paged_scatter_fn,
                sample_fn,
            )
            from mlapi_tpu.ops.quant import (
                paged_cache_tree, paged_pools_of,
            )

            tiers = {
                min(self.model.max_positions, rb + tier)
                for rb in self.prompt_buckets
            }
            one_key = jnp.asarray(self._key_data(0)[None])
            zt1 = jnp.asarray(np.zeros((1,), np.float32))
            zk1 = jnp.asarray(np.zeros((1,), np.int32))
            op1 = jnp.asarray(np.ones((1,), np.float32))
            for bj in self.prompt_buckets:
                for total in tiers:
                    if bj >= total:
                        continue
                    npv = -(-total // self.pool.page)
                    tab1 = np.zeros((1, npv), np.int32)
                    cache = paged_cache_tree(self.pool.layers, tab1)
                    if self.prefill_page_native:
                        row = np.full(
                            (1, bj), self.tokenizer.pad_id, np.int32
                        )
                        _, cache = paged_prefill_fn(self.model, bj)(
                            self.params, cache, jnp.asarray(row),
                            jnp.int32(0), one_key, zt1,
                            jnp.asarray(
                                np.asarray([max(bj - 1, 0)], np.int32)
                            ),
                            zk1, op1,
                        )
                    else:
                        cache = paged_scatter_fn()(
                            cache, self.model.init_cache(1, bj),
                            jnp.asarray(tab1), jnp.int32(0),
                        )
                    self.pool.layers = paged_pools_of(cache)
                    self._warmed_scatter.add((bj, npv))
                    shapes += 1
            if self.prefill_interleave:
                # Interleaved long-prompt admission: the cp-wide paged
                # extend chunk at [1, npv] plus the standalone sampler
                # — the two programs an interleaved prefill dispatches.
                cp = self.prompt_buckets[-1]
                for total in tiers:
                    npv = -(-total // self.pool.page)
                    tab1 = np.zeros((1, npv), np.int32)
                    cache = paged_cache_tree(self.pool.layers, tab1)
                    cache, logits = paged_extend_fn(self.model, cp)(
                        self.params, cache,
                        jnp.asarray(np.full(
                            (1, cp), self.tokenizer.pad_id, np.int32
                        )),
                        jnp.int32(0),
                        jnp.asarray(np.asarray([cp - 1], np.int32)),
                        jnp.int32(0), jnp.int32(0),
                    )
                    self.pool.layers = paged_pools_of(cache)
                    sample_fn(self.model)(
                        logits, one_key, zt1, zk1, op1
                    )
                    self._warmed_extend.add((cp, npv))
                    shapes += 1
            return shapes
        for run_bucket in self.prompt_buckets:
            total = min(self.model.max_positions, run_bucket + tier)
            if total - run_bucket < 1:
                continue
            for bsz in batches:
                if bsz * 2 <= batches[-1]:
                    sel = np.concatenate(
                        [np.arange(bsz), np.zeros(bsz)]
                    ).astype(np.int32)
                    _compact_fn()(
                        self.model.init_cache(bsz, total), jnp.asarray(sel)
                    )
                    self._warmed_growth.add((bsz, bsz * 2, total))
                for bj in self.prompt_buckets:
                    # A joiner's bucket must fit below some reachable
                    # decode position: pos ranges over
                    # [run_bucket, total).
                    if bj >= total:
                        continue
                    admit_scatter_fn()(
                        self.model.init_cache(bsz, total), minis[bj],
                        jnp.int32(0), jnp.int32(0),
                    )
                    self._warmed_scatter.add((bj, total, bsz))
                    shapes += 1
        return shapes


def _load_meta_only(path):
    """Read just the manifest (no params I/O)."""
    import json
    from pathlib import Path

    from mlapi_tpu.checkpoint.io import CheckpointMeta, _MANIFEST

    manifest = Path(path) / _MANIFEST
    if not manifest.exists():
        raise FileNotFoundError(f"{path} is not a committed checkpoint")
    return CheckpointMeta.from_json(json.loads(manifest.read_text()))
