"""Shared-prefix KV caching for generative serving.

One :class:`PrefixCache` per :class:`TextGenerationEngine`: it owns
the LRU of prefilled prefix KVs, the per-key build events (concurrent
first requests for the SAME prefix share one build; hits on other
prefixes never wait), the cross-batch widened-KV cache, and the
hit/miss/fallback counters ``/metrics`` exports. Device work (prefill,
widen, warm grids) runs through the engine's model/params — the cache
holds a back-reference for those, but every piece of PREFIX STATE
lives here. Split out of ``engine.py`` (r04 VERDICT "Next" #7).

Host-tier integration (r13, ``serving/kv_tier.py``): when the engine
carries a :class:`~mlapi_tpu.serving.kv_tier.KVTier`
(``--kv-tier-bytes``), this cache is BOTH tier seams' client — an
entry falling off this dict's own LRU spills its contiguous KV to the
tier before being discarded, and a device-cache miss consults the
tier before paying the cold prefill: :meth:`entry` rebuilds the
``_PrefixEntry`` from the spilled blob (``device_put``, zero prefill
FLOPs — ``builds`` does not move), and :meth:`paged_entry` restores
evicted pool page sets straight from the blob
(``PagePool.restore_entry``) instead of re-adopting. Every restore is
byte-identical to the state it replaces, so greedy streams cannot
tell {evict → restore} from {never evicted}. Tier absent (the
default): every path below is bit-for-bit the r12 behavior.
"""

from __future__ import annotations

import collections
import threading

import jax
import jax.numpy as jnp
import numpy as np

from mlapi_tpu.serving.requests import _PrefixEntry
from mlapi_tpu.utils.logging import get_logger

_log = get_logger("serving.prefix")


class PrefixCache:
    def __init__(self, engine, max_entries: int = 8):
        self.eng = engine
        self.max_entries = max_entries
        # text -> _PrefixEntry, LRU-bounded (each entry holds a
        # [1, prefix_bucket] KV pytree on device).
        self._entries: collections.OrderedDict = collections.OrderedDict()
        # Guards the LRU against concurrent _encode calls (submit runs
        # encoding in executor threads): without it, N first requests
        # naming the same prefix would each pay the cold prefill.
        # ``_building`` holds per-key in-flight build events so cold
        # builds never block hits on OTHER prefixes.
        self._lock = threading.Lock()
        self._building: dict = {}
        # Cross-batch prefix sharing: right-aligned [1, P] widenings
        # of registered prefix KVs (keyed (fp, P), LRU-bounded) and
        # the region widths P whose stacked program grid is warmed
        # (strict mode groups cross-prefix only within this set).
        self._wide: collections.OrderedDict = collections.OrderedDict()
        self.mix_warmed: set = set()
        # Stats (read by /metrics via the engine's properties).
        # ``builds`` counts actual cold prefills (``_build`` runs) —
        # the counter the zero-prefill-FLOPs restore claim is pinned
        # against: a tier restore increments ``misses`` (it missed the
        # device cache) but never ``builds``.
        self.hits = 0
        self.misses = 0
        self.fallbacks = 0
        self.builds = 0

    def __len__(self) -> int:
        return len(self._entries)

    def count_fallback(self) -> None:
        """A prefix request served through the plain path instead of
        the KV path (empty suffix, unstackable entry): counted under
        the lock — callers run on concurrent encode executor threads
        (mlapi-lint MLA002, caught r19)."""
        with self._lock:
            self.fallbacks += 1

    def entry(self, text: str) -> _PrefixEntry:
        """Return (computing on first use, LRU-cached after) the KV
        cache of a shared prompt prefix. The forward pass over the
        prefix runs ONCE; every request naming the same prefix reuses
        its keys/values straight from device memory — the
        time-to-first-token win prefix caching exists for. The first
        request with a new prefix pays the prefill (and possibly XLA
        compiles for its shapes) on its own latency. Concurrent first
        requests for the SAME prefix share one build (per-key event);
        hits on other prefixes never wait behind a build — the lock
        guards only the dict, not the device work."""
        while True:
            with self._lock:
                entry = self._entries.get(text)
                if entry is not None:
                    self._entries.move_to_end(text)
                    self.hits += 1
                    return entry
                ev = self._building.get(text)
                if ev is None:
                    ev = threading.Event()
                    self._building[text] = ev
                    break
            # Someone else is building this prefix: wait, then re-check
            # (their failure leaves the entry absent — we retry as the
            # builder and surface the same error to this caller).
            ev.wait(timeout=600.0)
        try:
            # Device-cache miss: the host tier first (a spilled blob
            # rebuilds the entry with ZERO prefill FLOPs), then the
            # cold prefill. Either way it is a miss — the tier's own
            # restore_hits counter carries the savings story.
            entry = self._restore(text)
            if entry is None:
                entry = self._build(text)
            tier = getattr(self.eng, "kv_tier", None)
            if tier is not None:
                # The rebuild metadata a later spill must attach (the
                # pool spill seam knows page ids, not buckets).
                tier.note_meta(
                    text, bucket=entry.bucket, lo=entry.lo,
                    used=entry.used,
                )
            evicted = []
            with self._lock:
                self._entries[text] = entry
                self.misses += 1
                while len(self._entries) > self.max_entries:
                    old, old_e = self._entries.popitem(last=False)  # LRU
                    evicted.append(old_e)
                    if self.eng.pool is not None:
                        # The evicted entry's pool pages lose their
                        # entry hold (rows still sharing them keep
                        # theirs; the pages free when the last row
                        # departs). No pool-side spill here — the
                        # entry's contiguous KV below is the same
                        # bytes, readable from THIS thread.
                        self.eng.pool.drop_entry(old)
            for old_e in evicted:
                # Outside the lock: the spill device_gets a [1, P]
                # cache — other prefixes' lookups must not wait on it.
                # A concurrent re-arrival of the evicted prefix in
                # this window just pays a cold build (correct, merely
                # unlucky).
                self._spill_entry(old_e)
            return entry
        finally:
            with self._lock:
                self._building.pop(text, None)
            ev.set()

    def _plan(self, text: str):
        """Tokenize and bucket one prefix EXACTLY as a cold build
        would — ``(ids, bucket, lo)``. Shared between :meth:`_build`
        and tier-restore validation, so a spilled blob only ever
        applies when its geometry matches what a build would produce
        today (tokenizer/bucket/page-size drift turns the blob into a
        miss, never a wrong cache)."""
        eng = self.eng
        ids = eng.tokenizer.token_ids(text)
        if not ids:
            raise ValueError("prefix tokenizes to nothing")
        # The prefix must leave room for at least the smallest suffix
        # bucket plus one generated token.
        cap = eng.model.max_positions - eng.prompt_buckets[0] - 1
        if len(ids) > cap:
            raise ValueError(
                f"prefix is {len(ids)} tokens; at most {cap} fit "
                f"the model window (max_positions="
                f"{eng.model.max_positions})"
            )
        bucket = min(max(eng._bucket(len(ids)), len(ids)), cap)
        if eng.pool is not None:
            # Page-align the prefix bucket AT STORE TIME: region ends
            # and right-alignment shifts between entries then land on
            # page boundaries, so stacked (cross-prefix) groups share
            # ref-counted pages instead of copying widened stacks
            # (BatchRun._prefill_paged_prefix), and a same-fp batch's
            # suffix starts on a fresh tile (no COW). A few pad slots
            # per entry buy pointer sharing per batch. When the model
            # window can't fit the aligned bucket the entry stays
            # unaligned — groups containing it fall back to copy
            # semantics, counted in ``eng.kv_prefix_copy_fallback``.
            aligned = -(-bucket // eng.pool.page) * eng.pool.page
            if aligned <= cap:
                bucket = aligned
        return ids, bucket, bucket - len(ids)

    def _build(self, text: str) -> _PrefixEntry:
        """Tokenize, validate, prefill, and (strict mode) warm one
        prefix — device work, run OUTSIDE the registry lock."""
        from mlapi_tpu.models.gpt import prefill_fn

        eng = self.eng
        ids, bucket, _ = self._plan(text)
        with self._lock:
            # Concurrent builds of DIFFERENT prefixes run on separate
            # encode executor threads; a bare += here lost updates on
            # the counter the zero-prefill-FLOPs claims are pinned
            # against (mlapi-lint MLA002, caught r19).
            self.builds += 1
        row = np.full((1, bucket), eng.tokenizer.pad_id, np.int32)
        row[0, -len(ids):] = ids
        lo = bucket - len(ids)
        _, kv = prefill_fn(eng.model, bucket)(
            eng.params, jnp.asarray(row),
            jnp.asarray(eng._key_data(0)[None]),
            jnp.asarray(np.zeros((1,), np.float32)),
            jnp.asarray(np.asarray([lo], np.int32)),
            jnp.asarray(np.zeros((1,), np.int32)),
            jnp.asarray(np.ones((1,), np.float32)),
        )
        entry = _PrefixEntry(text, kv, bucket, lo, len(ids))
        if eng._strict_admit:
            self.warm_shapes(entry)
        return entry

    # -- host-tier + peer seams (kv_tier.py / kv_peer.py; no-ops when
    # absent) -----------------------------------------------------------
    def _restore(self, text: str) -> _PrefixEntry | None:
        """Warm-source consult on a device-cache miss, cheapest
        first: the LOCAL tier blob, then (``--kv-peer-fetch``) a
        router-hinted WARM PEER's blob over the wire — either way the
        entry rebuilds by ``device_put`` of stored-format bytes, ZERO
        prefill FLOPs (``builds`` does not move) — or ``None`` to
        fall back to the cold build. Runs on the encode executor
        thread, so the peer hop never touches the dispatch thread
        (the cold prefill it replaces blocks this same thread for
        longer). Failure discipline: geometry or metadata drift DROPS
        a tier blob / counts a peer MISS (the bytes can never apply
        here) and goes cold; a transient failure (including injected
        ``tier_restore``/``peer_fetch`` raises) counts its seam's
        failure counter and goes cold — either way the caller's path
        is the normal prefill, never a half-built entry. A peer blob
        that DOES apply is additionally staged into the local tier
        (``KVTier.stage``) so the paged formation restores its pool
        pages through the existing alloc-first
        ``PagePool.restore_entry`` path on the dispatch thread."""
        from mlapi_tpu.serving import faults

        tier = getattr(self.eng, "kv_tier", None)
        peer = getattr(self.eng, "kv_peer", None)
        if tier is not None:
            # absent -> counted restore miss (the local-tier story)
            blob = tier.lookup(text)
            if blob is not None:
                entry = None
                try:
                    faults.fire("tier_restore")
                    entry = self._entry_from_blob(text, blob)
                except Exception as e:
                    tier.count_restore_failure()
                    _log.debug(
                        "tier entry restore failed (%s); cold prefill", e
                    )
                if entry is not None:
                    if self.eng._strict_admit:
                        self.warm_shapes(entry)
                    tier.count_restore(blob)
                    return entry
                # Drifted (blob dropped) or transiently failed: the
                # peer below may still beat the cold prefill.
        if peer is None:
            return None
        blob = peer.fetch(text)  # miss/failure counted inside
        if blob is None:
            return None
        try:
            entry = self._entry_from_blob(text, blob, drop=False)
        except Exception as e:
            peer.count_miss()
            _log.debug(
                "peer blob failed to apply (%s); cold prefill", e
            )
            return None
        if entry is None:
            # Geometry drift vs what a local build would produce
            # today (different bucket/page config than the peer):
            # dropped as a miss, exactly like a corrupt wire body —
            # and the hint goes too: config drift is persistent, so
            # every future miss would re-transfer a full blob that
            # provably can never apply (the same pure-loss argument
            # as the 404 hint drop).
            peer.count_miss()
            peer.drop_hint(text)
            return None
        peer.count_applied(blob.nbytes)
        if tier is not None:
            try:
                # Stage locally: the dispatch-thread paged_entry path
                # then finds the blob in the LOCAL tier and restores
                # pool pages alloc-first via restore_entry — no wire
                # I/O on the dispatch thread, pages conserved on any
                # failure. Best-effort: a staging failure only costs
                # the adopt-path copy at formation.
                tier.stage(
                    text, blob.payload, blob.page,
                    bucket=blob.bucket, lo=blob.lo, used=blob.used,
                )
            except Exception as e:
                _log.debug("peer blob staging failed (%s)", e)
        if self.eng._strict_admit:
            self.warm_shapes(entry)
        return entry

    def _entry_from_blob(self, text: str, blob,
                         drop: bool = True) -> _PrefixEntry | None:
        """Blob payload ``{layer: {leaf: [n, page, ...]}}`` → the
        ``[1, bucket]`` contiguous entry KV, byte-identical to the one
        the original build produced (the spill gathered exactly those
        bytes; slots past ``bucket`` in the final page are spill-time
        pool residue, sliced off here and never read). Returns
        ``None`` when the blob's recorded geometry does not match
        what a cold build would produce today — after dropping the
        blob from the tier when ``drop`` (peer-fetched blobs pass
        ``drop=False``: there is nothing local to drop, and the
        caller counts the miss on the peer's own counters)."""
        if blob.bucket is None:
            # Spilled before any entry registration recorded its
            # metadata: pool-page restore still works (paged_entry),
            # but an entry cannot be rebuilt. Keep the blob.
            return None
        ids, bucket, lo = self._plan(text)
        if (
            blob.bucket != bucket
            or blob.lo != lo
            or blob.used != len(ids)
            or blob.num_pages * blob.page < bucket
        ):
            if drop:
                self.eng.kv_tier.drop(text)
            _log.debug(
                "%s blob geometry drifted for %r; cold prefill",
                "tier" if drop else "peer", text,
            )
            return None
        kv = {
            ln: {
                name: jnp.asarray(
                    np.ascontiguousarray(
                        a.reshape(
                            (1, a.shape[0] * a.shape[1]) + a.shape[2:]
                        )[:, :bucket]
                    )
                )
                for name, a in layer.items()
            }
            for ln, layer in blob.payload.items()
        }
        return _PrefixEntry(text, kv, bucket, lo, len(ids))

    def _spill_entry(self, entry: _PrefixEntry) -> None:
        """Spill a dict-LRU-evicted entry's contiguous KV to the host
        tier before it is garbage-collected — the second spill seam
        (the first is ``PagePool._spill_and_release``). Reads the
        entry's own ``[1, P]`` KV, never pool arrays, so it is safe
        from registration threads; page-shaped to the pool's page size
        (paged engines) so the blob is interchangeable with pool
        spills, or one bucket-wide page (contiguous engines). A
        failure here (including an injected ``tier_spill`` raise)
        falls back to the pre-tier discard, counted."""
        tier = getattr(self.eng, "kv_tier", None)
        if tier is None:
            return
        from mlapi_tpu.serving.kv_tier import payload_from_contiguous

        page = (
            self.eng.pool.page if self.eng.pool is not None
            else entry.bucket
        )
        try:
            tier.note_meta(
                entry.fp, bucket=entry.bucket, lo=entry.lo,
                used=entry.used,
            )
            payload = payload_from_contiguous(entry.kv, page)
            tier.spill(entry.fp, payload, page)
        except Exception as e:
            tier.count_spill_failure()
            _log.debug("tier entry spill failed (%s); evicting cold", e)

    def warm_shapes(self, entry: _PrefixEntry) -> None:
        """Registration-time warm of the prefix-batch programs: on a
        tunnel attach (strict mode) the first BATCH using a new prefix
        must not stall the device stream on an XLA compile, so the
        (suffix bucket × small batch) grid at the default cache tier
        compiles as part of building the entry — the registration
        request already owns that latency."""
        from mlapi_tpu.models.gpt import decode_chunk_fn, prefix_prefill_fn

        eng = self.eng
        if eng.pool is not None:
            # Paged engines run the suffix through paged_extend_fn
            # against pool-shaped caches; warming those needs live
            # pool state this registration thread must not touch (the
            # decode thread owns the pool arrays). Strict-mode paged
            # prefix batches therefore compile their suffix program on
            # first formation, and cross-prefix mixing stays
            # same-prefix (mix_warmed never populates) — noted in
            # DESIGN §15.
            return
        batches = [1]
        while batches[-1] < eng.max_batch:
            batches.append(batches[-1] * 2)

        p = entry.bucket
        for sb in eng.prompt_buckets:
            if p + sb + 1 > eng.model.max_positions:
                continue  # no room for such suffixes behind this prefix
            total = eng._cache_len(p + sb, eng.default_max_new_tokens)
            for bsz in batches:
                suffix = np.full(
                    (bsz, sb), eng.tokenizer.pad_id, np.int32
                )
                hole = jnp.asarray(np.full((bsz,), sb - 1, np.int32))
                keys = jnp.asarray(
                    np.stack([eng._key_data(0)] * bsz)
                )
                zt = jnp.asarray(np.zeros((bsz,), np.float32))
                zk = jnp.asarray(np.zeros((bsz,), np.int32))
                op = jnp.asarray(np.ones((bsz,), np.float32))
                _, cache = prefix_prefill_fn(eng.model, sb, total)(
                    eng.params, entry.kv, jnp.asarray(suffix),
                    hole, jnp.int32(entry.lo), keys, zt, zk, op,
                )
                # Cross-prefix (stacked) variants: per-row KV stack +
                # lo vector, and the vector-lo decode-chunk program —
                # these are keyed on SHAPES only, so warming them once
                # per region width covers every combination of
                # registered prefixes whose group max is this bucket.
                # bsz == 1 is a mixed batch compacted to one row: the
                # scalar-path cache with the vector-lo decode.
                lo_vec = jnp.asarray(np.full((bsz,), entry.lo, np.int32))
                if bsz > 1:
                    kv_stack = jax.tree.map(
                        lambda a: jnp.broadcast_to(
                            a, (bsz,) + a.shape[1:]
                        ),
                        entry.kv,
                    )
                    _, cache = prefix_prefill_fn(eng.model, sb, total)(
                        eng.params, kv_stack, jnp.asarray(suffix),
                        hole, lo_vec, keys, zt, zk, op,
                    )
                decode_chunk_fn(eng.model, eng.chunk)(
                    eng.params, cache,
                    jnp.asarray(np.zeros((bsz,), np.int32)),
                    jnp.int32(p + sb), hole, zt, keys,
                    jnp.asarray(np.ones((bsz,), np.int32)), zk, op,
                    jnp.int32(p), lo_vec,
                )
        with self._lock:
            # Registration threads warm concurrently; the formation
            # path reads membership from the dispatch thread
            # (mlapi-lint MLA002, caught r19).
            self.mix_warmed.add(p)

    def paged_entry(self, fp, kv, holds: int):
        """Pool-page residency for a prefix entry (paged engines):
        return ``(pages, need_adopt)`` — the shared page ids with
        ``holds`` row references ALREADY taken (atomically with the
        lookup/registration, so a concurrent entry eviction can never
        free the set between lookup and use), plus whether the
        entry's contiguous ``[1, P]`` KV still has to be scattered
        into them (first use; once per entry LIFETIME). HOST-ONLY on
        purpose: the caller performs the adopt scatter after ALL of
        the batch's page allocation has succeeded, so a
        :class:`PagePoolExhausted` can never fire after a donating
        device call has already consumed the pool arrays. After
        adoption, every batch row naming this prefix just points its
        page table here (ref-counted; the contiguous path
        re-broadcast the prefix KV into every row of every batch).
        Under pool pressure the page set may have been evicted
        (``PagePool._spill_and_release``); with a host tier attached
        the eviction SPILLED those pages, so the miss first tries
        ``PagePool.restore_entry`` — a ``device_put`` of the blob back
        into fresh pages, byte-identical to the re-adopt it replaces —
        and only then falls back to the adopt scatter. A
        :class:`~mlapi_tpu.serving.paged_pool.PagePoolExhausted`
        during restore propagates loudly (restore allocates FIRST, so
        nothing is half-installed; the adopt path would need the same
        pages and fail the same way); any other restore failure
        (including an injected ``tier_restore`` raise) is counted and
        falls back to the adopt, pages conserved."""
        import jax

        pool = self.eng.pool
        pages = pool.entry_pages(fp, holds=holds)
        if pages is not None:
            return pages, False
        tier = getattr(self.eng, "kv_tier", None)
        if tier is not None:
            from mlapi_tpu.serving.paged_pool import (
                PagePoolExhausted, PagePoolPoisoned,
            )

            blob = tier.lookup(fp)  # absent -> counted restore miss
            if blob is not None:
                try:
                    pages = pool.restore_entry(fp, blob, holds=holds)
                except (PagePoolExhausted, PagePoolPoisoned):
                    # Exhaustion: the adopt fallback needs the same
                    # pages and would fail the same way. Poisoning:
                    # the fallback would read consumed buffers. Both
                    # propagate loudly, nothing half-installed.
                    raise
                except Exception as e:
                    tier.count_restore_failure()
                    _log.debug(
                        "tier page restore failed (%s); re-adopting", e
                    )
                    pages = None
                if pages is not None:
                    return pages, False
        p = jax.tree.leaves(kv)[0].shape[1]
        pages = pool.alloc(-(-p // pool.page))
        pool.put_entry_pages(fp, pages, holds=holds)
        return pages, True

    @staticmethod
    def widen(kv, own_len: int, p_len: int):
        """``[1, own_len]`` prefix-KV pytree → ``[1, p_len]``,
        right-aligned (real content ends at the common region end)."""
        if own_len == p_len:
            return kv
        off = p_len - own_len
        return jax.tree.map(
            lambda a: jax.lax.dynamic_update_slice(
                jnp.zeros((1, p_len) + a.shape[2:], a.dtype), a,
                (0, off) + (0,) * (a.ndim - 2),
            ),
            kv,
        )

    def stacked(self, reqs, p_len: int, b_pad: int):
        """Per-row ``[b_pad, p_len]`` prefix-KV stack for a
        cross-prefix batch: each live row's own prefix right-aligned
        to the common region end (cached per (fp, p_len) — the widen
        runs once per prefix per width, not once per batch); dummy
        rows are zeros, fully masked by ``lo == p_len``."""
        rows = []
        for r in reqs:
            key = (r.prefix_fp, p_len)
            wide = self._wide.get(key)
            if wide is None:
                wide = self.widen(r.prefix_kv, r.prefix_len, p_len)
                self._wide[key] = wide
                while len(self._wide) > 2 * self.max_entries:
                    self._wide.popitem(last=False)
            else:
                self._wide.move_to_end(key)
            rows.append(wide)
        if b_pad > len(reqs):
            zero = jax.tree.map(jnp.zeros_like, rows[0])
            rows.extend([zero] * (b_pad - len(reqs)))
        return jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *rows
        )
